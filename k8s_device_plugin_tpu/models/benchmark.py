"""In-pod benchmark runner — what the example/benchmark pods execute.

≙ the reference's benchmark container command (k8s-pod-example-gpu.yaml runs
convnet-benchmarks' `benchmark_alexnet.py` inside the pod).  Here the pod runs
    python -m k8s_device_plugin_tpu.models.benchmark --model resnet50 ...
against whatever chips the plugin allocated: the injected TPU_* env makes
libtpu expose exactly those chips, and the mesh axes are laid over them in
TPU_VISIBLE_CHIPS order so collectives ride the granted ICI block.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .alexnet import AlexNet
from .bert import Bert, BertConfig
from .data import synthetic_image_batch, synthetic_lm_batch, synthetic_token_batch
from .resnet import ResNet50
from .train import create_train_state, make_train_step
from ..parallel import distributed
from ..parallel.distributed import make_slice_mesh
from ..parallel.sharding import shard_train_step
from ..utils import tracing


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _positive_int(text: str) -> int:
    value = int(text)
    if value < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {value}")
    return value


def _sync(x):
    """Force completion via a device→host copy of ``x``.

    `jax.block_until_ready` is NOT a sync point on the tunneled TPU backend
    (axon): round-2 measured 20 ResNet-50 steps "completing" in 0.03s —
    5× the chip's physical bf16 peak — because the client-side buffer
    reports ready while the remote computation is still queued.  Copying
    bytes back cannot lie; every timed region here ends in a device_get.
    """
    return jax.device_get(x)


def measure_two_point(run_small, run_big, n_delta: int, n_big: int):
    """Shared two-point timer for every benchmark in the repo.

    ``run_small``/``run_big`` are no-arg callables that execute one
    pre-compiled short/long program AND sync on its result (device_get).
    The short program runs twice: the spread between its two timings is a
    direct estimate of the dispatch/sync jitter, and the long-short delta
    only counts as signal when it clears 3x that jitter — keying the noise
    floor to measured jitter, not to a fraction of total runtime, so a
    small delta on top of a large constant part (e.g. long-prompt decode)
    is still trusted when the clock is steady.

    Returns (seconds attributed to the ``n_delta`` extra units, fell_back):
    on fallback the estimate is the long run scaled by ``n_delta/n_big`` —
    single-point, honest about including constant overhead.
    """
    times = []
    for fn in (run_small, run_small, run_big):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    t_small = min(times[0], times[1])
    jitter = abs(times[1] - times[0])
    dt = times[2] - t_small
    if dt <= 3 * jitter or dt <= 0:
        return times[2] * n_delta / max(n_big, 1), True
    return dt, False


def chained_tps(fn, short: int, full: int, label: str = "decode") -> float:
    """Units/sec from two whole-program lengths (the generate-bench shape).

    ``fn(n)`` must execute an n-unit program AND sync its result
    (device_get).  Warms/compiles both lengths, then two-point times them
    so constant prefill/dispatch cost cancels; on a below-noise-floor
    delta it logs and returns the scaled single-point estimate
    (overhead-diluted, but honest about it).  Shared by every bench that
    times a cached generate program (bench.py secondaries) so the
    warm/measure/fallback dance isn't re-cloned per bench.
    """
    fn(short)
    fn(full)
    dt, fell_back = measure_two_point(
        lambda: fn(short), lambda: fn(full), full - short, full
    )
    if fell_back:
        log(f"  ({label} delta below noise floor; single-point)")
    return (full - short) / dt


def multi_step(step, n: int):
    """Wrap ``step: (state, batch) -> (state, loss)`` into an ``n``-step
    `lax.fori_loop` — n training steps in ONE device dispatch.

    Per-dispatch overhead on a tunneled TPU is ~70-90ms (measured round 2)
    and dispatches do not pipeline across the relay, so a host-side step
    loop times the tunnel, not the chip.  An in-program loop is also simply
    how TPU training loops should be written: one traced program, no host
    round-trips.  `fori_loop` with a carry-only body (no per-step stacked
    outputs) keeps the program's output buffers identical to a single
    step's — the leanest shape for the remote-execution path.
    Returns ``(state, batch) -> (state, last_loss)``; jit at the call site.
    """

    def run(state, batch):
        # First step outside the loop pins the loss's shape/dtype for the
        # carry without guessing what the loss function returns.
        state, loss = step(state, batch)

        def body(_, carry):
            s, _ = carry
            return step(s, batch)

        return jax.lax.fori_loop(0, n - 1, body, (state, loss))

    return run


def timed_steps(step, state, batch, warmup: int, steps: int) -> tuple:
    """Two-point single-dispatch timing harness.

    AOT-compiles loop-of-step at two lengths (``warmup`` and
    ``warmup+steps``) and times one execution of each; the time difference
    covers exactly ``steps`` steps with the constant dispatch+sync overhead
    (tunnel RTT, device_get latency) cancelled out.  ``warmup`` here sizes
    the short program — compilation is excluded by AOT, not by discarded
    runs.  Returns (state, loss, seconds_for_timed_steps); with
    ``small = max(1, warmup)`` the state advances ``3*small + steps`` steps
    (the short program runs twice to estimate timing jitter — see
    measure_two_point).
    """
    small = max(1, warmup)
    big = small + steps
    t0 = time.perf_counter()
    # AOT-compile both lengths up front (no execution): the timed calls
    # below are then first executions of ready executables — symmetric
    # constant overhead for both points, no compile inside the timed
    # region, and only small+big total steps executed (so the final
    # state/loss stay interpretable).
    run_small = jax.jit(multi_step(step, small), donate_argnums=0).lower(
        state, batch
    ).compile()
    run_big = jax.jit(multi_step(step, big), donate_argnums=0).lower(
        state, batch
    ).compile()
    log(f"compile {time.perf_counter() - t0:.1f}s")
    holder = {"state": state, "loss": None}

    def exec_small():
        holder["state"], holder["loss"] = run_small(holder["state"], batch)
        _sync(holder["loss"])

    def exec_big():
        holder["state"], holder["loss"] = run_big(holder["state"], batch)
        _sync(holder["loss"])

    dt, fell_back = measure_two_point(exec_small, exec_big, steps, big)
    if fell_back:
        log("two-point step delta below noise floor; reporting single-point")
    return holder["state"], holder["loss"], dt


def _gpt_config(args):
    from .transformer import GPTConfig

    if args.tiny:
        return GPTConfig.tiny()
    return GPTConfig(
        vocab_size=32000,
        hidden_size=1024,
        num_layers=8,
        num_heads=16,
        num_kv_heads=4,
        intermediate_size=2816,
        max_seq=max(args.seq_len, args.prompt_len + args.decode_tokens),
    )


def build(model_name: str, args, rng):
    if model_name == "alexnet":
        model = AlexNet(num_classes=1000, dtype=jnp.bfloat16)
        batch = synthetic_image_batch(rng, args.batch_size, args.image_size)
        return model, batch, "images", args.batch_size
    if model_name == "resnet50":
        model = ResNet50(
            num_classes=1000, dtype=jnp.bfloat16, stem=args.stem
        )
        batch = synthetic_image_batch(rng, args.batch_size, args.image_size)
        return model, batch, "images", args.batch_size
    if model_name == "vit":
        from .vit import ViT, ViTConfig

        if args.tiny:
            cfg = ViTConfig.tiny()
        else:
            # 256px/patch16 = 256 tokens — 128-aligned, so the encoder takes
            # the fused flash path end to end; --image-size overrides.
            cfg = ViTConfig(image_size=args.image_size if args.image_size != 224 else 256)
        model = ViT(cfg)
        batch = synthetic_image_batch(
            rng, args.batch_size, cfg.image_size, num_classes=cfg.num_classes
        )
        return model, batch, "images", args.batch_size
    if model_name == "bert":
        model = Bert(BertConfig.base())
        batch = synthetic_token_batch(rng, args.batch_size, args.seq_len)
        return model, batch, "input_ids", args.batch_size * args.seq_len
    if model_name == "gpt":
        from .transformer import TransformerLM

        cfg = _gpt_config(args)
        model = TransformerLM(cfg)
        batch = synthetic_lm_batch(rng, args.batch_size, args.seq_len, cfg.vocab_size)
        return model, batch, "input_ids", args.batch_size * args.seq_len
    raise SystemExit(f"unknown model {model_name!r}")


def checkpointed_steps(
    step, state, batch, target_steps: int, ckpt, every: int, warmup: int = 0
):
    """Train from the state's current step up to ``target_steps`` (absolute),
    saving asynchronously every ``every`` steps and once at the end.

    The first ``warmup`` steps run OUTSIDE the timed region (they absorb XLA
    compilation, like timed_steps' warmup) but are still real training steps
    — they advance ``state.step`` and participate in the checkpoint cadence,
    so resume arithmetic stays exact.  The final save is forced so a clean
    exit always leaves the latest step durable; mid-run kills lose at most
    ``every`` steps — the preemption contract the e2e test pins.

    Execution is chunked: the steps between two checkpoint boundaries run
    as ONE compiled scan (see `multi_step`), synced with a device_get only
    where a save needs the post-step state — so checkpoint cadence costs
    one host round-trip per save, not per step.
    Returns (state, last_loss | None, timed_seconds, steps_timed).
    """
    start = int(jax.device_get(state.step))
    warm_until = min(start + warmup, target_steps)
    # Absolute step numbers where the host must intervene: every checkpoint
    # boundary (s % every == 0, matching the reference cadence of saving
    # after step s), the warmup/timed split, and the end.
    bounds = sorted(
        {s for s in range(start + 1, target_steps + 1) if s % every == 0}
        | {warm_until, target_steps}
    )
    bounds = [b for b in bounds if b > start]
    # AOT-compile every distinct chunk length BEFORE any timer runs: a
    # chunk length first reached after warm_until would otherwise compile
    # inside the timed region and dominate dt with compile time.
    compiled: dict[int, object] = {}
    t0 = time.perf_counter()
    for a, b in zip([start] + bounds[:-1], bounds):
        n = b - a
        if n and n not in compiled:
            compiled[n] = jax.jit(multi_step(step, n), donate_argnums=0).lower(
                state, batch
            ).compile()
    if compiled:
        log(f"compile ({len(compiled)} chunk lengths) {time.perf_counter() - t0:.1f}s")

    def run_chunk(state, n):
        return compiled[n](state, batch)

    loss = None
    # warmup == 0 (or a resume landing past warm_until): everything is timed.
    t0 = time.perf_counter() if warm_until <= start < target_steps else None
    dt = 0.0
    cur = start
    for b in bounds:
        state, loss = run_chunk(state, b - cur)
        # Sync before saving so the saved state is the post-step one (and
        # so the timed region below measures execution, not queueing).
        _sync(loss)
        cur = b
        if b % every == 0:
            ckpt.save(state)
            log(f"checkpoint queued at step {b}")
        if b == warm_until and b != target_steps:
            t0 = time.perf_counter()
    if t0 is not None:
        dt = time.perf_counter() - t0
    # Final forced save — but not at a step that's already durable (a resumed
    # run that had nothing left to do would hit orbax's step-exists error).
    if int(jax.device_get(state.step)) != ckpt.latest_step():
        ckpt.save(state, force=True)
    ckpt.wait()
    return state, loss, dt, max(target_steps - warm_until, 0)


def run_decode(args) -> None:
    """Autoregressive decode throughput (tokens/sec) through the KV cache —
    the inference-side companion to the training benchmarks."""
    from .transformer import TransformerLM, greedy_generate, sample_generate

    cfg = _gpt_config(args)
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(
        rng, (args.batch_size, args.prompt_len), 0, cfg.vocab_size
    )
    params = model.init(rng, prompt)["params"]

    if args.temperature is not None:
        sample_rng = jax.random.PRNGKey(1)

        def greedy_generate(cfg, params, prompt, n):  # noqa: F811 — same timing path
            return sample_generate(
                cfg, params, prompt, n,
                rng=sample_rng, temperature=args.temperature, top_k=args.top_k,
            )

    # Two-point timing (see measure_two_point): a 1-new-token generate
    # covers the constant costs (dispatch/sync RTT plus the bulk prefill
    # pass); the full generate adds exactly decode_tokens-1 more decode
    # steps, so the time difference is pure decode and the reported
    # tokens/sec is neither RTT- nor prefill-diluted.  decode_tokens == 1
    # degenerates to single-point over all generated tokens incl. prefill.
    two_point = args.decode_tokens > 1
    full_steps = args.decode_tokens
    t0 = time.perf_counter()
    if two_point:
        _sync(greedy_generate(cfg, params, prompt, 1))
    _sync(greedy_generate(cfg, params, prompt, args.decode_tokens))
    log(f"decode compile+first run {time.perf_counter() - t0:.1f}s")
    with tracing.trace(args.trace_dir):
        if two_point:
            def exec_short():
                _sync(greedy_generate(cfg, params, prompt, 1))

            def exec_full():
                _sync(greedy_generate(cfg, params, prompt, args.decode_tokens))

            dt, fell_back = measure_two_point(
                exec_short, exec_full, args.decode_tokens - 1, full_steps
            )
            if fell_back:
                log("decode delta below noise floor; reporting single-point")
                two_point = False
                dt = dt * full_steps / (args.decode_tokens - 1)
        else:
            t0 = time.perf_counter()
            _sync(greedy_generate(cfg, params, prompt, args.decode_tokens))
            dt = time.perf_counter() - t0
    steps = args.decode_tokens - 1 if two_point else full_steps
    total_tokens = args.batch_size * steps
    print(
        json.dumps(
            {
                "model": "gpt-decode",
                "sampler": "greedy"
                if args.temperature is None
                else f"temperature={args.temperature},top_k={args.top_k}",
                "chips": len(jax.devices()),
                "batch": args.batch_size,
                "prompt_len": args.prompt_len,
                "new_tokens": args.decode_tokens,
                "steps": steps,
                "throughput": round(total_tokens / dt, 2),
                "unit": "decoded tokens/sec (two-point, prefill+overhead excluded)"
                if two_point
                else "generated tokens/sec (incl. prefill cost)",
                "ms_per_token": round(dt / steps * 1e3, 3),
            }
        ),
        flush=True,
    )


def _run_router_phase(args) -> dict | None:
    """ROUTER perf phase: prefix-affinity routing vs a random-placement
    control over the SAME seeded multi-session traffic, against K real
    (tiny) serving replicas behind the router daemon.

    What the row claims and how it is measured:

    - **prefix-hit rate** — KV-tier hits (retained + host arena) summed
      across the replica engines per routed request.  Affinity keeps a
      session's shared prefix on one replica where the tiers revive it;
      random placement scatters it, so each replica keeps re-grafting.
      Engine counters, not router bookkeeping — the benefit is real KV
      work avoided.
    - **TTFT p99** — the router's own client-observed first-token
      histogram (tpu_router_ttft_seconds), warm, measured over the
      identical request sequence both times (same traffic seed).

    The replicas are deliberately tiny (GPTConfig.tiny) so the phase
    costs two small compiles, not two of the headline engines; both
    phases run over the SAME compiled replicas with KV tiers cleared
    in between, affinity first so any residual warmth favors the
    CONTROL.  Returns the JSON `router` block (None when disabled via
    --router-replicas 0)."""
    import dataclasses
    import os as _os
    import sys as _sys
    import threading

    from ..router.server import RouterServer
    from ..utils.metrics import MetricsRegistry
    from .engine import EngineMetrics, ServingEngine
    from .http_server import EngineServer
    from .transformer import GPTConfig, PagedConfig, TransformerLM

    n_replicas = getattr(args, "router_replicas", 2)
    if n_replicas < 2:
        return None
    # The multi-session replay lives with the chaos/sim harness
    # (tests/sim/traffic.py); the bench runs from the repo image, where
    # the repo root may or may not already be importable.
    try:
        from tests.sim.traffic import RouterTraffic
    except ImportError:
        _sys.path.insert(
            0,
            _os.path.dirname(
                _os.path.dirname(
                    _os.path.dirname(_os.path.abspath(__file__))
                )
            ),
        )
        from tests.sim.traffic import RouterTraffic

    page_size = 4
    cfg = dataclasses.replace(GPTConfig.tiny(), max_seq=64)
    paged = PagedConfig(
        page_size=page_size, num_pages=64, max_pages_per_seq=16
    )
    rng = jax.random.PRNGKey(0)
    servers = []
    engines = []
    for i in range(n_replicas):
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(i), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        registry = MetricsRegistry()
        engine = ServingEngine(
            cfg,
            params,
            paged,
            max_slots=4,
            metrics=EngineMetrics(registry),
            kv_retain=True,
            kv_host_cache_mb=16,
        )
        engines.append(engine)
        servers.append(
            EngineServer(
                engine, host="127.0.0.1", port=0, registry=registry
            ).start()
        )

    def _post_replica(port, prompt, max_new):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": max_new}
            ).encode(),
            method="POST",
        )
        urllib.request.urlopen(req, timeout=120).read()

    # Warmup EVERY replica over the (batch, bucket) prefill grid the
    # replay can hit (prefix 16 + suffix <= 4 tokens -> one bucket;
    # concurrent admissions batch up to the client concurrency), so no
    # XLA compile lands inside either measured pass — and neither
    # policy's pass eats a compile the other skipped.
    for server in servers:
        for group in (1, 2, 3, 4):
            threads = [
                threading.Thread(
                    target=_post_replica,
                    args=(server.port, [7 + g] * 18, 6),
                )
                for g in range(group)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    replica_names = [f"127.0.0.1:{s.port}" for s in servers]
    # More sessions than replicas: every session random placement
    # scatters pays a cold prefix graft per EXTRA replica it touches,
    # while affinity pays exactly one per session — the gap the
    # hit-rate columns exist to show.
    sessions, prefix_len, n_requests = 8, 16, 32

    def _kv_hits():
        return sum(e.kv_retained_hits + e.kv_host_hits for e in engines)

    def _measure(mode):
        router = RouterServer(
            replica_names,
            host="127.0.0.1",
            port=0,
            # One prefix block = one KV page of the tiny replicas; four
            # blocks = exactly the shared session prefix.
            prefix_block_tokens=page_size,
            prefix_max_blocks=prefix_len // page_size,
            poll_interval_s=0.2,
            hedge=False,
            policy_mode=mode,
            seed=3,
        ).start()
        traffic = RouterTraffic(
            "127.0.0.1",
            router.port,
            seed=17,
            sessions=sessions,
            prefix_len=prefix_len,
            vocab=cfg.vocab_size,
        )
        # Warm pass (same seed as the measured pass: identical shapes),
        # then clear every KV tier so the measurement starts cold.
        traffic.run(
            n_requests, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        for engine in engines:
            engine.kvcache_clear()
        hits0 = _kv_hits()
        ttft_snap = router.metrics.ttft_seconds.snapshot()
        report = traffic.run(
            n_requests, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        placements = {
            key: router.metrics.placements.value(placement=key)
            for key in ("home", "overflow", "random", "failover")
        }
        out = {
            "prefix_hits": _kv_hits() - hits0,
            "hit_rate": round((_kv_hits() - hits0) / n_requests, 3),
            "ttft_p99_ms": (
                None
                if (
                    q := router.metrics.ttft_seconds.quantile(
                        0.99, since=ttft_snap
                    )
                )
                is None
                else round(q * 1e3, 3)
            ),
            "home_rate": round(
                placements["home"] / max(1, sum(placements.values())), 3
            ),
            "dropped": report.dropped,
            "failovers": int(router.metrics.failovers.value()),
            "retries": int(router.metrics.retries.value()),
        }
        router.stop()
        return out

    # Affinity FIRST: any residual warmth then biases toward the
    # random CONTROL, never for the claim.
    affinity = _measure("affinity")
    random_ctl = _measure("random")
    for server in servers:
        server.stop()
    block = {
        "replicas": n_replicas,
        "requests": n_requests,
        "sessions": sessions,
        "affinity": affinity,
        "random": random_ctl,
    }
    log(
        "perf-ledger row: | ROUTER prefix-affinity (K=%d, %d sessions) | "
        "affinity %.2f KV hits/req, TTFT p99 %s ms (home rate %.2f) vs "
        "random %.2f hits/req, %s ms | - | `benchmark.py --model serving` "
        "| update on bench round |"
        % (
            n_replicas,
            sessions,
            affinity["hit_rate"],
            affinity["ttft_p99_ms"],
            affinity["home_rate"],
            random_ctl["hit_rate"],
            random_ctl["ttft_p99_ms"],
        )
    )
    return block


def _run_fabric_phase(args) -> dict | None:
    """FABRIC perf phase: the fleet-wide content-addressed KV fabric
    (router/fabric.py, ISSUE 18) vs an affinity-only control over the
    SAME seeded traffic in which every session opens with one SHARED
    system prompt.

    What the row claims and how it is measured:

    - **fleet hits/request** — with the fabric on, the shared prefix is
      prefilled ONCE fleet-wide: the first replica to hold it advertises
      a bloom digest, the router's locator stamps it as the handoff
      source on every dial whose target lacks the prefix, and the target
      pulls the pages instead of recomputing them.  Engine KV-tier hits
      (retained + host arena) per request must be strictly ABOVE the
      affinity-only control, where each replica pays its own cold
      prefill of the very same system prompt.  bench_diff screams
      NO-FABRIC-HITS when the cross-peer pull count is zero.
    - **TTFT p99** — the router's client-observed histogram over the
      identical sequence; the pulls must not cost latency (bench_diff
      screams FABRIC-TTFT-REGRESSED past 1.2x the control).

    The fabric pass runs FIRST so residual warmth favors the CONTROL;
    the control pass sleeps the same locator-settle time the fabric
    pass measured, so neither side gets a free warm-up.  Returns the
    JSON ``fabric`` block (None when multi-replica phases are disabled
    via --router-replicas < 2)."""
    import dataclasses
    import os as _os
    import sys as _sys
    import threading
    import time as _time

    from ..router.fabric import FabricConfig
    from ..router.server import RouterServer
    from ..utils.metrics import MetricsRegistry
    from .engine import EngineMetrics, ServingEngine
    from .http_server import EngineServer
    from .transformer import GPTConfig, PagedConfig, TransformerLM

    if getattr(args, "router_replicas", 2) < 2:
        return None
    # Fleet-wide dedup is only interesting past two replicas: with
    # three, affinity alone CANNOT keep the shared prompt hot
    # everywhere, so the control pays the recompute the fabric avoids.
    n_replicas = max(3, getattr(args, "router_replicas", 2))
    try:
        from tests.sim.traffic import RouterTraffic
    except ImportError:
        _sys.path.insert(
            0,
            _os.path.dirname(
                _os.path.dirname(
                    _os.path.dirname(_os.path.abspath(__file__))
                )
            ),
        )
        from tests.sim.traffic import RouterTraffic

    page_size = 4
    cfg = dataclasses.replace(GPTConfig.tiny(), max_seq=64)
    paged = PagedConfig(
        page_size=page_size, num_pages=64, max_pages_per_seq=16
    )
    servers = []
    engines = []
    # IDENTICAL weights on every replica — a real fleet serves one
    # model, and the handoff fingerprint check rightly refuses KV
    # pulled across different params.
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    for i in range(n_replicas):
        registry = MetricsRegistry()
        engine = ServingEngine(
            cfg,
            params,
            paged,
            max_slots=4,
            metrics=EngineMetrics(registry),
            kv_retain=True,
            kv_host_cache_mb=16,
        )
        engines.append(engine)
        servers.append(
            EngineServer(
                engine, host="127.0.0.1", port=0, registry=registry
            ).start()
        )

    def _post_replica(port, prompt, max_new):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": max_new}
            ).encode(),
            method="POST",
        )
        urllib.request.urlopen(req, timeout=120).read()

    # Warmup every replica over the (batch, bucket) grid the replay can
    # hit (shared 16 + unique 16 + suffix <= 4 tokens; admissions batch
    # up to the client concurrency) so no XLA compile lands inside a
    # measured pass.
    for server in servers:
        for group in (1, 2, 3, 4):
            threads = [
                threading.Thread(
                    target=_post_replica,
                    args=(server.port, [7 + g] * 36, 6),
                )
                for g in range(group)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    replica_names = [f"127.0.0.1:{s.port}" for s in servers]
    # Every session shares the same 16-token system prompt but keeps a
    # 16-token unique tail, so affinity homes SESSIONS apart while the
    # fabric dedups the shared HEAD across those homes.
    sessions, prefix_len, shared_len, n_requests = 8, 32, 16, 32

    def _kv_hits():
        return sum(e.kv_retained_hits + e.kv_host_hits for e in engines)

    def _pulls():
        return sum(e.handoff_fetches for e in engines)

    def _measure(use_fabric, settle_s):
        router = RouterServer(
            replica_names,
            host="127.0.0.1",
            port=0,
            prefix_block_tokens=page_size,
            prefix_max_blocks=prefix_len // page_size,
            poll_interval_s=0.2,
            hedge=False,
            policy_mode="affinity",
            seed=3,
            fabric=use_fabric,
            fabric_config=FabricConfig(default_page_size=page_size),
        ).start()
        traffic = RouterTraffic(
            "127.0.0.1",
            router.port,
            seed=17,
            sessions=sessions,
            prefix_len=prefix_len,
            shared_prefix_len=shared_len,
            vocab=cfg.vocab_size,
        )
        # Warm pass (identical shapes), then clear every KV tier so the
        # measurement starts cold on every replica.
        traffic.run(
            n_requests, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        for engine in engines:
            engine.kvcache_clear()
        # Seed ONE owner with the shared system prompt (through the
        # router, so affinity picks the home it would in production),
        # then give the locator time to see the cleared digests and the
        # new owner's advertisement.  The control pass sleeps the SAME
        # measured settle so TTFT is compared apples to apples.
        t0 = _time.monotonic()
        _post_replica(router.port, traffic.prefixes[0][:shared_len], 4)
        if use_fabric:
            # Right after the clear the locator still holds PRE-clear
            # views (every replica nonzero) for up to a poll tick —
            # settled means the refreshed truth: exactly the seed
            # owner advertises, everyone else reads empty.
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline:
                roots = router.fabric.advertised_roots()
                if sum(1 for v in roots.values() if v) == 1:
                    break
                _time.sleep(0.05)
            settle_s = _time.monotonic() - t0
        else:
            _time.sleep(max(0.0, settle_s - (_time.monotonic() - t0)))
        hits0 = _kv_hits()
        pulls0 = _pulls()
        ttft_snap = router.metrics.ttft_seconds.snapshot()
        report = traffic.run(
            n_requests, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        out = {
            "fleet_hits": _kv_hits() - hits0,
            "hit_rate": round((_kv_hits() - hits0) / n_requests, 3),
            "ttft_p99_ms": (
                None
                if (
                    q := router.metrics.ttft_seconds.quantile(
                        0.99, since=ttft_snap
                    )
                )
                is None
                else round(q * 1e3, 3)
            ),
            "cross_peer_pulls": _pulls() - pulls0,
            "dropped": report.dropped,
        }
        router.stop()
        return out, settle_s

    # Fabric FIRST: any residual warmth then biases toward the
    # affinity-only CONTROL, never for the claim.
    fabric_run, settle_s = _measure(True, 0.0)
    control, _ = _measure(False, settle_s)
    for server in servers:
        server.stop()
    block = {
        "replicas": n_replicas,
        "requests": n_requests,
        "sessions": sessions,
        "shared_prefix_len": shared_len,
        "fabric": fabric_run,
        "control": control,
    }
    log(
        "perf-ledger row: | FABRIC fleet KV (K=%d, %d sessions, shared "
        "%d) | fabric %.2f KV hits/req, TTFT p99 %s ms, %d cross-peer "
        "pulls vs control %.2f hits/req, %s ms | - | `benchmark.py "
        "--model serving` | update on bench round |"
        % (
            n_replicas,
            sessions,
            shared_len,
            fabric_run["hit_rate"],
            fabric_run["ttft_p99_ms"],
            fabric_run["cross_peer_pulls"],
            control["hit_rate"],
            control["ttft_p99_ms"],
        )
    )
    return block


def _run_canary_phase(args) -> dict | None:
    """CANARY perf phase: the active correctness plane's overhead and
    detection self-check (router/prober.py, ISSUE 17).

    What the row claims and how it is measured:

    - **overhead** — serving throughput (client-observed tokens/sec
      through the router over the SAME seeded traffic) with the canary
      prober running at an aggressive interval vs with it off, against
      real (tiny) serving replicas.  The prober-ON pass runs FIRST so
      any residual warmth favors the OFF control — the overhead number
      is conservative.  bench_diff screams PROBE-OVERHEAD past 1%.
    - **mismatch_detected / fences** — the detection self-check: after
      the measured passes, the ``engine.readback=corrupt`` failpoint
      (docs/chaos.md) flips one token byte in every readback; the
      prober MUST verdict mismatch within a few sweeps and auto-fence.
      bench_diff screams MISMATCH-MISSED when this flips false — a
      blind detector is the worst possible correctness-plane
      regression, and nothing else would say so.

    Returns the JSON ``canary`` block (None when the router phase is
    disabled via --router-replicas < 2 — same replicas budget)."""
    import dataclasses
    import os as _os
    import sys as _sys
    import threading
    import time as _time

    from ..router.prober import CanaryConfig
    from ..router.server import RouterServer
    from ..utils import failpoints
    from ..utils.metrics import MetricsRegistry
    from .engine import EngineMetrics, ServingEngine
    from .http_server import EngineServer
    from .transformer import GPTConfig, PagedConfig, TransformerLM

    if getattr(args, "router_replicas", 2) < 2:
        return None
    try:
        from tests.sim.traffic import RouterTraffic
    except ImportError:
        _sys.path.insert(
            0,
            _os.path.dirname(
                _os.path.dirname(
                    _os.path.dirname(_os.path.abspath(__file__))
                )
            ),
        )
        from tests.sim.traffic import RouterTraffic

    page_size = 4
    cfg = dataclasses.replace(GPTConfig.tiny(), max_seq=64)
    paged = PagedConfig(
        page_size=page_size, num_pages=64, max_pages_per_seq=16
    )
    servers = []
    for i in range(2):
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(100 + i), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        registry = MetricsRegistry()
        engine = ServingEngine(
            cfg,
            params,
            paged,
            max_slots=4,
            metrics=EngineMetrics(registry),
        )
        servers.append(
            EngineServer(
                engine,
                host="127.0.0.1",
                port=0,
                registry=registry,
                enable_admin=True,  # the prober's auto-fence target
            ).start()
        )

    def _post_replica(port, prompt, max_new):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": max_new}
            ).encode(),
            method="POST",
        )
        urllib.request.urlopen(req, timeout=120).read()

    # Warm every (batch, bucket) shape BOTH the traffic replay and the
    # canary probes can hit, so no XLA compile lands inside either
    # measured pass (the probe prompt is tiny — its bucket too).
    for server in servers:
        for group in (1, 2, 3, 4):
            threads = [
                threading.Thread(
                    target=_post_replica,
                    args=(server.port, [7 + g] * 18, 6),
                )
                for g in range(group)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        _post_replica(server.port, [11, 13, 17, 19], 4)

    replica_names = [f"127.0.0.1:{s.port}" for s in servers]
    canary_cfg = CanaryConfig(
        interval_s=0.25,  # far hotter than production: worst case
        probe_tokens=4,
        prompts=((11, 13, 17, 19),),
        k_mismatch=2,
        fence=True,
    )

    def _measure(canary_on):
        router = RouterServer(
            replica_names,
            host="127.0.0.1",
            port=0,
            prefix_block_tokens=page_size,
            prefix_max_blocks=4,
            poll_interval_s=0.2,
            hedge=False,
            seed=3,
            canary=canary_on,
            canary_config=canary_cfg,
        ).start()
        traffic = RouterTraffic(
            "127.0.0.1",
            router.port,
            seed=23,
            sessions=4,
            prefix_len=16,
            vocab=cfg.vocab_size,
        )
        # Warm pass, then the measured pass over identical shapes.
        traffic.run(8, concurrency=4, suffix_len=(1, 4), max_new=(4, 8))
        report = traffic.run(
            24, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        tps = report.tokens / max(report.duration_s, 1e-9)
        return router, tps, report

    # Prober ON first: residual warmth then favors the OFF control,
    # never the claim.
    router_on, tps_on, report_on = _measure(True)
    probes = sum(
        row["probes"]
        for row in router_on.prober.snapshot()["replicas"].values()
    )

    # Detection self-check on the still-running canary router: corrupt
    # every readback, wait for mismatch -> auto-fence.
    failpoints.arm_spec("engine.readback=corrupt")
    mismatch_detected = False
    fences = 0
    try:
        deadline = _time.monotonic() + 15.0
        while _time.monotonic() < deadline:
            snap = router_on.prober.snapshot()
            fences = snap["fences_fired"]
            if fences >= 1:
                mismatch_detected = True
                break
            _time.sleep(0.1)
    finally:
        failpoints.disarm("engine.readback")
    router_on.stop()
    for server in servers:
        server.unfence()

    router_off, tps_off, report_off = _measure(False)
    router_off.stop()
    for server in servers:
        server.stop()

    overhead = max(0.0, 1.0 - tps_on / tps_off) if tps_off else None
    block = {
        "replicas": 2,
        "interval_s": canary_cfg.interval_s,
        "tokens_per_sec_canary": round(tps_on, 2),
        "tokens_per_sec_control": round(tps_off, 2),
        "overhead": round(overhead, 4) if overhead is not None else None,
        "probes": probes,
        "dropped": report_on.dropped + report_off.dropped,
        "mismatch_detected": mismatch_detected,
        "fences": fences,
    }
    log(
        "perf-ledger row: | CANARY active probing (interval %.2fs) | "
        "overhead %s (%.2f vs %.2f tokens/sec, %d probes); injected "
        "corruption %s (%d fences) | - | `benchmark.py --model serving` "
        "| update on bench round |"
        % (
            canary_cfg.interval_s,
            block["overhead"],
            tps_on,
            tps_off,
            probes,
            "detected+fenced" if mismatch_detected else "MISSED",
            fences,
        )
    )
    return block


def _run_postmortem_phase(args) -> dict | None:
    """POSTMORTEM perf phase: black-box archaeology overhead and the
    capture/classification self-check (router/postmortem.py +
    tools/postmortem.py, ISSUE 20).

    What the row claims and how it is measured:

    - **overhead** — serving throughput (client-observed tokens/sec
      through the router over the SAME seeded traffic) with the fleet
      postmortem collector armed vs off, against real (tiny) serving
      replicas.  The armed pass runs FIRST so residual warmth favors
      the control — the overhead number is conservative.  bench_diff
      screams CAPTURE-OVERHEAD past 1%.
    - **bundle_found / root_cause** — the archaeology self-check: after
      the measured passes, a watchdog-source fence incident is injected
      on one replica; the summary-poll incident cursor must fire
      exactly one fleet bundle, and ``tools/postmortem.py`` must
      classify the ON-DISK bundle ``watchdog_hang``.  bench_diff
      screams CAPTURE-MISSED when no bundle lands and ROOTCAUSE-WRONG
      on a misclassification — a capture plane that misses or
      misattributes incidents is worse than none (operators trust it).

    Returns the JSON ``postmortem`` block (None when the router phase
    is disabled via --router-replicas < 2 — same replicas budget)."""
    import dataclasses
    import importlib.util
    import os as _os
    import shutil as _shutil
    import sys as _sys
    import tempfile as _tempfile
    import threading
    import time as _time

    from ..router.server import RouterServer
    from ..utils.metrics import MetricsRegistry
    from .engine import EngineMetrics, ServingEngine
    from .http_server import EngineServer
    from .transformer import GPTConfig, PagedConfig, TransformerLM

    if getattr(args, "router_replicas", 2) < 2:
        return None
    repo_root = _os.path.dirname(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )
    try:
        from tests.sim.traffic import RouterTraffic
    except ImportError:
        _sys.path.insert(0, repo_root)
        from tests.sim.traffic import RouterTraffic

    spec = importlib.util.spec_from_file_location(
        "postmortem_tool", _os.path.join(repo_root, "tools", "postmortem.py")
    )
    pm_tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm_tool)

    page_size = 4
    cfg = dataclasses.replace(GPTConfig.tiny(), max_seq=64)
    paged = PagedConfig(
        page_size=page_size, num_pages=64, max_pages_per_seq=16
    )
    servers = []
    for i in range(2):
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(200 + i), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        registry = MetricsRegistry()
        engine = ServingEngine(
            cfg,
            params,
            paged,
            max_slots=4,
            metrics=EngineMetrics(registry),
        )
        servers.append(
            EngineServer(
                engine, host="127.0.0.1", port=0, registry=registry
            ).start()
        )

    def _post_replica(port, prompt, max_new):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": max_new}
            ).encode(),
            method="POST",
        )
        urllib.request.urlopen(req, timeout=120).read()

    # Warm every (batch, bucket) shape the traffic replay can hit, so
    # no XLA compile lands inside either measured pass.
    for server in servers:
        for group in (1, 2, 3, 4):
            threads = [
                threading.Thread(
                    target=_post_replica,
                    args=(server.port, [7 + g] * 18, 6),
                )
                for g in range(group)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    replica_names = [f"127.0.0.1:{s.port}" for s in servers]
    dump_dir = _tempfile.mkdtemp(prefix="bench-postmortem-")

    def _measure(postmortem_on):
        router = RouterServer(
            replica_names,
            host="127.0.0.1",
            port=0,
            prefix_block_tokens=page_size,
            prefix_max_blocks=4,
            poll_interval_s=0.2,
            hedge=False,
            seed=3,
            postmortem=postmortem_on,
            postmortem_dir=dump_dir,
        ).start()
        traffic = RouterTraffic(
            "127.0.0.1",
            router.port,
            seed=29,
            sessions=4,
            prefix_len=16,
            vocab=cfg.vocab_size,
        )
        # Warm pass, then the measured pass over identical shapes.
        traffic.run(8, concurrency=4, suffix_len=(1, 4), max_new=(4, 8))
        report = traffic.run(
            24, concurrency=4, suffix_len=(1, 4), max_new=(4, 8)
        )
        tps = report.tokens / max(report.duration_s, 1e-9)
        return router, tps, report

    # Collector ON first: residual warmth then favors the OFF control,
    # never the claim.
    router_on, tps_on, report_on = _measure(True)

    # Archaeology self-check on the still-running armed router: a
    # watchdog-source fence incident on replica 0 (the flight event +
    # discrete incident the real hung-step watchdog emits) must ride
    # the summary-poll cursor into ONE fleet bundle that classifies as
    # watchdog_hang FROM DISK.
    victim = servers[0]
    victim.engine.flight.record(
        "engine.fenced", reason="hung_step", source="watchdog"
    )
    victim.engine.anomaly.report(
        "engine.fenced", reason="hung_step", source="watchdog"
    )
    bundle_found = False
    root_cause = None
    deadline = _time.monotonic() + 20.0
    while _time.monotonic() < deadline:
        if router_on.postmortem.captures >= 1:
            bundle_found = True
            break
        _time.sleep(0.1)
    captures = router_on.postmortem.captures
    if bundle_found:
        bundle_path = router_on.postmortem.last_bundle
        loaded = pm_tool.load_bundle(bundle_path)
        timeline = pm_tool.build_timeline(loaded["components"])
        root_cause = pm_tool.classify(timeline)["root_cause"]
    router_on.stop()

    router_off, tps_off, report_off = _measure(False)
    router_off.stop()
    for server in servers:
        server.stop()
    _shutil.rmtree(dump_dir, ignore_errors=True)

    overhead = max(0.0, 1.0 - tps_on / tps_off) if tps_off else None
    rootcause_ok = root_cause == "watchdog_hang"
    block = {
        "replicas": 2,
        "tokens_per_sec_postmortem": round(tps_on, 2),
        "tokens_per_sec_control": round(tps_off, 2),
        "overhead": round(overhead, 4) if overhead is not None else None,
        "dropped": report_on.dropped + report_off.dropped,
        "captures": captures,
        "bundle_found": bundle_found,
        "root_cause": root_cause,
        "rootcause_ok": rootcause_ok,
    }
    log(
        "perf-ledger row: | POSTMORTEM fleet capture | overhead %s "
        "(%.2f vs %.2f tokens/sec); injected watchdog fence %s "
        "(%d bundles, classified %s) | - | `benchmark.py --model "
        "serving` | update on bench round |"
        % (
            block["overhead"],
            tps_on,
            tps_off,
            "captured" if bundle_found else "MISSED",
            captures,
            root_cause if rootcause_ok else f"WRONG ({root_cause})",
        )
    )
    return block


def _run_autoscale_phase(args) -> dict:
    """AUTOSCALE perf phase: the closed-loop fleet controller
    (controller/reconciler.py — the REAL Reconciler + FleetSimActuator,
    fake clock) vs a static peak-provisioned fleet over the SAME
    deterministic 600-sim-second diurnal + flash-crowd demand trace.

    What the row claims and how it is measured:

    - **replica-minutes** — both fleets' bills over the identical
      trace, from the controller's own accrual ledger (serving AND
      still-warming replicas are billed; the elastic fleet must come
      in STRICTLY under the static fleet sized for the observed peak,
      or the autoscaler is not paying for itself).
    - **TTFT p99 / SLO violations** — a fluid-queue fleet model: one
      global backlog drained at ``cap_rps`` per serving replica, plus
      an M/M/1-flavored in-service wait term so a keeping-up-but-busy
      fleet reports nonzero pressure (utilization separates busy from
      idle without a backlog — without that term the model flaps:
      every drain-to-empty reads as cold, every reap re-hots the
      fleet).  TTFT = base + queue wait; a sim-second above ``slo_ms``
      is a violation, and the controller fleet must log ZERO.

    The demand trace, thresholds, and clock are all deterministic (no
    RNG, no wall time), so the block's numbers are exactly reproducible
    and tools/bench_diff.py can gate on them (REPLICA-MINUTES-REGRESSED
    / AUTOSCALE-SLO-VIOLATED).  Pure host-side Python: no compiles, no
    devices, ~milliseconds of wall clock."""
    import math

    from ..controller import (
        ControllerConfig,
        FleetSimActuator,
        Reconciler,
    )
    from ..router.migration import scale_recommendation

    sim_seconds = 600
    cap_rps = 40.0  # one replica's drain rate
    base_ttft_ms = 60.0
    slo_ms = 2500.0  # TTFT budget: base + queue wait
    hot_wait_s, cold_wait_s = 0.2, 0.02
    warm_lag_s = 3.0  # spawn -> serving (peer-warmed join)

    def demand(t: float) -> float:
        """Diurnal sinusoid (5-minute "day", 15..75 rps) with a flash
        crowd riding the second peak: +80 rps ramping in over 30s,
        holding 60s, ramping out."""
        diurnal = 45.0 + 30.0 * math.sin(
            2 * math.pi * (t - 225.0) / 300.0
        )
        if 300 <= t < 330:
            flash = 80.0 * (t - 300) / 30.0
        elif 330 <= t < 390:
            flash = 80.0
        elif 390 <= t < 420:
            flash = 80.0 * (420 - t) / 30.0
        else:
            flash = 0.0
        return max(0.0, diurnal + flash)

    class _Sim:
        """Deterministic fluid-queue fleet: the actuator seam mutates
        it, the fleet() view is what the controller polls."""

        def __init__(self, n0: int):
            self.n = n0
            self.names = [f"sim-{i}" for i in range(n0)]
            self.counter = n0
            self.warming: list = []  # [ready_at, name]
            self.queue = 0.0
            self.t = 0.0
            self.ttfts_ms: list = []
            self.violations = 0
            self.replica_seconds = 0.0
            self.peak = n0

        # ----- actuator verbs (FleetSimActuator closures) -----------
        def spawn(self, role: str) -> str:
            name = f"sim-{self.counter}"
            self.counter += 1
            self.warming.append([self.t + warm_lag_s, name])
            return name

        def reap(self, name: str) -> None:
            if name in self.names:
                self.names.remove(name)
                self.n -= 1

        # ----- signal model -----------------------------------------
        def wait_s(self, d: float) -> float:
            # rho capped below 1: past saturation the backlog term
            # carries the overload signal (uncapped, the M/M/1 term
            # diverges and reports a 25s wait over an empty queue).
            rho = min(0.98, d / (self.n * cap_rps))
            return (
                self.queue / (self.n * cap_rps)
                + rho / (1.0 - rho) / cap_rps
            )

        # ----- one sim second ---------------------------------------
        def step(self) -> None:
            for entry in [w for w in self.warming if w[0] <= self.t]:
                self.warming.remove(entry)
                self.names.append(entry[1])
                self.n += 1
            d = demand(self.t)
            self.queue = max(0.0, self.queue + d - self.n * cap_rps)
            ttft = base_ttft_ms + self.wait_s(d) * 1000.0
            self.ttfts_ms.append(ttft)
            self.violations += ttft > slo_ms
            self.replica_seconds += self.n + len(self.warming)
            self.peak = max(self.peak, self.n + len(self.warming))
            self.t += 1.0

        # ----- the /debug/fleet shape the controller polls ----------
        def fleet(self) -> dict:
            wait = round(self.wait_s(demand(self.t)), 4)
            per_q = int(self.queue / self.n)
            rows = {
                name: {
                    "role": "unified",
                    "pressure_s": wait,
                    "queue_depth": per_q,
                    "eligible": True,
                    "reachable": True,
                    "draining": False,
                    "fenced": False,
                }
                for name in self.names
            }
            # Warming joiners: visible (and billed) but ineligible, so
            # they neither read as cold headroom nor get reaped.
            for _, name in self.warming:
                rows[name] = {
                    "role": "unified",
                    "pressure_s": 0.0,
                    "queue_depth": 0,
                    "eligible": False,
                    "reachable": True,
                    "draining": False,
                    "fenced": False,
                }
            return {
                "replicas": rows,
                "recommendation": scale_recommendation(
                    rows,
                    hot_wait_s=hot_wait_s,
                    cold_wait_s=cold_wait_s,
                ),
            }

    static_n = max(
        math.ceil(demand(t) / cap_rps) for t in range(sim_seconds)
    )

    sim = _Sim(2)
    actuator = FleetSimActuator(
        spawn_fn=sim.spawn,
        join_fn=lambda name, role: None,  # joins when warm_lag elapses
        drain_fn=lambda name: None,  # cold pool: nothing in flight
        reap_fn=sim.reap,
        warm_fn=lambda name, donor: None,  # lag above IS the transfer
    )
    rc = Reconciler(
        sim.fleet,
        actuator,
        config=ControllerConfig(
            interval_s=2.0,
            sustain_ticks=2,
            cooldown_s=10.0,
            min_replicas=1,
            max_replicas=12,
            hot_wait_s=hot_wait_s,
            cold_wait_s=cold_wait_s,
        ),
        now=lambda: sim.t,
    )
    for s in range(sim_seconds):
        if s % 2 == 0:
            rc.tick()
        sim.step()

    static = _Sim(static_n)
    for _ in range(sim_seconds):
        static.step()

    def _p99(xs: list) -> float:
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * 0.99))]

    ctrl_minutes = round(sim.replica_seconds / 60.0, 2)
    static_minutes = round(static.replica_seconds / 60.0, 2)
    block = {
        "sim_seconds": sim_seconds,
        "slo_ms": slo_ms,
        "controller": {
            "replica_minutes": ctrl_minutes,
            "ttft_p99_ms": round(_p99(sim.ttfts_ms), 1),
            "slo_violations": sim.violations,
            "peak_replicas": sim.peak,
            "scale_ups": rc.scale_ups,
            "scale_downs": rc.scale_downs,
            "role_flips": rc.role_flips,
            "actions": rc.actions_executed,
        },
        "static_peak": {
            "replicas": static_n,
            "replica_minutes": static_minutes,
            "ttft_p99_ms": round(_p99(static.ttfts_ms), 1),
            "slo_violations": static.violations,
        },
        "replica_minutes_saved": (
            round(1.0 - ctrl_minutes / static_minutes, 3)
            if static_minutes
            else None
        ),
    }
    log(
        "perf-ledger row: | AUTOSCALE closed-loop controller (%ds "
        "diurnal+flash sim) | replica-minutes %.1f vs static-peak %.1f "
        "(%.0f%% saved); ttft p99 %.0fms vs %.0fms (slo %.0fms, "
        "violations %d vs %d); %d actions (%d up, %d down) | - | "
        "`benchmark.py --model serving` | update on bench round |"
        % (
            sim_seconds,
            ctrl_minutes,
            static_minutes,
            100.0 * (block["replica_minutes_saved"] or 0.0),
            block["controller"]["ttft_p99_ms"],
            block["static_peak"]["ttft_p99_ms"],
            slo_ms,
            sim.violations,
            static.violations,
            rc.actions_executed,
            rc.scale_ups,
            rc.scale_downs,
        )
    )
    return block


def _run_kernels_phase(args) -> dict | None:
    """KERNELS perf phase: the split-K paged-attention kernel vs the
    engine's gather fallback vs the old single-pass Pallas path, per
    shape x KV format — the per-shape kernel perf ledger that
    tools/bench_diff.py gates regressions against.

    What the row claims and how it is measured:

    - **kernel** — `ops.paged_attention` through its default routing
      (compiled Mosaic split-K on TPU; the vectorized XLA
      implementation of the same split math on CPU — the route the
      engine's decode step actually takes), split degree from the
      per-generation tuning table (ops/tuning.py).
    - **gather** — the engine's fallback math verbatim
      (models/transformer.py: materialize the [max_len] view,
      dequantize it when quantized, masked grouped einsum).
    - **single** — the pre-split-K kernel shape: `num_splits=1` forced
      through the Pallas lane (the interpreter on CPU — exactly what
      the r03–r05 smoke rows measured at 0.06–0.12x of gather; the
      compiled 1-split kernel on TPU).

    Every arm runs the SAME jitted-callable discipline (warm twice,
    min-of-N timed executions, device_get sync), and the quantized
    shapes share the bf16 shape's geometry so the `int8_vs_bf16` field
    is a like-for-like fused-dequant claim.  Returns the JSON `kernels`
    block (None when skipped via `--no-kernel`)."""
    if not getattr(args, "kernel", True):
        return None
    from ..ops import tuning
    from ..ops.paged_attention import paged_attention
    from ..ops.quant import (
        dequantize_kv,
        dequantize_kv4,
        quantize_kv,
        quantize_kv4,
    )

    # (name, batch, heads, kv_heads, head_dim, page_size, pages, fill, fmt)
    # — the CPU smoke set: one moderate GQA shape per format plus a
    # longer MQA context where the split axis has real work.  fill < 1
    # leaves a partial frontier page (the masked-tail case).
    shapes = [
        ("b4_gqa_f32", 4, 8, 4, 64, 16, 8, 0.75, "f32"),
        ("b2_mqa_long_f32", 2, 16, 2, 64, 16, 32, 0.4, "f32"),
        ("b4_gqa_bf16", 4, 8, 4, 64, 16, 8, 0.75, "bf16"),
        ("b4_gqa_int8", 4, 8, 4, 64, 16, 8, 0.75, "int8"),
        ("b4_gqa_int4", 4, 8, 4, 64, 16, 8, 0.75, "int4"),
    ]

    def _time(fn, operands, iters):
        out = fn(*operands)  # compile
        _sync(out)
        _sync(fn(*operands))
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            _sync(fn(*operands))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    def _gather_decode(q, kr, vr, lens, sk=None, sv=None, fmt="f32"):
        # The engine's gather-path math verbatim: gathered [max_len]
        # view (dequantized first when quantized), grouped einsum with
        # the positional mask, f32 softmax.
        batch, heads, head_dim = q.shape
        kv_heads = kr.shape[2]
        group = heads // kv_heads
        if fmt == "int8":
            kr = dequantize_kv(kr, sk, q.dtype)
            vr = dequantize_kv(vr, sv, q.dtype)
        elif fmt == "int4":
            kr = dequantize_kv4(kr, sk, q.dtype)
            vr = dequantize_kv4(vr, sv, q.dtype)
        qg = q.reshape(batch, kv_heads, group, 1, head_dim)
        s = jnp.einsum(
            "bhgqd,bkhd->bhgqk", qg, kr, preferred_element_type=jnp.float32
        ) * (head_dim ** -0.5)
        mask = jnp.arange(kr.shape[1])[None, None, None, None, :] < (
            lens[:, None, None, None, None]
        )
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(vr.dtype)
        out = jnp.einsum("bhgqk,bkhd->bhgqd", p, vr)
        return out.reshape(batch, heads, head_dim)

    generation = tuning.device_generation()
    rows: dict[str, dict] = {}
    for name, batch, heads, kv_heads, head_dim, ps, pages, fill, fmt in shapes:
        dt = jnp.float32 if fmt == "f32" else jnp.bfloat16
        import zlib

        rng = jax.random.PRNGKey(zlib.crc32(name.encode()) % (1 << 31))
        ks = jax.random.split(rng, 4)
        n_pool = batch * pages + 1
        q = jax.random.normal(ks[0], (batch, heads, head_dim), dt)
        pool_k = jax.random.normal(ks[1], (n_pool, ps, kv_heads, head_dim), dt)
        pool_v = jax.random.normal(ks[2], (n_pool, ps, kv_heads, head_dim), dt)
        table = (
            jnp.arange(batch * pages, dtype=jnp.int32).reshape(batch, pages)
            + 1
        )
        max_len = pages * ps
        lens = jnp.asarray(
            [max(1, int(max_len * fill) - 3 * i) for i in range(batch)],
            jnp.int32,
        )
        sk = sv = None
        if fmt == "int8":
            pool_k, sk = quantize_kv(pool_k)
            pool_v, sv = quantize_kv(pool_v)
        elif fmt == "int4":
            pool_k, sk = quantize_kv4(pool_k)
            pool_v, sv = quantize_kv4(pool_v)
        splits = tuning.pick_num_splits(pages, generation)
        quant_kw = {"scale_k": sk, "scale_v": sv} if sk is not None else {}
        kernel_fn = jax.jit(
            lambda q, k, v, t, ln, **kw: paged_attention(q, k, v, t, ln, **kw)
        )
        operands = (q, pool_k, pool_v, table, lens)
        kernel_ms = _time(
            lambda *o: kernel_fn(*o, **quant_kw), operands, iters=7
        )

        def gather_full(q, k, v, t, ln):
            kr = k[t].reshape(batch, max_len, kv_heads, -1)
            vr = v[t].reshape(batch, max_len, kv_heads, -1)
            skr = sk[t].reshape(batch, max_len, kv_heads) if sk is not None else None
            svr = sv[t].reshape(batch, max_len, kv_heads) if sv is not None else None
            return _gather_decode(q, kr, vr, ln, skr, svr, fmt)

        gather_ms = _time(jax.jit(gather_full), operands, iters=7)
        # The old path is SLOW on CPU (the whole point of the row);
        # two timed iterations bound the phase's wall clock.
        single_fn = jax.jit(
            lambda q, k, v, t, ln: paged_attention(
                q, k, v, t, ln, num_splits=1, use_pallas=True, **quant_kw
            )
        )
        try:
            single_ms = _time(single_fn, operands, iters=2)
        except Exception as e:  # pragma: no cover - env without Pallas
            log(f"  kernels: single-pass lane unavailable ({e!r})")
            single_ms = None
        rows[name] = {
            "fmt": fmt,
            "batch": batch,
            "heads": heads,
            "kv_heads": kv_heads,
            "head_dim": head_dim,
            "page_size": ps,
            "pages": pages,
            "splits": splits,
            "kernel_ms": round(kernel_ms, 4),
            "gather_ms": round(gather_ms, 4),
            "single_ms": round(single_ms, 4) if single_ms else None,
            "kernel_vs_gather": round(gather_ms / kernel_ms, 3),
            "single_vs_gather": (
                round(gather_ms / single_ms, 3) if single_ms else None
            ),
        }
        log(
            "  kernels %-16s %-5s S=%d kernel %.3fms gather %.3fms "
            "single %sms -> %.2fx gather"
            % (
                name, fmt, splits, kernel_ms, gather_ms,
                f"{single_ms:.3f}" if single_ms else "-",
                gather_ms / kernel_ms,
            )
        )
    min_ratio = min(r["kernel_vs_gather"] for r in rows.values())
    int8_vs_bf16 = None
    if "b4_gqa_int8" in rows and "b4_gqa_bf16" in rows:
        int8_vs_bf16 = round(
            rows["b4_gqa_bf16"]["kernel_ms"] / rows["b4_gqa_int8"]["kernel_ms"],
            3,
        )
    block = {
        "generation": generation,
        "shapes": rows,
        "min_kernel_vs_gather": min_ratio,
        "int8_vs_bf16": int8_vs_bf16,
    }
    log(
        "perf-ledger row: | KERNELS split-K paged attention (%d shapes) | "
        "kernel vs gather min %.2fx (int8 vs bf16 %sx; splits from "
        "%s row) | - | `benchmark.py --model serving --kernel` | update "
        "on bench round |"
        % (len(rows), min_ratio, int8_vs_bf16, generation)
    )
    return block


def _run_overload_phase(eng, args, baseline_tps: float) -> dict:
    """OVERLOAD perf phase: a 2x sustained overload storm with mixed
    priorities through the SAME compiled engine, with the overload
    controller installed the way the serving CLI default installs it.

    What the row claims and how it is measured:

    - **hi-pri TTFT p99** — per-request submit→first-token wall time of
      the high-priority class, measured unloaded (requests run alone)
      then during the storm.  Priority admission is supposed to keep
      the two within 1.2x: high-priority work jumps the queue while
      normal/low absorb the wait.
    - **goodput ratio** — in-deadline completed tokens over all emitted
      tokens (the controller's own ledger): the fraction of chip work
      clients could actually use.
    - **sheds** — deadline-doomed low-priority requests must shed
      (expired) instead of occupying slots; ``pool_exact`` pins that
      sheds returned every page (free pool back to allocatable).

    The storm sizes itself from the measured decode throughput: total
    demanded tokens ≈ 2x what the engine can serve inside the low-pri
    deadline, so low-priority deadline-carrying requests genuinely
    cannot all fit — the shed path runs for real, not by injection."""
    from .engine_overload import OverloadConfig, OverloadController

    eng.overload = OverloadController(
        eng.max_slots,
        # Submit-side load shedding is disabled (huge wait factor) so
        # the phase's shed ledger isolates the DEADLINE path — the
        # storm's shape (which low-pri requests expire) stays a
        # function of measured drain, not of the drain-rate estimate
        # the previous phases happened to leave behind.
        OverloadConfig(target_queue_wait_s=0.25, shed_wait_factor=1e9),
        metrics=eng.metrics,
        flight=eng.flight,
    )
    n_new = args.decode_tokens
    prompt = lambda i: [  # noqa: E731 — same shape as the main jobs
        (13 * i + j) % eng.cfg.vocab_size for j in range(args.prompt_len)
    ]
    # Warm the admission-burst batch shapes a mixed-priority storm can
    # hit (2-wide and 3-wide groups pad to 2/4; 1 and slots-wide are
    # already warm from the main serving warmup).
    eng.run([(prompt(90 + i), 2) for i in range(2)])
    eng.run([(prompt(94 + i), 2) for i in range(3)])

    def _ttft_p99(reqs):
        ttfts = sorted(
            r.first_token_at - r.submitted_at
            for r in reqs
            if r.first_token_at
        )
        if not ttfts:
            return None
        return ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]

    # Unloaded baseline: high-priority requests with the engine to
    # themselves.
    unloaded = []
    for i in range(4):
        unloaded += eng.run([(prompt(i), n_new)], priority=0)
    hi_unloaded = _ttft_p99(unloaded)

    # The storm: slots high + 2*slots normal + 2*slots low, all at
    # once — a queue several times deeper than the engine.  Low-pri
    # requests carry a deadline sized to HALF the storm's expected
    # drain time: since priority admission serves them last, the tail
    # genuinely cannot finish in time and must shed.
    n_hi = eng.max_slots
    n_norm = 2 * eng.max_slots
    n_low = 2 * eng.max_slots
    est_drain_s = ((n_hi + n_norm + n_low) * n_new) / max(baseline_tps, 1.0)
    low_deadline_s = max(est_drain_s / 2, 0.05)
    goodput0 = eng.overload.goodput_tokens
    raw0 = eng.overload.raw_tokens
    sheds0 = eng.overload.sheds_total
    storm: list = []
    hi_reqs = []
    for i in range(n_norm):
        storm.append(
            eng.submit(prompt(10 + i), n_new, priority=1, tenant="norm")
        )
    for i in range(n_low):
        storm.append(
            eng.submit(
                prompt(30 + i), n_new, priority=2, tenant="low",
                deadline_s=low_deadline_s,
            )
        )
    for i in range(n_hi):
        req = eng.submit(prompt(50 + i), n_new, priority=0, tenant="hi")
        storm.append(req)
        hi_reqs.append(req)
    t0 = time.perf_counter()
    guard = 0
    while not all(r.done for r in storm):
        eng.step()
        guard += 1
        if guard > 200_000:
            raise RuntimeError("overload storm failed to drain")
    storm_s = time.perf_counter() - t0
    hi_storm = _ttft_p99(hi_reqs)
    sheds = eng.overload.sheds_total - sheds0
    goodput = eng.overload.goodput_tokens - goodput0
    raw = eng.overload.raw_tokens - raw0
    pool_exact = (
        len(eng.free_pages) == eng.paged.num_pages - 1
        and all(s is None for s in eng.slots)
    )
    ratio = (hi_storm / hi_unloaded) if hi_unloaded and hi_storm else None
    block = {
        "storm_requests": len(storm),
        "storm_seconds": round(storm_s, 2),
        "low_deadline_s": round(low_deadline_s, 3),
        "hi_ttft_p99_unloaded_ms": (
            round(hi_unloaded * 1e3, 3) if hi_unloaded else None
        ),
        "hi_ttft_p99_storm_ms": (
            round(hi_storm * 1e3, 3) if hi_storm else None
        ),
        "hi_ttft_p99_ratio": round(ratio, 3) if ratio else None,
        "goodput_tokens": goodput,
        "raw_tokens": raw,
        "goodput_ratio": round(goodput / raw, 3) if raw else None,
        "sheds": sheds,
        "sheds_by_kind": dict(eng.overload.shed_counts),
        "limit_final": round(eng.overload.limit, 2),
        "pool_exact": pool_exact,
    }
    log(
        "perf-ledger row: | OVERLOAD control (b%d, %d-req storm) | "
        "hi-pri TTFT p99 %s -> %s ms (%sx), goodput %s, %d sheds, pool "
        "exact %s | - | `benchmark.py --model serving` | update on bench "
        "round |"
        % (
            eng.max_slots,
            len(storm),
            block["hi_ttft_p99_unloaded_ms"],
            block["hi_ttft_p99_storm_ms"],
            block["hi_ttft_p99_ratio"],
            block["goodput_ratio"],
            sheds,
            pool_exact,
        )
    )
    eng.overload = None  # leave the engine the way the next phase expects
    return block


def _run_slo_phase(eng, args) -> dict:
    """SLO perf phase: what the SLI/usage accounting seam costs on the
    SAME compiled engine (utils/slo.py; ISSUE 16).

    The same jobs decode with the SLO plane detached, then attached (a
    host-side toggle like the trace phase — no new compiles); the
    per-token cost difference is the measured accounting overhead.
    tools/bench_diff.py screams SLO-OVERHEAD past 1%.  The block also
    self-checks the alert pipeline: a synthetic burn injected into the
    SAME tracker must fire the fast-burn page rule (bench_diff screams
    BURN-ALERT-MISSED if it ever doesn't)."""
    from ..utils.slo import SLOTracker, UsageMeter

    prompt = lambda i: [  # noqa: E731 — same shape as the main jobs
        (13 * i + j) % eng.cfg.vocab_size for j in range(args.prompt_len)
    ]
    jobs = [
        (prompt(120 + i), args.decode_tokens)
        for i in range(2 * eng.max_slots)
    ]
    eng.slo = None
    eng.usage = None
    t0 = time.perf_counter()
    off_done = eng.run(jobs)
    off_dt = time.perf_counter() - t0
    off_tokens = sum(len(r.tokens) for r in off_done)
    eng.slo = SLOTracker()
    eng.usage = UsageMeter()
    t0 = time.perf_counter()
    on_done = eng.run(jobs)
    on_dt = time.perf_counter() - t0
    on_tokens = sum(len(r.tokens) for r in on_done)
    off_tps = off_tokens / off_dt if off_dt else 0.0
    on_tps = on_tokens / on_dt if on_dt else 0.0
    overhead = (off_tps / on_tps) - 1.0 if on_tps else 0.0
    verdicts = sum(pair[1] for pair in eng.slo.totals().values())
    tenants_metered = eng.usage.snapshot()["tracked_tenants"]
    # Alert-pipeline self-check on the live tracker: a synthetic
    # sustained burn (50% bad availability, budget 0.001) must fire the
    # fast-burn page rule on the next evaluation.
    eng.slo.record("availability", True, n=50)
    eng.slo.record("availability", False, n=50)
    burn_alert_fired = any(
        t["state"] == "fired" and t["rule"] == "fast_burn"
        for t in eng.slo.evaluate()
    )
    eng.slo = None  # leave the engine the way the next phase expects
    eng.usage = None
    block = {
        "overhead": round(overhead, 4),
        "off_tokens_per_sec": round(off_tps, 2),
        "on_tokens_per_sec": round(on_tps, 2),
        "sli_verdicts": verdicts,
        "tenants_metered": tenants_metered,
        "burn_alert_fired": burn_alert_fired,
    }
    log(
        "perf-ledger row: | SLO accounting (b%d) | slo off %.2f → on "
        "%.2f tokens/sec (overhead %+.2f%%; %d verdicts, burn alert "
        "fired %s) | - | `benchmark.py --model serving` | update on "
        "bench round |"
        % (
            eng.max_slots,
            off_tps,
            on_tps,
            overhead * 100.0,
            verdicts,
            burn_alert_fired,
        )
    )
    return block


def _run_restart_phase(eng, args) -> dict:
    """RESTART perf phase: cold vs warm post-restart TTFT through the
    crash-safe KV-arena snapshot (models/engine_snapshot.py).

    What the row claims and how it is measured:

    - A session set sharing a full-page prompt prefix runs once to warm
      the tiers, then the arena persists to disk (the fence/drain/
      SIGTERM save).  The "restart" is modeled on the SAME compiled
      engine — ``kvcache_clear()`` is exactly the serving state a
      process death loses, while the XLA programs stand in for the
      restarted pod's persistent compilation cache
      (--compilation-cache-dir); the genuinely-fresh-process path is
      scored by the warm-restart chaos scenario.
    - **cold** restart: tiers cleared, no snapshot — every session
      re-prefills its prefix; per-request TTFT from the request's own
      submit/first-token stamps (requests run serially so TTFT is
      prefill, not queue wait).
    - **warm** restart: tiers cleared, snapshot REHYDRATED — prefix
      pages restore host->device instead of recomputing; same sessions,
      same stamps.  The restore scatter shape is compiled during the
      warmup pass so neither measured pass eats a compile.
    """
    import tempfile

    from .engine_snapshot import load_arena_snapshot, save_arena_snapshot

    page = eng.paged.page_size
    plen = args.prompt_len
    pl = (plen // page) * page  # the shareable FULL-page prefix
    if pl < page:
        return {"skipped": f"prompt_len {plen} < one page ({page})"}
    prefix = [(17 + j) % eng.cfg.vocab_size for j in range(pl)]
    sessions = [
        prefix + [(70 + 3 * s + j) % eng.cfg.vocab_size
                  for j in range(plen - pl)]
        for s in range(4)
    ]
    n_new = args.decode_tokens

    def _ttfts(reqs):
        return sorted(
            r.first_token_at - r.submitted_at
            for r in reqs
            if r.first_token_at
        )

    def _q(sorted_vals, q):
        if not sorted_vals:
            return None
        return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]

    # Warmup: populate the tiers, force the offload path, and compile
    # the restore scatter (one restore round) before anything is timed.
    eng.kvcache_clear()
    for s in sessions:
        eng.run([(s, n_new)])
    with eng._lock:
        eng._kv_reclaim(len(eng._kv_retained))
    eng.run([(sessions[0], n_new)])  # restore-path compile
    snapdir = tempfile.mkdtemp(prefix="tpu-kv-restart-")
    path = f"{snapdir}/kv_arena.snapshot"
    saved = save_arena_snapshot(eng, path, trigger="bench")
    if not saved.get("ok"):
        return {"skipped": f"snapshot save failed: {saved.get('reason')}"}

    # COLD restart: serving state gone, nothing rehydrated.
    eng.kvcache_clear()
    hits0 = eng.kv_host_hits
    cold_reqs = [eng.run([(s, n_new)])[0] for s in sessions]
    cold_hits = eng.kv_host_hits - hits0
    cold = _ttfts(cold_reqs)

    # WARM restart: same death, snapshot rehydrated first.
    eng.kvcache_clear()
    loaded = load_arena_snapshot(eng, path)
    hits0, restores0 = eng.kv_host_hits, eng.kv_restores
    warm_reqs = [eng.run([(s, n_new)])[0] for s in sessions]
    warm_hits = eng.kv_host_hits - hits0
    restored_pages = eng.kv_restores - restores0
    warm = _ttfts(warm_reqs)
    eng.kvcache_clear()

    cold_p99, warm_p99 = _q(cold, 0.99), _q(warm, 0.99)
    block = {
        "sessions": len(sessions),
        "prefix_tokens": pl,
        "snapshot_bytes": saved["bytes"],
        "snapshot_entries": saved["entries"],
        "entries_loaded": loaded.get("restored", 0),
        "cold": {
            "ttft_p50_ms": round(_q(cold, 0.5) * 1e3, 3),
            "ttft_p99_ms": round(cold_p99 * 1e3, 3),
            "prefix_hits": cold_hits,
        },
        "warm": {
            "ttft_p50_ms": round(_q(warm, 0.5) * 1e3, 3),
            "ttft_p99_ms": round(warm_p99 * 1e3, 3),
            "prefix_hits": warm_hits,
            "restored_pages": restored_pages,
        },
        "warm_speedup": round(cold_p99 / warm_p99, 3) if warm_p99 else None,
    }
    log(
        "perf-ledger row: | RESTART warm vs cold (b%d, %d sessions) | "
        "post-restart TTFT p99 cold %.3f → warm %.3f ms (%.3fx; %d pages "
        "restored, %d arena entries, snapshot %d B) | - | `benchmark.py "
        "--model serving` | update on bench round |"
        % (
            eng.max_slots,
            len(sessions),
            block["cold"]["ttft_p99_ms"],
            block["warm"]["ttft_p99_ms"],
            block["warm_speedup"] or 0.0,
            restored_pages,
            loaded.get("restored", 0),
            saved["bytes"],
        )
    )
    return block


def _run_elastic_phase(eng, args) -> dict:
    """ELASTIC perf phase: cold-join vs peer-warmed-join TTFT p99 over
    shared-prefix sessions (ISSUE 14 — elastic fleet scale-up).

    What the row claims and how it is measured:

    - The "donor" is the SAME compiled engine after serving a
      shared-prefix session set: its warm state is serialized through
      ``engine_snapshot.encode_snapshot`` — byte-for-byte the stream a
      real donor's ``GET /debug/snapshot`` sends a joining replica.
    - A **cold join** is modeled by clearing every KV tier (exactly
      what a fresh replica lacks) and serving the same sessions: every
      prefix re-prefills.  Per-request TTFT from the request's own
      submit/first-token stamps, requests serial so TTFT is prefill.
    - A **peer-warmed join** clears the same tiers, then rehydrates the
      donor's wire bytes through the same parse+verify+admit path
      ``fetch_peer_snapshot`` uses (minus the socket; the socket path
      itself is pinned in tier-1 and scored under chaos) — prefix
      pages restore host→device instead of recomputing.  The restore
      scatter compiles during the warmup pass so neither measured join
      eats a compile.

    The acceptance bar the diurnal-burst sim scores (warmed joiner's
    first-minute TTFT p99 within ~1.2x of warm peers) shows up here as
    ``warmed_speedup`` — a value below 1 means peer warm-up made the
    join SLOWER than cold and the ledger row screams NO-WARMUP.
    """
    import io

    from . import engine_snapshot as snap_mod

    page = eng.paged.page_size
    plen = args.prompt_len
    pl = (plen // page) * page  # the shareable FULL-page prefix
    if pl < page:
        return {"skipped": f"prompt_len {plen} < one page ({page})"}
    prefix = [(23 + j) % eng.cfg.vocab_size for j in range(pl)]
    sessions = [
        prefix + [(90 + 5 * s + j) % eng.cfg.vocab_size
                  for j in range(plen - pl)]
        for s in range(4)
    ]
    n_new = args.decode_tokens

    def _ttfts(reqs):
        return sorted(
            r.first_token_at - r.submitted_at
            for r in reqs
            if r.first_token_at
        )

    def _q(sorted_vals, q):
        if not sorted_vals:
            return None
        return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]

    # Donor warmup: serve the sessions, spill the retained tier into
    # the host arena (pool pressure's path), and compile the restore
    # scatter before anything is timed.
    eng.kvcache_clear()
    for s in sessions:
        eng.run([(s, n_new)])
    with eng._lock:
        eng._kv_reclaim(len(eng._kv_retained))
    eng.run([(sessions[0], n_new)])  # restore-path compile

    # The donor's wire stream: exactly what GET /debug/snapshot sends.
    with eng._lock:
        layout = snap_mod.snapshot_layout(eng)
        fingerprint = snap_mod.params_fingerprint(eng.params)
        entries = snap_mod.collect_entries(eng)
    wire = b"".join(snap_mod.encode_snapshot(layout, fingerprint, entries))

    # COLD join (the control): a fresh replica with no donor.
    eng.kvcache_clear()
    hits0 = eng.kv_host_hits
    cold_reqs = [eng.run([(s, n_new)])[0] for s in sessions]
    cold_hits = eng.kv_host_hits - hits0
    cold = _ttfts(cold_reqs)

    # PEER-WARMED join: same fresh replica, donor stream rehydrated
    # through the fetch path's parse+verify+admit before first traffic.
    eng.kvcache_clear()
    _, parsed = snap_mod._parse_snapshot(
        io.BytesIO(wire), layout, fingerprint
    )
    restored_entries = snap_mod._admit_entries(eng, parsed)
    hits0, restores0 = eng.kv_host_hits, eng.kv_restores
    warm_reqs = [eng.run([(s, n_new)])[0] for s in sessions]
    warm_hits = eng.kv_host_hits - hits0
    restored_pages = eng.kv_restores - restores0
    warm = _ttfts(warm_reqs)
    eng.kvcache_clear()

    cold_p99, warm_p99 = _q(cold, 0.99), _q(warm, 0.99)
    block = {
        "sessions": len(sessions),
        "prefix_tokens": pl,
        "wire_bytes": len(wire),
        "entries": len(entries),
        "entries_restored": restored_entries,
        "cold_join": {
            "ttft_p50_ms": round(_q(cold, 0.5) * 1e3, 3),
            "ttft_p99_ms": round(cold_p99 * 1e3, 3),
            "prefix_hits": cold_hits,
        },
        "warmed_join": {
            "ttft_p50_ms": round(_q(warm, 0.5) * 1e3, 3),
            "ttft_p99_ms": round(warm_p99 * 1e3, 3),
            "prefix_hits": warm_hits,
            "restored_pages": restored_pages,
        },
        "warmed_speedup": (
            round(cold_p99 / warm_p99, 3) if warm_p99 else None
        ),
    }
    log(
        "perf-ledger row: | ELASTIC cold vs peer-warmed join (b%d, %d "
        "sessions) | join TTFT p99 cold %.3f → warmed %.3f ms (%.3fx; "
        "%d entries / %d pages restored over %d wire bytes) | - | "
        "`benchmark.py --model serving` | update on bench round |"
        % (
            eng.max_slots,
            len(sessions),
            block["cold_join"]["ttft_p99_ms"],
            block["warmed_join"]["ttft_p99_ms"],
            block["warmed_speedup"] or 0.0,
            restored_entries,
            restored_pages,
            len(wire),
        )
    )
    return block


def _run_disagg_phase(eng, args) -> dict:
    """DISAGG perf phase: decode ITL p99 flat-vs-growing as long-prompt
    prefill load scales (ISSUE 15 — disaggregated prefill/decode).

    What the row claims and how it is measured:

    - **Unloaded baseline**: chatty decode requests alone on the main
      (unified) bench engine; ITL p99 read from the same engine
      histogram operators scrape.
    - **Unified control**: the same chatty traffic while a long-prompt
      request is injected every K steps — the injected prefill chunks
      run on the SAME step loop, so chatty ITL inflates (the problem
      disaggregation removes).
    - **Disagg**: a fresh decode-ROLE engine serves the chatty traffic;
      the long prompts' prefill runs on the unified engine standing in
      as the prefill pool, their finished pages cross through the REAL
      wire encoding (encode_preamble/encode_entry → the snapshot
      verifier → the arena), and the decode engine admits each long
      request by restoring pages and skipping the covered chunks.  The
      injection rate is DOUBLED vs the control — the acceptance bar is
      decode ITL p99 within ~1.2x of unloaded while prefill load
      doubles, with the unified control regressing.
    - **Oracle**: one injected long request's tokens on the decode
      engine must be bit-identical to the unified engine's (greedy —
      the handoff acceptance pin, at serving scale).
    """
    import io

    from . import engine_handoff as handoff_mod
    from . import engine_snapshot as snap_mod
    from .engine import EngineMetrics, ServingEngine

    from ..utils.metrics import MetricsRegistry

    page = eng.paged.page_size
    long_new = 4
    # Long prompts fill the paged window minus their tiny decode budget
    # — the longest prefill this engine can be asked for.
    long_len = ((eng.paged.max_len - long_new - 2) // page) * page
    if long_len < 2 * page or long_len <= args.prompt_len:
        return {
            "skipped": f"max_len {eng.paged.max_len} leaves no room for a "
            "long prompt"
        }
    chatty_prompts = [
        [(13 * i + j) % eng.cfg.vocab_size for j in range(args.prompt_len)]
        for i in range(max(2, args.slots - 1))
    ]
    long_prompts = [
        [(17 * i + 29 + j) % eng.cfg.vocab_size for j in range(long_len)]
        for i in range(8)
    ]
    interval = 24  # steps between injected long prompts (control rate)
    chatty_new = max(args.decode_tokens, 6 * interval // len(chatty_prompts))

    def _measure(engine, inject=None):
        """(itl_p99_s, injected request handles) for one traffic run.

        ITL is measured as per-STEP wall time: every active chatty slot
        emits exactly one token per step, so the step wall clock IS
        that token's inter-token gap — same quantity the
        tpu_engine_itl_seconds histogram aggregates, without its bucket
        quantization (a 1.2x acceptance bar needs exact quantiles)."""
        gaps: list[float] = []
        reqs = [engine.submit(p, chatty_new) for p in chatty_prompts]
        injected = []
        steps = 0
        while any(not r.done for r in reqs):
            t0 = time.perf_counter()
            engine.step()
            gaps.append(time.perf_counter() - t0)
            steps += 1
            if inject is not None:
                got = inject(steps)
                if got is not None:
                    injected.append(got)
        # Drain injected stragglers outside the measured window's
        # bookkeeping (their decode rides the same loop either way).
        guard = 0
        while any(not r.done for r in injected):
            engine.step()
            guard += 1
            if guard > 50_000:
                raise RuntimeError("disagg phase failed to drain")
        ordered = sorted(gaps)
        p99 = ordered[min(int(0.99 * len(ordered)), len(ordered) - 1)]
        return p99, injected

    # The unified engine stands in for BOTH the control and the prefill
    # pool; chunked prefill on both sides so the comparison is the
    # architecture, not the chunking.
    prev_chunk = eng._prefill_chunk
    eng._prefill_chunk = page * 2

    def _warm_mixed(engine, pre_admit=None):
        """Untimed warmup replicating the measured traffic SHAPE: the
        long admission lands in the same slot, with the same occupied
        chatty slots, as it will during measurement — so slot-indexed
        scatters and the long-bucket chunk programs compile here, not
        inside a measured p99."""
        reqs = [engine.submit(p, 8) for p in chatty_prompts]
        long_req = None
        steps = 0
        while any(not r.done for r in reqs) or (
            long_req is not None and not long_req.done
        ):
            engine.step()
            steps += 1
            if steps == 2:
                if pre_admit is not None:
                    pre_admit()
                long_req = engine.submit(long_prompts[0], long_new)
        engine.kvcache_clear()

    eng.kvcache_clear()
    try:
        # Warmup (untimed): the long-bucket chunk program + one full
        # mixed-slot round.
        _warm_mixed(eng)

        # --- Unloaded baseline ------------------------------------------
        itl_unloaded, _ = _measure(eng)

        # --- Unified control: long prefills share the decode loop -------
        def inject_unified(step, _next=[0]):
            if step % interval or _next[0] >= len(long_prompts) // 2:
                return None
            prompt = long_prompts[_next[0]]
            _next[0] += 1
            return eng.submit(prompt, long_new)

        itl_unified, _ = _measure(eng, inject_unified)

        # --- Disagg: decode-role engine + wire-transferred prefixes -----
        import dataclasses as _dc

        dec = ServingEngine(
            _dc.replace(eng.cfg, paged=None),
            eng.params,
            eng.paged,
            max_slots=eng.max_slots,
            metrics=EngineMetrics(MetricsRegistry()),
            prefill_chunk=page * 2,
            kv_retain=True,
            kv_host_cache_mb=64,
            role="decode",
        )
        # The prefill pool's output, as wire bytes (the donor ran the
        # long prefills above and retains their pages; entries re-read
        # through the resident path are the bytes /v1/prefill streams).
        eng.kvcache_clear()
        with eng._lock:
            layout = snap_mod.snapshot_layout(eng)
            fingerprint = snap_mod.params_fingerprint(eng.params)
        wires = []
        oracle_tokens = []
        for prompt in long_prompts:
            # The donor run doubles as the LOCAL-PREFILL ORACLE: greedy
            # tokens for the same prompt, same compiled programs.  The
            # wire then comes from a REAL prefill probe (the tap path
            # /v1/prefill serves), entries + shipped logits.
            oracle_tokens.append(list(eng.run([(prompt, long_new)])[0].tokens))
            tap = eng.handoff_begin(prompt, None)
            entries = []
            try:
                for _ in range(10_000):
                    eng.step()
                    while True:
                        e = tap.pop(0.0)
                        if e is None:
                            break
                        entries.append(e)
                    if tap.req.done and tap.pushed <= len(entries):
                        break
            finally:
                eng.handoff_end(tap)
            wires.append(
                snap_mod.encode_preamble(layout, fingerprint, len(entries))
                + b"".join(
                    snap_mod.encode_entry(layout, k, r) for k, r in entries
                )
                + (
                    handoff_mod.encode_logits_section(tap.logits)
                    if tap.logits is not None
                    else b""
                )
            )
            eng.kvcache_clear()

        def _admit_wire(idx):
            buf = io.BytesIO(wires[idx])
            _, parsed = snap_mod._parse_snapshot(buf, layout, fingerprint)
            admitted = snap_mod._admit_entries(dec, parsed)
            logits = handoff_mod.read_logits_section(buf)
            if logits is not None:
                with dec._lock:
                    dec._kv_arena.put(
                        ("logits", -1, tuple(long_prompts[idx])),
                        {"logits": logits},
                        logits.nbytes,
                    )
            return admitted
        # Warmup the decode engine: the same mixed shape, with the long
        # admission arriving as a HANDOFF (restore scatter + seeded
        # tail chunk + mixed-slot graft all compile here).
        dec.run([(chatty_prompts[0], 2)])

        _warm_mixed(dec, pre_admit=lambda: _admit_wire(0))
        assert dec.handoff_skipped_tokens > 0, (
            "disagg warmup never skipped covered prefill"
        )

        handoff_entries = 0

        def inject_disagg(step, _next=[0]):
            # DOUBLE the control's prefill load: every interval/2 steps.
            nonlocal handoff_entries
            if step % (interval // 2) or _next[0] >= len(long_prompts) // 2:
                return None
            idx = _next[0]
            _next[0] += 1
            handoff_entries += _admit_wire(idx)
            return dec.submit(long_prompts[idx], long_new)

        itl_disagg, disagg_long = _measure(dec, inject_disagg)
        tokens_match = bool(disagg_long) and [
            list(r.tokens) for r in disagg_long
        ] == oracle_tokens[: len(disagg_long)]
    finally:
        eng._prefill_chunk = prev_chunk
        eng.kvcache_clear()

    def _ms(value):
        return None if value is None else round(value * 1e3, 3)

    unified_ratio = (
        round(itl_unified / itl_unloaded, 3)
        if itl_unified and itl_unloaded
        else None
    )
    disagg_ratio = (
        round(itl_disagg / itl_unloaded, 3)
        if itl_disagg and itl_unloaded
        else None
    )
    block = {
        "prefill_jobs": len(long_prompts) // 2,
        "long_prompt_tokens": long_len,
        "itl_p99_unloaded_ms": _ms(itl_unloaded),
        "unified": {
            "itl_p99_loaded_ms": _ms(itl_unified),
            "ratio": unified_ratio,
        },
        "disagg": {
            "itl_p99_loaded_ms": _ms(itl_disagg),
            "ratio": disagg_ratio,
            "handoff_entries": handoff_entries,
            "skipped_prefill_tokens": dec.handoff_skipped_tokens,
            "tokens_match": tokens_match,
        },
    }
    log(
        "perf-ledger row: | DISAGG prefill/decode split (b%d, %d-token "
        "prefills) | decode ITL p99 %.3f ms unloaded → unified %.3f "
        "(%.2fx) vs disagg %.3f ms at 2x prefill load (%.2fx; %d entries "
        "shipped, %d prefill tokens skipped, tokens %s) | - | "
        "`benchmark.py --model serving` | update on bench round |"
        % (
            eng.max_slots,
            long_len,
            block["itl_p99_unloaded_ms"] or 0.0,
            block["unified"]["itl_p99_loaded_ms"] or 0.0,
            unified_ratio or 0.0,
            block["disagg"]["itl_p99_loaded_ms"] or 0.0,
            disagg_ratio or 0.0,
            handoff_entries,
            dec.handoff_skipped_tokens,
            "bit-identical" if tokens_match else "DIVERGED",
        )
    )
    return block


def run_serving(args) -> None:
    """Continuous-batching serving benchmark through the SAME telemetry
    operators scrape: the TTFT/ITL percentiles in the JSON line are read
    back from the EngineMetrics histograms on the registry (PromQL-style
    bucket interpolation, utils/metrics.py Histogram.quantile), not from
    a parallel stopwatch path — so BENCH rounds and Grafana dashboards
    report the same numbers, and a drift between them is itself a bug.

    The decode loop is timed TWICE over the same job set — synchronous
    (overlap off) then overlapped (the serving default) — and the JSON
    line carries both, so every bench round records what keeping one
    step in flight buys on this link (plus the hit/discard counts that
    say whether the pipeline actually stayed primed)."""
    import math

    from ..utils.metrics import MetricsRegistry
    from ..utils.spans import SpanRecorder
    from .engine import EngineMetrics, ServingEngine
    from .transformer import PagedConfig, TransformerLM

    import dataclasses

    page_size = 16
    mpp = math.ceil((args.prompt_len + args.decode_tokens) / page_size)
    paged = PagedConfig(
        page_size,
        num_pages=args.slots * mpp + 1,
        max_pages_per_seq=mpp,
    )
    cfg = dataclasses.replace(_gpt_config(args), max_seq=paged.max_len)
    rng = jax.random.PRNGKey(0)
    params = TransformerLM(cfg).init(
        rng, jnp.zeros((1, 2), jnp.int32)
    )["params"]
    registry = MetricsRegistry()
    spans = SpanRecorder()
    eng = ServingEngine(
        cfg,
        params,
        paged,
        max_slots=args.slots,
        metrics=EngineMetrics(registry),
        spans=spans,
        kv_retain=True,
        kv_host_cache_mb=64,
    )
    jobs = [
        (
            [(11 * i + j) % cfg.vocab_size for j in range(args.prompt_len)],
            args.decode_tokens,
        )
        for i in range(args.requests)
    ]
    # Warmup compiles prefill + step outside the timed region (the repo's
    # measurement-honesty rule); the histogram snapshots below subtract
    # its compile-dominated observations from the reported quantiles.
    # Both pipeline modes run the SAME compiled step program (the overlap
    # knob selects host-side scheduling, not a new program), so one
    # warmup covers the pair — but it must cover BOTH admission-burst
    # prefill shapes the timed runs hit (slots-wide initial burst and
    # the single-request mid-drain refill), or whichever mode runs first
    # would eat the missing compile inside its timed region.
    eng.run([(jobs[0][0], 2)])
    eng.run([(p, 2) for p, _ in jobs[: args.slots]])

    # Synchronous baseline FIRST (any residual warm-cache bias then works
    # against the overlapped number, not for it): same jobs, overlap off.
    eng._overlap_steps = 0
    t0 = time.perf_counter()
    sync_done = eng.run(jobs)
    sync_dt = time.perf_counter() - t0
    sync_tokens = sum(len(r.tokens) for r in sync_done)
    sync_tps = sync_tokens / sync_dt

    ttft_h, itl_h = eng.metrics.ttft_seconds, eng.metrics.itl_seconds
    ttft_snap, itl_snap = ttft_h.snapshot(), itl_h.snapshot()

    def _ms(value):
        return None if value is None else round(value * 1e3, 3)

    # The headline run: overlapped pipeline (the serving default).
    eng._overlap_steps = 1
    hits0, discards0 = eng.overlap_hits, eng.overlap_discards
    t0 = time.perf_counter()
    done = eng.run(jobs)
    dt = time.perf_counter() - t0
    tokens = sum(len(r.tokens) for r in done)
    overlap_tps = tokens / dt
    log(
        "perf-ledger row: | Overlapped decode pipeline (b%d) | sync %.2f "
        "→ overlapped %.2f tokens/sec (%.3fx; hits %d, discards %d) | - "
        "| `benchmark.py --model serving` | update on bench round |"
        % (
            args.slots,
            round(sync_tps, 2),
            round(overlap_tps, 2),
            overlap_tps / sync_tps if sync_tps else 0.0,
            eng.overlap_hits - hits0,
            eng.overlap_discards - discards0,
        )
    )
    # The SAME per-step profile /debug/profile serves on a live server
    # (models/engine_profiler.py): per-phase p50/p99 over the rolling
    # window — so a BENCH round records where the steps' time went, not
    # just how many tokens came out.
    prof = eng.profiler.snapshot()
    phase_p50 = {
        phase: stats["window_p50_ms"]
        for phase, stats in prof["phases"].items()
        if stats["window_steps"]
    }
    log(
        "perf-ledger row: | Serving step phase breakdown (b%d) | step p50 "
        "%.3f ms (%s) | - | `benchmark.py --model serving` ≡ GET "
        "/debug/profile | update on bench round |"
        % (
            args.slots,
            prof["step_ms"]["p50"],
            ", ".join(f"{k} {v:.3f}" for k, v in phase_p50.items()),
        )
    )

    # --- KV cache tiering: repeated-prefix + preemption-churn workload ---
    # Phase 1: one hot prompt with SERIAL (non-overlapping) lifetimes, so
    # live prefix sharing cannot help — only the retained tier can.  Timed
    # with tiering off (every lifetime re-grafts its prompt pages) then on
    # (pages revive off the retained LRU; the graft skips them).
    prefix_job = (jobs[0][0], args.decode_tokens)
    n_rep = min(args.requests, 6)
    eng._kv_retain = False
    eng.kvcache_clear()
    t0 = time.perf_counter()
    rec_tokens = sum(
        len(r.tokens) for _ in range(n_rep) for r in eng.run([prefix_job])
    )
    dt_recompute = time.perf_counter() - t0
    eng._kv_retain = True
    eng.kvcache_clear()
    kv_hits0 = eng.kv_retained_hits + eng.kv_host_hits
    t0 = time.perf_counter()
    res_tokens = sum(
        len(r.tokens) for _ in range(n_rep) for r in eng.run([prefix_job])
    )
    dt_restore = time.perf_counter() - t0
    kv_hits = eng.kv_retained_hits + eng.kv_host_hits - kv_hits0
    rec_tps = rec_tokens / dt_recompute if dt_recompute else 0.0
    res_tps = res_tokens / dt_restore if dt_restore else 0.0
    kv_speedup = res_tps / rec_tps if rec_tps else 0.0

    # Phase 2: preemption churn — optimistic admission over a deliberately
    # tightened pool (free pages parked aside), so growing slots preempt
    # their juniors and the victims resume.  With the tiers on, resumes
    # restore (zero prefill re-run) instead of recomputing.
    eng.kvcache_clear()
    pre0 = eng.preemptions
    resumes0 = eng.kv_resumes_restored
    recomputes0 = eng.kv_resumes_recompute
    eng._optimistic = True
    page_size = eng.paged.page_size
    prompt_pages = (args.prompt_len + 1 + page_size - 1) // page_size
    keep = mpp + 2 * prompt_pages  # oldest can finish; juniors must churn
    with eng._lock:
        parked = [
            eng.free_pages.pop()
            for _ in range(max(0, len(eng.free_pages) - keep))
        ]
    churn_done = eng.run(jobs[: max(2, args.slots)])
    churn_tokens = sum(len(r.tokens) for r in churn_done)
    with eng._lock:
        eng.kvcache_clear()
        for page in parked:
            eng.free_pages.append(page)
    eng._optimistic = False
    churn_preempts = eng.preemptions - pre0
    churn_restored = eng.kv_resumes_restored - resumes0
    churn_recomputed = eng.kv_resumes_recompute - recomputes0
    log(
        "perf-ledger row: | KV cache tiering (b%d) | repeated-prefix "
        "recompute %.2f → restore %.2f tokens/sec (%.3fx; tier hits %d) "
        "| preemption churn: %d preempts, %d restored / %d recomputed "
        "resumes | `benchmark.py --model serving` | update on bench round |"
        % (
            args.slots,
            rec_tps,
            res_tps,
            kv_speedup,
            kv_hits,
            churn_preempts,
            churn_restored,
            churn_recomputed,
        )
    )

    # --- Tracing overhead phase (TRACE row) ------------------------------
    # The always-on span layer must stay ~free: the SAME jobs decode
    # through the SAME compiled programs with the recorder detached,
    # then attached (host-side toggle — no new compiles), and the
    # per-token cost difference is the measured tracing overhead.
    # tools/bench_diff.py screams TRACE-OVERHEAD past 2%.
    trace_spans0 = len(spans.snapshot()) + spans.dropped
    eng.spans = None
    t0 = time.perf_counter()
    off_done = eng.run(jobs)
    trace_off_dt = time.perf_counter() - t0
    off_tokens = sum(len(r.tokens) for r in off_done)
    eng.spans = spans
    t0 = time.perf_counter()
    on_done = eng.run(jobs)
    trace_on_dt = time.perf_counter() - t0
    on_tokens = sum(len(r.tokens) for r in on_done)
    trace_off_tps = off_tokens / trace_off_dt if trace_off_dt else 0.0
    trace_on_tps = on_tokens / trace_on_dt if trace_on_dt else 0.0
    trace_overhead = (
        (trace_off_tps / trace_on_tps) - 1.0 if trace_on_tps else 0.0
    )
    trace_spans_recorded = (
        len(spans.snapshot()) + spans.dropped - trace_spans0
    )
    # Rides GET /debug/profile (and the profile JSON block below): the
    # live answer to "what does tracing cost on this replica".
    eng.profiler.note_trace_overhead(trace_overhead)
    trace_block = {
        "overhead": round(trace_overhead, 4),
        "off_tokens_per_sec": round(trace_off_tps, 2),
        "on_tokens_per_sec": round(trace_on_tps, 2),
        "spans_recorded": trace_spans_recorded,
    }
    log(
        "perf-ledger row: | Tracing overhead (b%d) | spans off %.2f → on "
        "%.2f tokens/sec (overhead %+.2f%%; %d spans) | - | `benchmark.py "
        "--model serving` | update on bench round |"
        % (
            args.slots,
            trace_off_tps,
            trace_on_tps,
            trace_overhead * 100.0,
            trace_spans_recorded,
        )
    )

    # --- Tensor-parallel phase (MULTICHIP row) ---------------------------
    # Same jobs through a tp=N engine built the CLI-facing way
    # (mesh_from_allocation + the sharded ctor), timed against the tp=1
    # overlapped number above.  Gated on a multi-device backend whose
    # head counts the tp degree divides; the row carries decode tokens/s
    # at tp=1 vs tp=N, the scaling efficiency, discards under tp, and
    # whether the token streams stayed bit-identical.
    tp_block = None
    tp_n = len(jax.devices())
    if tp_n > 1 and cfg.kv_heads % tp_n == 0 and cfg.num_heads % tp_n == 0:
        from ..parallel.mesh import mesh_from_allocation

        tp_mesh = mesh_from_allocation(tp_n)
        tp_eng = ServingEngine(
            cfg,
            params,
            paged,
            max_slots=args.slots,
            metrics=EngineMetrics(MetricsRegistry()),
            mesh=tp_mesh,
            kv_retain=True,
            kv_host_cache_mb=64,
        )
        # Warmup MUST cover the tp-sharded step/block shapes: sharded
        # params and pools compile DISTINCT executables, so reusing the
        # single-chip warmup above would charge the tp compiles to the
        # first measured round (the r6 warmup bug).  Same two shapes the
        # tp=1 warmup covers — single prefill and the slots-wide burst.
        tp_eng.run([(jobs[0][0], 2)])
        tp_eng.run([(p, 2) for p, _ in jobs[: args.slots]])
        tp_discards0 = tp_eng.overlap_discards
        t0 = time.perf_counter()
        tp_done = tp_eng.run(jobs)
        tp_dt = time.perf_counter() - t0
        tp_tokens = sum(len(r.tokens) for r in tp_done)
        tp_tps = tp_tokens / tp_dt if tp_dt else 0.0
        tp_match = [r.tokens for r in tp_done] == [r.tokens for r in done]
        tp_speedup = tp_tps / overlap_tps if overlap_tps else 0.0
        tp_block = {
            "size": tp_n,
            "tokens_per_sec": round(tp_tps, 2),
            "tp1_tokens_per_sec": round(overlap_tps, 2),
            "speedup": round(tp_speedup, 3),
            "scaling_efficiency": round(tp_speedup / tp_n, 3),
            "discards": tp_eng.overlap_discards - tp_discards0,
            "tokens_match": tp_match,
        }
        log(
            "perf-ledger row: | MULTICHIP tensor-parallel serving "
            "(tp=%d, b%d) | tp=1 %.2f → tp=%d %.2f tokens/sec (%.3fx, "
            "efficiency %.3f; discards %d; tokens %s) | - | `benchmark.py "
            "--model serving` | update on bench round |"
            % (
                tp_n,
                args.slots,
                overlap_tps,
                tp_n,
                tp_tps,
                tp_speedup,
                tp_speedup / tp_n,
                tp_block["discards"],
                "bit-identical" if tp_match else "DIVERGED",
            )
        )
    # --- Kernels phase (KERNELS rows): split-K vs gather vs single-pass
    kernels_block = _run_kernels_phase(args)
    # --- Overload phase (OVERLOAD row): 2x storm, mixed priorities -----
    overload_block = _run_overload_phase(eng, args, overlap_tps)
    # --- Restart phase (RESTART row): cold vs warm arena rehydration ---
    restart_block = _run_restart_phase(eng, args)
    # --- Elastic phase (ELASTIC row): cold vs peer-warmed join ---------
    elastic_block = _run_elastic_phase(eng, args)
    # --- Disagg phase (DISAGG row): decode ITL under prefill load ------
    disagg_block = _run_disagg_phase(eng, args)
    # --- Router phase (ROUTER row): affinity vs random placement -------
    router_block = _run_router_phase(args)
    # --- Fabric phase (FABRIC row): fleet KV vs affinity-only control --
    fabric_block = _run_fabric_phase(args)
    # --- SLO phase (SLO row): accounting overhead + alert self-check ---
    slo_block = _run_slo_phase(eng, args)
    # --- Canary phase (CANARY row): prober overhead + detection check --
    canary_block = _run_canary_phase(args)
    # --- Autoscale phase (AUTOSCALE row): controller vs static peak ----
    autoscale_block = _run_autoscale_phase(args)
    # --- Postmortem phase (POSTMORTEM row): capture overhead + verdict -
    postmortem_block = _run_postmortem_phase(args)
    print(
        json.dumps(
            {
                "model": "serving",
                "chips": len(jax.devices()),
                "slots": args.slots,
                "requests": len(done),
                "prompt_len": args.prompt_len,
                "new_tokens": args.decode_tokens,
                "throughput": round(tokens / dt, 2),
                "unit": "tokens/sec (continuous batching, warm, "
                "overlapped pipeline)",
                "overlap": {
                    "tokens_per_sec": round(overlap_tps, 2),
                    "sync_tokens_per_sec": round(sync_tps, 2),
                    "speedup": round(overlap_tps / sync_tps, 3)
                    if sync_tps
                    else None,
                    "hits": eng.overlap_hits - hits0,
                    "discards": eng.overlap_discards - discards0,
                },
                "ttft_p50_ms": _ms(ttft_h.quantile(0.5, since=ttft_snap)),
                "ttft_p99_ms": _ms(ttft_h.quantile(0.99, since=ttft_snap)),
                "itl_p50_ms": _ms(itl_h.quantile(0.5, since=itl_snap)),
                "itl_p99_ms": _ms(itl_h.quantile(0.99, since=itl_snap)),
                "kvcache": {
                    "prefix_recompute_tokens_per_sec": round(rec_tps, 2),
                    "prefix_restore_tokens_per_sec": round(res_tps, 2),
                    "restore_speedup": round(kv_speedup, 3),
                    "hits": kv_hits,
                    "retained_hits": eng.kv_retained_hits,
                    "host_hits": eng.kv_host_hits,
                    "restores": eng.kv_restores,
                    "reclaims": eng.kv_reclaims,
                    "offloads": eng.kv_offloads,
                    "churn_tokens": churn_tokens,
                    "preemptions": churn_preempts,
                    "resumes_restored": churn_restored,
                    "resumes_recomputed": churn_recomputed,
                },
                "tp": tp_block,
                "kernels": kernels_block,
                "overload": overload_block,
                "restart": restart_block,
                "elastic": elastic_block,
                "disagg": disagg_block,
                "router": router_block,
                "fabric": fabric_block,
                "slo": slo_block,
                "canary": canary_block,
                "autoscale": autoscale_block,
                "postmortem": postmortem_block,
                "trace": trace_block,
                "spans_recorded": len(spans.snapshot()) + spans.dropped,
                "profile": {
                    "steps": prof["steps"],
                    "step_ms_p50": prof["step_ms"]["p50"],
                    "step_ms_p99": prof["step_ms"]["p99"],
                    "phase_ms_p50": phase_p50,
                    "occupancy": prof["occupancy"],
                    # The tracing phase noted it on the profiler, so the
                    # live GET /debug/profile carries the same number.
                    "trace_overhead": trace_block["overhead"],
                    "incidents": eng.anomaly.snapshot()["incidents_total"],
                },
            }
        ),
        flush=True,
    )


def run_pipelined(args) -> None:
    """Decoder-LM training through the pipelined path (--pp stages) —
    the in-pod way to exercise pp on a multi-chip allocation, with either
    schedule.  Reports tokens/sec like the gpt path."""
    from ..parallel.mesh import make_mesh
    from ..parallel.pipeline_lm import PipelinedLM

    if args.model != "gpt":
        raise SystemExit("--pp requires --model gpt (the pipelined decoder)")
    cfg = _gpt_config(args)
    devices = jax.devices()
    if len(devices) < args.pp:
        raise SystemExit(f"--pp {args.pp} but only {len(devices)} device(s)")
    if cfg.num_layers % args.pp:
        raise SystemExit(
            f"num_layers {cfg.num_layers} not divisible by --pp {args.pp}"
        )
    mesh = make_mesh({"pp": args.pp}, devices=devices[: args.pp])
    plm = PipelinedLM(cfg, mesh, n_micro=args.n_micro)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(
        rng, (args.batch_size, args.seq_len + 1), 0, cfg.vocab_size
    )
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    tx = optax.sgd(0.1, momentum=0.9)
    micro_rows = max(args.batch_size // args.n_micro, 1)
    state = plm.create_train_state(
        plm.init(rng, batch["input_ids"][:micro_rows]), tx
    )
    step = jax.jit(
        plm.make_train_step(tx, schedule=args.pp_schedule), donate_argnums=0
    )
    state, loss, dt = timed_steps(step, state, batch, args.warmup, args.steps)
    tokens = args.batch_size * args.seq_len * args.steps
    print(
        json.dumps(
            {
                "model": "gpt-pp",
                "schedule": args.pp_schedule,
                "chips": len(devices),
                "pp": args.pp,
                "n_micro": args.n_micro,
                "global_batch": args.batch_size,
                "throughput": round(tokens / dt, 2),
                "unit": "tokens/sec",
                "step_time_ms": round(dt / args.steps * 1e3, 2),
                "final_loss": float(loss),
            }
        ),
        flush=True,
    )


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(prog="tpu-benchmark")
    p.add_argument(
        "--model",
        choices=[
            "alexnet", "resnet50", "vit", "bert", "gpt", "gpt-decode",
            "serving",
        ],
        default="resnet50",
    )
    p.add_argument("--batch-size", type=int, default=128, help="GLOBAL batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--seq-len", type=int, default=384)
    p.add_argument("--steps", type=_positive_int, default=30)
    p.add_argument("--warmup", type=_positive_int, default=5)
    p.add_argument("--dp", type=int, default=-1, help="data-parallel axis size (-1: all devices)")
    p.add_argument("--mp", type=int, default=1, help="param-sharding axis size")
    p.add_argument(
        "--pp",
        type=int,
        default=0,
        help="pipeline stages (gpt only): run the decoder through the "
        "pipelined-LM path over a pp mesh axis instead of dp/mp",
    )
    p.add_argument(
        "--pp-schedule",
        choices=["gpipe", "1f1b"],
        default="gpipe",
        help="pipeline schedule (with --pp): gpipe (autodiff backward) or "
        "1f1b (interleaved, O(stages) activation memory)",
    )
    p.add_argument(
        "--n-micro",
        type=_positive_int,
        default=4,
        help="microbatches per step in the pipelined path (with --pp)",
    )
    p.add_argument(
        "--fused-xent",
        action="store_true",
        help="gpt only: fused LM-head + cross-entropy loss tail "
        "(ops/fused_xent.py) — the [batch, seq, vocab] logits tensor "
        "never materializes",
    )
    p.add_argument("--prompt-len", type=_positive_int, default=64, help="gpt-decode/serving prompt")
    p.add_argument("--decode-tokens", type=_positive_int, default=128, help="gpt-decode/serving new tokens")
    p.add_argument(
        "--slots",
        type=_positive_int,
        default=4,
        help="serving: engine decode slots (continuous-batching width)",
    )
    p.add_argument(
        "--requests",
        type=_positive_int,
        default=16,
        help="serving: synthetic requests pushed through the engine",
    )
    p.add_argument(
        "--kernel",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="serving: run the KERNELS phase (split-K paged-attention "
        "kernel vs the gather fallback vs the old single-pass lane, per "
        "shape x KV format — the per-shape ledger tools/bench_diff.py "
        "gates; --no-kernel skips it)",
    )
    p.add_argument(
        "--router-replicas",
        type=int,
        default=2,
        help="serving: replicas in the ROUTER phase (prefix-affinity vs "
        "random-placement control over K tiny real serving replicas "
        "behind the router daemon; 0/1 skips the phase)",
    )
    p.add_argument(
        "--temperature",
        type=float,
        default=None,
        help="gpt-decode: sample with this temperature instead of greedy argmax",
    )
    p.add_argument(
        "--top-k", type=_positive_int, default=None,
        help="gpt-decode: restrict sampling to the k highest logits",
    )
    p.add_argument(
        "--stem",
        choices=["conv7", "space_to_depth"],
        default="conv7",
        help="resnet50 stem: standard 7x7/s2 conv or the space-to-depth "
        "packing (geometry-equivalent, MXU-friendlier — models/resnet.py)",
    )
    p.add_argument(
        "--grad-accum",
        type=_positive_int,
        default=1,
        help="microbatches per optimizer step (one scanned program; "
        "activation memory of one microbatch, full-batch update math) — "
        "the GLOBAL batch must divide evenly",
    )
    p.add_argument("--tiny", action="store_true", help="tiny model config (CPU smoke; gpt and vit)")
    p.add_argument(
        "--trace-dir",
        default=tracing.default_trace_dir(),
        help="write a jax.profiler trace of the timed region here",
    )
    p.add_argument(
        "--checkpoint-dir",
        default="",
        help="orbax checkpoint directory (models/checkpoint.py). When set, "
        "the run saves every --checkpoint-every steps and at exit, so a "
        "preempted pod (health fault, node drain — the BASELINE config-5 "
        "scenario) can resume instead of restarting. ≙ SURVEY §5.4: the "
        "reference plugin is stateless because the kubelet checkpoints "
        "device assignments; the WORKLOAD side must checkpoint itself.",
    )
    p.add_argument(
        "--checkpoint-every",
        type=_positive_int,
        default=10,
        help="steps between async checkpoint saves (with --checkpoint-dir)",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="restore the newest checkpoint under --checkpoint-dir before "
        "training; --steps is then the ABSOLUTE target step, so a resumed "
        "run finishes the remaining steps",
    )
    p.add_argument(
        "--compilation-cache-dir",
        default=os.environ.get("TPU_COMPILATION_CACHE_DIR", ""),
        help="persist XLA compilations here so a restarted benchmark pod "
        "(node drain, preemption — the --resume scenario) skips its "
        "recompiles; empty = no persistent cache",
    )
    args = p.parse_args(argv)

    # Honor an explicit JAX_PLATFORMS from the pod spec even if the image's
    # site hooks programmatically pinned a platform (the CPU-control pod
    # k8s-pod-example-cpu.yaml depends on this: ≙ the reference pinning its
    # control run off-GPU with HIP_VISIBLE_DEVICES=-1).
    from ..utils.platform import (
        enable_compilation_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env(empty_is_auto=False, log=log)
    enable_compilation_cache(args.compilation_cache_dir, log=log)

    # Multi-host (k8s-job-resnet50-2host.yaml): stitch processes over DCN,
    # derived from the plugin-injected TPU_WORKER_* env (or explicit JAX_*
    # overrides — parallel/distributed.py).  jax.devices() then spans the
    # slice and the dp axis crosses hosts.
    if distributed.initialize():
        log(f"jax.distributed: process {jax.process_index()}/{jax.process_count()}")

    # Validate flag combinations BEFORE any model construction so a wrong
    # pod spec fails in milliseconds with a clear message, and no path can
    # silently ignore a requested behavior.
    if args.fused_xent and args.model != "gpt":
        raise SystemExit("--fused-xent requires --model gpt")
    if args.grad_accum > 1 and (
        args.fused_xent or args.pp > 1 or args.model == "gpt-decode"
    ):
        raise SystemExit(
            "--grad-accum applies to the standard train step only (the "
            "fused-xent and pipelined steps manage their own "
            "microbatching, and gpt-decode does not train)"
        )
    if args.grad_accum > 1 and args.batch_size % args.grad_accum:
        raise SystemExit(
            f"--batch-size {args.batch_size} is not divisible by "
            f"--grad-accum {args.grad_accum}"
        )
    if args.fused_xent and args.pp > 1:
        raise SystemExit(
            "--fused-xent is not supported with --pp (the pipelined LM head "
            "runs inside the 1F1B/GPipe objective); drop one of the flags"
        )

    if args.model == "gpt-decode":
        run_decode(args)
        return

    if args.model == "serving":
        run_serving(args)
        return

    if args.pp > 1:
        run_pipelined(args)
        return

    devices = jax.devices()
    log(f"devices: {[str(d) for d in devices]}")
    mesh = make_slice_mesh({"dp": args.dp, "mp": args.mp})
    log(f"mesh: {dict(mesh.shape)}")

    rng = jax.random.PRNGKey(0)
    model, batch, input_key, items_per_step = build(args.model, args, rng)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(rng, model, batch, tx, input_key=input_key)
    if args.fused_xent:
        from .train import make_fused_lm_train_step

        step_fn = make_fused_lm_train_step(model, tx)
        log("loss tail: fused LM-head + cross-entropy (no logits tensor)")
    else:
        step_fn = make_train_step(
            model, tx, input_key=input_key, grad_accum=args.grad_accum
        )
        if args.grad_accum > 1:
            log(f"grad accumulation: {args.grad_accum} microbatches/step")
    step, state, batch_sh = shard_train_step(step_fn, mesh, state, batch)
    if jax.process_count() > 1:
        # Each process owns a slice of the global batch; assemble global
        # arrays from process-local shards (the SPMD multi-host idiom).
        n = jax.process_count()

        def globalize(x, sh):
            per = x.shape[0] // n
            pid = jax.process_index()
            local = np.asarray(x)[pid * per : (pid + 1) * per]
            return jax.make_array_from_process_local_data(sh, local)

        batch = jax.tree.map(globalize, batch, batch_sh)
    else:
        batch = jax.device_put(batch, batch_sh)

    resumed_from = 0
    if args.checkpoint_dir:
        from .checkpoint import CheckpointManager

        ckpt = CheckpointManager(args.checkpoint_dir)
        if args.resume and ckpt.latest_step() is not None:
            # Restore AFTER shard_train_step placed the state: orbax lands
            # every leaf directly in its NamedSharding, no host round-trip.
            state = ckpt.restore(state)
            resumed_from = int(jax.device_get(state.step))
            log(f"resumed from checkpoint step {resumed_from}")
        if resumed_from >= args.steps:
            log(
                f"WARNING: checkpoint already at step {resumed_from} >= "
                f"--steps {args.steps}; nothing to train. Stale checkpoint "
                f"dir from a previous run? Clear it (or raise --steps) to "
                f"re-benchmark."
            )
        with tracing.trace(args.trace_dir):
            state, loss, dt, steps_run = checkpointed_steps(
                step,
                state,
                batch,
                args.steps,
                ckpt,
                args.checkpoint_every,
                warmup=args.warmup,
            )
        ckpt.close()
    else:
        with tracing.trace(args.trace_dir):
            state, loss, dt = timed_steps(step, state, batch, args.warmup, args.steps)
        steps_run = args.steps

    n_chips = len(devices)
    throughput = items_per_step * steps_run / dt if dt > 0 else 0.0
    unit = "tokens/sec" if args.model in ("bert", "gpt") else "images/sec"
    record = {
        "model": args.model,
        "chips": n_chips,
        "global_batch": args.batch_size,
        "throughput": round(throughput, 2),
        "throughput_per_chip": round(throughput / n_chips, 2),
        "unit": unit,
        "step_time_ms": round(dt / steps_run * 1e3, 2) if steps_run else 0.0,
        "final_loss": float(loss) if loss is not None else None,
        # Two-point timing executes warmup + (warmup+steps) steps total, so
        # final_step exceeds --steps; it is the truth about how far the
        # state advanced (checkpoint runs advance exactly to --steps).
        "final_step": int(jax.device_get(state.step)),
    }
    if args.checkpoint_dir:
        record["resumed_from"] = resumed_from
        # Stale-checkpoint rerun guard: True when this invocation trained
        # nothing at all (checkpoint was already at/over --steps).
        record["noop"] = record["final_step"] == resumed_from
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
