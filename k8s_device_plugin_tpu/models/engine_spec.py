"""Serving-engine speculative decoding: shared-pool self-speculation.

Split out of engine.py (round 4).  ``build_spec_rounds`` is a pure
builder (no engine state captured); ``SpeculativeMixin`` carries the
host-side round consumption that ServingEngine mixes in.  The algorithm
(Leviathan/Chen acceptance-rejection over the shared paged pool) is
documented on the builders below; models/speculative.py holds the
standalone dense-cache variant.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.spans import ENGINE_TRACE
from .engine_sampling import filter_top_k_top_p
from .engine_types import Request


def build_spec_rounds(model, draft_model, layer_names: list[str], gamma: int):
    """Build the two jitted speculative-round programs:
    ``(spec_round, spec_round_plain)`` — the full sampled/mixed round and
    the greedy-only fast path (no filter sorts, no softmaxes, no stacked
    Q distributions; _spec_step dispatches host-side on whether any
    active slot samples)."""

    @functools.partial(jax.jit, donate_argnums=(2,))
    def spec_round(
        params, dparams, cache, tokens, positions, temps, topks,
        topps, key,
    ):
        """One speculative round for every slot at once.

        tokens/positions: [slots, 1] (positions = each row's
        current length L).  gamma draft steps propose
        d_1..d_gamma per slot (writing draft K/V at L..L+gamma-1),
        then ONE (gamma+1)-token target pass scores
        [last, d_1..d_gamma] at L..L+gamma — overwriting every
        draft-written slot with exact target K/V, which is what
        makes the shared pool sound.

        Greedy slots (temp <= 0) use longest-agreeing-prefix
        verification (output exactly the greedy decode); sampled
        slots use Leviathan/Chen acceptance-rejection over the
        SAME per-slot temperature/top-k/top-p filter the ordinary
        step applies (accept d w.p. min(1, P(d)/Q(d)); first
        rejection resamples the residual max(0, P-Q), full accept
        samples the bonus from P) — marginally exact filtered
        target sampling, mixed freely in one batch.

        Returns (emitted [slots, gamma+1], a [slots], cache):
        row s's round tokens are emitted[s, :a[s]+1]; length
        rewind is host bookkeeping.
        """
        kd, ka, kt = jax.random.split(key, 3)
        sampling = temps > 0  # [slots]
        safe_t = jnp.where(sampling, temps, 1.0)[:, None]

        def d_step(carry, i):
            c, tok = carry
            logits, mut = draft_model.apply(
                {"params": dparams, "cache": c},
                tok,
                positions + i,
                mutable=["cache"],
            )
            row = logits[:, -1, :]
            greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
            filt = filter_top_k_top_p(row / safe_t, topks, topps)
            samp = jax.random.categorical(
                jax.random.fold_in(kd, i), filt
            ).astype(jnp.int32)
            nxt = jnp.where(sampling, samp, greedy)[:, None]
            q = jax.nn.softmax(filt, axis=-1)  # draft dist Q_i
            return (mut["cache"], nxt), (nxt[:, 0], q)

        (cache, _), (props_t, q_t) = jax.lax.scan(
            d_step, (cache, tokens), jnp.arange(gamma)
        )
        props = props_t.T  # [slots, gamma]
        qs = jnp.moveaxis(q_t, 0, 1)  # [slots, gamma, vocab]
        # The draft advanced every row's seq_lens to L+gamma;
        # rewind to L so the verify append writes L..L+gamma.
        L = positions[:, 0]
        cache = {
            name: {
                **cache[name],
                "attn": {**cache[name]["attn"], "seq_lens": L},
            }
            for name in layer_names
        }
        block = jnp.concatenate([tokens, props], axis=1)
        block_pos = positions + jnp.arange(gamma + 1)[None, :]
        v_logits, mut = model.apply(
            {"params": params, "cache": cache},
            block,
            block_pos,
            mutable=["cache"],
        )  # [slots, gamma+1, vocab]
        slots, vocab = v_logits.shape[0], v_logits.shape[2]
        v_filt = filter_top_k_top_p(
            (v_logits / safe_t[..., None]).reshape(-1, vocab),
            jnp.repeat(topks, gamma + 1),
            jnp.repeat(topps, gamma + 1),
        ).reshape(slots, gamma + 1, vocab)
        p = jax.nn.softmax(v_filt, axis=-1)  # target dist P_j

        # Greedy acceptance: longest prefix agreeing with argmax.
        t_greedy = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
        match_g = (props == t_greedy[:, :gamma]).astype(jnp.int32)
        a_g = jnp.sum(jnp.cumprod(match_g, axis=1), axis=1)
        # Sampling acceptance-rejection.
        p_d = jnp.take_along_axis(
            p[:, :gamma], props[..., None], axis=-1
        )[..., 0]
        q_d = jnp.take_along_axis(qs, props[..., None], axis=-1)[
            ..., 0
        ]
        u = jax.random.uniform(ka, (slots, gamma))
        accept = (u * q_d < p_d).astype(jnp.int32)
        a_s = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
        a = jnp.where(sampling, a_s, a_g)  # [slots]

        # Tail token at position a: correction/bonus.  Sampled
        # slots draw from the residual max(0, P_a - Q_a) (full
        # accept: Q_gamma := 0 so the residual is P_gamma itself).
        p_a = jnp.take_along_axis(p, a[:, None, None], axis=1)[:, 0]
        qs_pad = jnp.concatenate(
            [qs, jnp.zeros((slots, 1, vocab), qs.dtype)], axis=1
        )
        q_a = jnp.take_along_axis(qs_pad, a[:, None, None], axis=1)[
            :, 0
        ]
        resid = jnp.where(
            (a < gamma)[:, None], jnp.clip(p_a - q_a, min=0.0), p_a
        )
        norm = jnp.sum(resid, axis=-1, keepdims=True)
        tail_p = jnp.where(norm > 0, resid / norm, p_a)
        tail_samp = jax.random.categorical(
            kt, jnp.log(tail_p)
        ).astype(jnp.int32)
        tail_greedy = jnp.take_along_axis(t_greedy, a[:, None], 1)[
            :, 0
        ]
        tail = jnp.where(sampling, tail_samp, tail_greedy)
        idxs = jnp.arange(gamma + 1)[None, :]
        props_pad = jnp.concatenate(
            [props, jnp.zeros((slots, 1), jnp.int32)], axis=1
        )
        emitted = jnp.where(idxs < a[:, None], props_pad, tail[:, None])
        return emitted, a, mut["cache"]

    # Plain greedy round — no filter sorts, no softmaxes, no
    # stacked Q distributions.  Same step_plain rationale: a spec
    # engine serving only greedy requests (the CLI default) must
    # not pay the sampler machinery every round; _spec_step
    # dispatches host-side on whether any active slot samples.
    @functools.partial(jax.jit, donate_argnums=(2,))
    def spec_round_plain(params, dparams, cache, tokens, positions):
        def d_step(carry, i):
            c, tok = carry
            logits, mut = draft_model.apply(
                {"params": dparams, "cache": c},
                tok,
                positions + i,
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(
                jnp.int32
            )[:, None]
            return (mut["cache"], nxt), nxt[:, 0]

        (cache, _), props_t = jax.lax.scan(
            d_step, (cache, tokens), jnp.arange(gamma)
        )
        props = props_t.T
        L = positions[:, 0]
        cache = {
            name: {
                **cache[name],
                "attn": {**cache[name]["attn"], "seq_lens": L},
            }
            for name in layer_names
        }
        block = jnp.concatenate([tokens, props], axis=1)
        block_pos = positions + jnp.arange(gamma + 1)[None, :]
        v_logits, mut = model.apply(
            {"params": params, "cache": cache},
            block,
            block_pos,
            mutable=["cache"],
        )
        slots = v_logits.shape[0]
        t_greedy = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
        match = (props == t_greedy[:, :gamma]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        tail = jnp.take_along_axis(t_greedy, a[:, None], 1)[:, 0]
        props_pad = jnp.concatenate(
            [props, jnp.zeros((slots, 1), jnp.int32)], axis=1
        )
        emitted = jnp.where(
            jnp.arange(gamma + 1)[None, :] < a[:, None],
            props_pad,
            tail[:, None],
        )
        return emitted, a, mut["cache"]

    return spec_round, spec_round_plain


class SpeculativeMixin:
    """Host-side speculative round consumption, mixed into ServingEngine
    (which owns every attribute referenced here)."""

    def _spec_step(self, active: list[int], finished: list[Request]) -> list[Request]:
        """One speculative round: gamma draft steps + one verify pass
        advance every active slot by 1..gamma+1 tokens.  Greedy slots
        emit EXACTLY their non-speculative greedy decode; sampled slots
        emit marginally exact filtered target samples (both pinned in
        tests/test_engine.py); speculation changes only the schedule."""
        active = self._ensure_frontier(active, self._spec_gamma)
        if not active:
            self._update_gauges()
            return finished
        round_t0 = time.monotonic()
        tokens = jnp.asarray(self._slot_last, jnp.int32)[:, None]
        positions = jnp.asarray(self._slot_len, jnp.int32)[:, None]
        if any(
            self.slots[s] is not None and self._slot_temp[s] > 0
            for s in range(self.max_slots)
        ):
            temps = jnp.asarray(self._slot_temp, jnp.float32)
            topks = jnp.asarray(self._slot_topk, jnp.int32)
            topps = jnp.asarray(self._slot_topp, jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            emitted, a_vec, self.cache = self._spec_round(
                self.params, self.draft_params, self.cache, tokens,
                positions, temps, topks, topps, sub,
            )
        else:
            emitted, a_vec, self.cache = self._spec_round_plain(
                self.params, self.draft_params, self.cache, tokens, positions
            )
        emitted = np.asarray(emitted)
        a_vec = np.asarray(a_vec)
        self._mark("spec_verify")
        now = time.monotonic()
        if self.spans:
            # One engine-scoped span per draft+verify round: acceptance
            # attrs make a low-acceptance regime visible right next to
            # the round's wall time in /debug/state.
            self.spans.record_span(
                "spec.verify",
                ENGINE_TRACE,
                start_monotonic=round_t0,
                end_monotonic=now,
                attrs={
                    "slots": len(active),
                    "proposed": int(self._spec_gamma) * len(active),
                    "accepted": int(sum(a_vec[s] for s in active)),
                },
            )
        gamma = self._spec_gamma
        emitted_total = 0
        for s in active:
            req = self.slots[s]
            a = int(a_vec[s])
            # Emit d_1..d_a then the target's own token at position a
            # (correction on rejection, bonus on full accept).  All a+1
            # tokens are consumed unless a finish condition truncates —
            # and truncation only ever coincides with req.done, so live
            # slots always consume exactly a+1.
            self.spec_proposed += gamma
            self.spec_accepted += a
            if self.metrics:
                self.metrics.spec_proposed.inc(gamma)
                self.metrics.spec_accepted.inc(a)
                if gamma > a:
                    self.metrics.spec_rejected.inc(gamma - a)
            round_toks = [int(emitted[s, j]) for j in range(a + 1)]
            consumed = 0
            for tok in round_toks:
                req.tokens.append(tok)
                self._slot_last[s] = tok
                consumed += 1
                emitted_total += 1
                if (
                    len(req.tokens) >= req.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or self._hit_stop(req)
                ):
                    break
            self._slot_len[s] += consumed
            self._observe_itl(s, consumed, now)
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        # The round left every row's device length at L+gamma+1; re-align
        # all rows to the host truth in one vector write per layer (idle
        # and just-cleared rows are 0 in _slot_len, matching _clear_slot).
        # A FRESH array per layer: sharing one across layers would hand
        # the next round's donation the same buffer twice, which XLA
        # rejects (donate(a), donate(a)).
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "seq_lens": jnp.array(self._slot_len, jnp.int32),
            }
        # Rounds advance each slot by a data-dependent 1..gamma+1: the
        # device-resident step state cannot be fed forward (engine.py).
        self._mark_state_dirty()
        self._mark("sample")
        self._step_tokens += emitted_total
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(emitted_total)
        self._update_gauges()
        return finished
