"""Shared serving-engine types: the request record and Prometheus series.

Split out of engine.py (round 4) so the engine orchestrator, admission
policy (engine_admission.py), and paging (engine_paging.py) submodules can
all name them without import cycles.  Public import surface stays
``models.engine`` (which re-exports these).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils.metrics import MetricsRegistry


def _pow2_int(text: str) -> int:
    """argparse type: positive power of two (chunk sizes must tile the
    power-of-two length buckets)."""
    import argparse

    value = int(text)
    if value < 1 or value & (value - 1):
        raise argparse.ArgumentTypeError(
            f"must be a positive power of two, got {value}"
        )
    return value


class EngineMetrics:
    """Prometheus series for the serving engine (same registry machinery
    the plugin daemon exposes on its --metrics-port).  Pass a shared
    registry to co-expose with other subsystems, or let each engine own
    one and mount it on a utils.metrics.MetricsServer."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.requests = registry.counter(
            "tpu_engine_requests_total",
            "Requests admitted into a decode slot",
        )
        self.tokens = registry.counter(
            "tpu_engine_tokens_total", "Tokens emitted across all requests"
        )
        self.steps = registry.counter(
            "tpu_engine_steps_total", "Jitted decode steps executed"
        )
        self.active_slots = registry.gauge(
            "tpu_engine_active_slots", "Slots currently serving a request"
        )
        self.queued = registry.gauge(
            "tpu_engine_queued_requests", "Requests waiting for slots/pages"
        )
        self.free_pages = registry.gauge(
            "tpu_engine_free_pages", "Unallocated KV-cache pages"
        )
        self.shared_pages = registry.gauge(
            "tpu_engine_shared_pages",
            "Pages currently referenced by more than one request (prefix sharing)",
        )
        self.spec_proposed = registry.counter(
            "tpu_engine_spec_proposed_total",
            "Draft tokens proposed by speculative rounds",
        )
        self.spec_accepted = registry.counter(
            "tpu_engine_spec_accepted_total",
            "Draft tokens the target accepted (rate = accepted/proposed)",
        )
        self.spec_rejected = registry.counter(
            "tpu_engine_spec_rejected_total",
            "Draft tokens the target rejected (proposed - accepted; a "
            "rising rate says gamma is too high for this traffic)",
        )
        self.preemptions = registry.counter(
            "tpu_engine_preemptions_total",
            "Slots evicted for recompute-resume under optimistic admission",
        )
        self.state_rebuilds = registry.counter(
            "tpu_engine_state_rebuilds_total",
            "Device step-state rebuilds from host lists (admissions, "
            "teardowns); steady decode should add ~2 per request "
            "lifecycle, not per token.  Speculative engines drive every "
            "step through their own host-published state and never "
            "rebuild, so this stays 0 when spec_gamma > 0",
        )
        self.overlap_hits = registry.counter(
            "tpu_engine_overlap_hits_total",
            "Decode rounds consumed from an overlapped in-flight "
            "dispatch (issued before the previous round's readback); "
            "in steady decode with overlap_steps=1 this tracks "
            "steps_total",
        )
        self.overlap_discards = registry.counter(
            "tpu_engine_overlap_discards_total",
            "Overlapped dispatches thrown away because a slot event "
            "(admission, finish, cancel, preemption) invalidated their "
            "inputs — one wasted device lane each; a rate rivalling "
            "overlap_hits says traffic churns too fast for "
            "--overlap-steps 1 to pay off",
        )
        self.step_seconds = registry.histogram(
            "tpu_engine_step_seconds",
            "Wall time of one engine step() call (admission + dispatch + "
            "consume); histogram_quantile() gives serving-step p50/p99",
        )
        self.wait_seconds = registry.histogram(
            "tpu_engine_request_wait_seconds",
            "Queue-to-first-token wait per request (admission latency "
            "under load)",
            # Wider than the step buckets: overload pushes waits far past
            # 10s, and a saturated top bucket would clamp the p99 exactly
            # when the metric matters.
            buckets=(
                0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0, 120.0, 300.0,
            ),
        )
        # The two serving-latency numbers operators actually page on.
        # TTFT = submit -> first emitted token (queue wait + batched
        # prefill + admission overhead); ITL = gap between consecutive
        # emitted tokens of one request (decode-block dispatches emit T
        # tokens at once, so each of those T observes dt/T — the sum
        # stays wall-accurate and histogram_quantile() stays meaningful).
        self.ttft_seconds = registry.histogram(
            "tpu_engine_ttft_seconds",
            "Submit-to-first-token latency per request; "
            "histogram_quantile(0.99, ...) is the serving SLO number",
            buckets=(
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0, 120.0,
            ),
        )
        self.itl_seconds = registry.histogram(
            "tpu_engine_itl_seconds",
            "Inter-token latency per emitted decode token "
            "(block dispatches amortize: each of T tokens observes dt/T)",
        )
        self.incidents = registry.counter(
            "tpu_engine_incidents_total",
            "Anomaly incidents emitted by the engine-side monitor "
            "(utils/anomaly.py): sustained deviations of step time or "
            "TTFT from their EWMA baselines; the records themselves are "
            "served at GET /debug/incidents",
            ["metric"],
        )
        self.tp_size = registry.gauge(
            "tpu_engine_tp_size",
            "Tensor-parallel degree of the serving engine (size of the "
            "tp mesh axis built from the plugin's allocation; 1 = "
            "single-chip).  Set once at engine construction",
        )
        # Split-K paged-attention kernel routing (ops/paged_attention.py):
        # whether this engine's decode steps read pages through the
        # kernel, and ctor-time fallback decisions worth surfacing (the
        # speculative verify pass riding gather, an untuned generation
        # running the conservative split row).
        self.kernel_enabled = registry.gauge(
            "tpu_engine_kernel_enabled",
            "1 when the paged decode reads the KV pool through the "
            "split-K flash-decode kernel, 0 on the gather fallback "
            "(PagedConfig.use_kernel; auto resolves to gather until a "
            "hardware round records tuning rows).  Set once at engine "
            "construction",
        )
        self.kernel_fallbacks = registry.counter(
            "tpu_engine_kernel_fallbacks_total",
            "Kernel-path fallback decisions at engine construction, by "
            "reason (spec_verify: the multi-token speculative verify "
            "pass rides the gather path by design while single-token "
            "steps keep the kernel; untuned_generation: no reviewed "
            "ops/tuning.py row for this chip — the kernel runs the "
            "conservative fallback split row until a hardware round "
            "records one).  Each pairs with a kernel.fallback flight "
            "event",
            ["reason"],
        )
        self.page_utilization = registry.gauge(
            "tpu_engine_kv_page_utilization",
            "Allocated fraction of the allocatable KV page pool (0..1; "
            "sustained ~1.0 with queued requests means the pool, not "
            "compute, caps concurrency)",
        )
        # KV cache tiering (models/engine_kvcache.py): tier sizes, hit and
        # demotion flow, and what restore-instead-of-recompute costs.
        self.kvcache_retained_pages = registry.gauge(
            "tpu_engine_kvcache_retained_pages",
            "Dead-but-valid KV pages held on the retained (tier-1) LRU — "
            "trie-reachable at zero refcount, reclaimed lazily under "
            "pool pressure",
        )
        self.kvcache_host_bytes = registry.gauge(
            "tpu_engine_kvcache_host_bytes",
            "Bytes held in the host-RAM KV arena (tier 2, bounded by "
            "--kv-host-cache-mb): offloaded pages plus preemption "
            "snapshots",
        )
        self.kvcache_hits = registry.counter(
            "tpu_engine_kvcache_hits_total",
            "Prefix pages served from a KV cache tier instead of "
            "recomputed (tier=retained: revived device page; tier=host: "
            "restored from the arena)",
            ["tier"],
        )
        self.kvcache_evictions = registry.counter(
            "tpu_engine_kvcache_evictions_total",
            "KV tier demotions/evictions (tier=retained: page reclaimed "
            "into the free pool, offloading first when the arena is on; "
            "tier=host: arena entries dropped to hold the byte budget)",
            ["tier"],
        )
        self.kvcache_restores = registry.counter(
            "tpu_engine_kvcache_restores_total",
            "Pages restored host->device via sliced page writes (no "
            "recompute, no new compiled shapes)",
        )
        self.kvcache_restore_seconds = registry.histogram(
            "tpu_engine_kvcache_restore_seconds",
            "Wall time of one host->device restore batch (all pages of "
            "one admission, every layer); compare against the prefill "
            "it replaced to validate the tier pays off",
        )
        self.resumes = registry.counter(
            "tpu_engine_resumes_total",
            "Preempted requests re-admitted after eviction "
            "(mode=restored: slot rebuilt from the KV tiers, zero "
            "prefill; mode=recompute: full prefill over prompt + "
            "generated tokens) — preemptions_total minus this is the "
            "victims still waiting",
            ["mode"],
        )
        self.resume_restored_tokens = registry.counter(
            "tpu_engine_resume_restored_tokens_total",
            "Tokens whose K/V a preemption resume restored instead of "
            "recomputing",
        )
        self.resume_recomputed_tokens = registry.counter(
            "tpu_engine_resume_recomputed_tokens_total",
            "Tokens re-prefilled by recompute-resumes (the work the KV "
            "tiers exist to avoid; a rising rate says the host arena is "
            "too small for the preemption churn)",
        )
        # Overload control (models/engine_overload.py).  The queue-wait
        # histogram is the AIMD limiter's input signal made scrapeable:
        # submit -> slot-assignment wait per admitted request, split by
        # priority class (a closed 3-value label, never per-tenant).
        self.queue_wait_seconds = registry.histogram(
            "tpu_engine_queue_wait_seconds",
            "Queue wait (submit to slot assignment) per admitted request "
            "by priority class — the overload limiter steers this toward "
            "--overload-target-wait; histogram_quantile() gives the "
            "per-class admission-latency p99",
            buckets=(
                0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0, 120.0, 300.0,
            ),
            labelnames=("priority",),
        )
        self.sheds = registry.counter(
            "tpu_engine_sheds_total",
            "Requests shed by overload control, by kind (expired: queued "
            "past deadline; infeasible: preempted from a slot that could "
            "no longer finish in time; queue_full / overload: rejected "
            "at submit) and priority class — shed requests never hold a "
            "slot or KV pages",
            ("kind", "priority"),
        )
        self.tenant_sheds = registry.counter(
            "tpu_engine_tenant_sheds_total",
            "Sheds per tenant (first 16 distinct tenants get their own "
            "label; later ones aggregate under _other so client-supplied "
            "names cannot mint unbounded series)",
            ("tenant",),
        )
        # SLO plane (utils/slo.py, ISSUE 16): one verdict per finished
        # request per objective, plus per-tenant usage meters.  The
        # tenant label rides the SAME bounded map as tenant_sheds (first
        # 16 distinct tenants, later ones fold into _other), so every
        # family stays under the fleet cardinality budget.
        self.sli_events = registry.counter(
            "tpu_engine_sli_events_total",
            "SLI verdicts by objective (ttft, itl_p99, availability) and "
            "verdict (good/bad) — the raw feed behind /debug/slo's error "
            "budgets; rate(verdict=bad) over rate() is the burn input",
            ("objective", "verdict"),
        )
        self.tenant_requests = registry.counter(
            "tpu_engine_tenant_requests_total",
            "Finished requests charged per tenant (16-tenant label cap, "
            "overflow under _other) — the /debug/usage row count",
            ("tenant",),
        )
        self.tenant_prompt_tokens = registry.counter(
            "tpu_engine_tenant_prompt_tokens_total",
            "Prompt tokens prefetched per tenant (charged only for "
            "requests that reached a slot; 16-tenant label cap)",
            ("tenant",),
        )
        self.tenant_decode_tokens = registry.counter(
            "tpu_engine_tenant_decode_tokens_total",
            "Decode tokens emitted per tenant (16-tenant label cap)",
            ("tenant",),
        )
        self.tenant_kv_page_seconds = registry.counter(
            "tpu_engine_tenant_kv_page_seconds_total",
            "KV page-seconds held per tenant: pages at finish x slot "
            "residency — a conservative upper bound (shared prefix pages "
            "charge every sharer; 16-tenant label cap)",
            ("tenant",),
        )
        self.tenant_queue_wait_seconds = registry.counter(
            "tpu_engine_tenant_queue_wait_seconds_total",
            "Seconds spent queued per tenant before a slot (or before "
            "the shed that answered instead; 16-tenant label cap)",
            ("tenant",),
        )
        self.goodput_tokens = registry.counter(
            "tpu_engine_goodput_tokens_total",
            "Tokens of requests that COMPLETED within their deadline "
            "(deadline-free requests count on completion) — compare "
            "against tpu_engine_tokens_total: the gap is work burned on "
            "requests that were shed, cancelled, or finished too late",
        )
        self.admission_limit = registry.gauge(
            "tpu_engine_admission_limit",
            "Current AIMD admitted-concurrency limit (slots the overload "
            "controller lets admission fill; max_slots when overload "
            "control is off or fully recovered)",
        )
        # Replica self-fencing (models/engine_watchdog.py + EngineServer):
        # a fenced replica stops admitting (503), reads fenced on
        # /healthz and the router's summary poll, and its in-flight
        # streams fail over — the metric pair is the rollout/alert
        # surface.
        self.fenced = registry.gauge(
            "tpu_engine_fenced",
            "1 while this replica is fenced (admission closed, router "
            "demoted, streams failing over); 0 otherwise.  Fence reasons "
            "ride tpu_engine_fences_total and GET /debug/state",
        )
        self.fences = registry.counter(
            "tpu_engine_fences_total",
            "Fence activations by source (watchdog: a dispatched step "
            "outlived its deadline; chip_health: a chip in this "
            "replica's mesh went Unhealthy/unplugged; operator: POST "
            "/debug/fence)",
            ["source"],
        )
        self.watchdog_deadline = registry.gauge(
            "tpu_engine_watchdog_deadline_seconds",
            "Current hung-step deadline (grace window during "
            "warmup/compiles, else factor x rolling step p99) — the "
            "wall-clock bound after which the watchdog fences",
        )
        # KV-arena warm restart (models/engine_snapshot.py): save/load
        # outcomes and the on-disk size — a corrupt load shows up as
        # outcome=corrupt with the replica serving cold, never poisoned.
        self.snapshot_saves = registry.counter(
            "tpu_engine_snapshot_saves_total",
            "KV-arena snapshot writes by outcome (ok / error); saves run "
            "on fence, drain, SIGTERM, and the periodic timer",
            ["outcome"],
        )
        self.snapshot_loads = registry.counter(
            "tpu_engine_snapshot_loads_total",
            "KV-arena snapshot restores at startup by outcome (ok / "
            "missing / corrupt / layout_mismatch / params_mismatch / "
            "disabled); anything but ok degrades to a clean cold start",
            ["outcome"],
        )
        self.snapshot_bytes = registry.gauge(
            "tpu_engine_snapshot_bytes",
            "Size of the last successfully written KV-arena snapshot "
            "(size the snapshot volume from this plus headroom)",
        )
        # Elastic warm scale-up (GET /debug/snapshot peer transfer):
        # donor-side serves and joiner-side fetches.  A joiner fetch
        # with anything but outcome=ok cold-started clean.
        self.snapshot_serves = registry.counter(
            "tpu_engine_snapshot_serves_total",
            "Peer snapshot streams served at GET /debug/snapshot by "
            "outcome (ok / refused / client_gone / error); refused = "
            "the joiner's layout/params fingerprint headers mismatched "
            "and no bytes moved",
            ["outcome"],
        )
        self.snapshot_served_bytes = registry.counter(
            "tpu_engine_snapshot_served_bytes",
            "KV-arena snapshot bytes streamed to warm-joining peers "
            "(donor-side transfer volume)",
        )
        self.snapshot_fetches = registry.counter(
            "tpu_engine_snapshot_fetches_total",
            "Peer snapshot fetches at warm join by outcome (ok / "
            "unreachable / refused / corrupt / layout_mismatch / "
            "params_mismatch / disabled); anything but ok degrades to "
            "a clean cold start",
            ["outcome"],
        )
        # Disaggregated prefill/decode serving (models/engine_handoff.py):
        # the replica's role plus the per-request KV handoff flow —
        # prefill-side probe serves, decode-side fetches, and the entry
        # counts moving through the content-addressed arena.
        self.role = registry.gauge(
            "tpu_engine_role",
            "Serving role of this replica (0 unified, 1 prefill, 2 "
            "decode — models/engine_handoff.py).  Set once at engine "
            "construction from --role",
        )
        self.handoff_serves = registry.counter(
            "tpu_engine_handoff_serves_total",
            "POST /v1/prefill probe streams served by outcome (ok / "
            "refused / rejected / error / client_gone / aborted); "
            "refused = fingerprint/role mismatch before any bytes, "
            "rejected = the probe submit was shed/invalid, aborted = "
            "the probe died mid-stream and the transfer was torn",
            ["outcome"],
        )
        self.handoff_fetches = registry.counter(
            "tpu_engine_handoff_fetches_total",
            "Decode-side prefill fetches (X-Handoff-Source pulls) by "
            "outcome (ok / unreachable / refused / corrupt / "
            "layout_mismatch / params_mismatch / disabled); anything "
            "but ok degrades to ordinary LOCAL prefill — existing "
            "arena contents are untouched",
            ["outcome"],
        )
        self.handoff_entries = registry.counter(
            "tpu_engine_handoff_entries_total",
            "Full KV prefix pages moved by the handoff machinery, by "
            "direction (published: prefill side into its own arena; "
            "served: streamed to a /v1/prefill caller; fetched: "
            "admitted into this decode replica's arena)",
            ["direction"],
        )
        self.handoff_refusals = registry.counter(
            "tpu_engine_handoff_refusals_total",
            "Decode-role /generate refusals (409 + X-Prefill-Needed): "
            "the prompt's full-page prefix was neither resident nor "
            "fetchable (no X-Handoff-Source locator) — the router "
            "should have routed the prefill first",
        )
        # Fleet KV fabric (models/engine_handoff.py fabric_digest +
        # the router's locator/replication plane, router/fabric.py).
        self.fabric_digest_roots = registry.gauge(
            "tpu_engine_fabric_digest_roots",
            "Distinct cumulative prefix roots advertised in the last "
            "built fabric bloom digest (trie-resident + host-arena); "
            "what the router's locator believes this replica can serve",
        )
        self.fabric_pulls = registry.counter(
            "tpu_engine_fabric_pulls_total",
            "Router-driven replication pulls (POST /debug/fabric/pull "
            "-> fetch_prefill from the named peer) by outcome (ok / "
            "error); error admits NOTHING and leaves the arena as-is",
            ["outcome"],
        )
        self.fabric_drops = registry.counter(
            "tpu_engine_fabric_drops_total",
            "Router-driven replica-eviction drops (POST "
            "/debug/fabric/drop): host-arena copies of a cold prefix "
            "released; live/retained device pages are never touched",
        )


@dataclasses.dataclass
class Request:
    """One generation request and, when finished, its output tokens.

    ``temperature`` 0 means greedy; > 0 samples that request's tokens at
    that temperature.  ``top_k``/``top_p`` restrict sampling to the k
    highest logits / the smallest nucleus with mass >= p (None = off;
    only meaningful with temperature > 0).  Slots with different sampler
    settings mix freely in one jitted step."""

    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # Multi-LoRA serving (cfg.lora_serve > 0): which stacked adapter this
    # request decodes through; None = base model.
    adapter: Optional[int] = None
    # Sparse logit bias: {token_id: added_logit} applied BEFORE greedy
    # argmax and sampling (OpenAI semantics: -100 bans, +100 forces);
    # capped at ServingEngine.MAX_BIAS entries.  Reported logprobs stay
    # UNBIASED (bias changes what gets picked, not what is scored).
    logit_bias: Optional[dict] = None
    # Stop sequences (token-id lists): generation ends when the output's
    # tail equals any of them; the matched suffix is EXCLUDED from
    # ``tokens`` (eos_id, by contrast, is included — the id itself is the
    # terminator, a stop sequence is a content sentinel).
    stop: Optional[list[list[int]]] = None
    # Latched by the engine when a stop sequence matched (the matched
    # suffix is truncated away, so the flag — not the tail — records it).
    stopped: bool = False
    # Record each emitted token's logprob under the unscaled model
    # distribution in ``token_logprobs`` (parallel to ``tokens``).
    # Sampler settings change what gets picked, never what is reported.
    logprobs: bool = False
    rid: int = -1
    # Overload-control contract (models/engine_overload.py): priority
    # class (0 high / 1 normal / 2 low — lower admits first, sheds
    # last), the tenant the request's token cost is charged to for fair
    # sharing, and an ABSOLUTE monotonic deadline (converted from the
    # wire's remaining-seconds form at submit; None = no deadline).
    # All three are inert when the engine runs without a controller.
    priority: int = 1
    tenant: str = ""
    deadline: Optional[float] = None
    # Set when overload control shed this request (a shed kind from
    # engine_overload.py: expired/infeasible/...); the HTTP layer maps
    # it to 504 (deadline sheds) or 503 + Retry-After (load sheds).
    shed: Optional[str] = None
    # End-to-end trace id: supplied by the client (X-Request-Id) or minted
    # at submit; echoed in responses/SSE events and stamped on every span
    # this request produces (utils/spans.py).
    trace_id: str = ""
    # Reserved root-span id (spans recorder): the queue/prefill/decode
    # child spans parent on it across threads; 0 when tracing is off.
    root_span: int = 0
    # Cross-process parent link (X-Trace-Context, utils/spans.py): the
    # 16-hex span id of the router attempt that carried this request,
    # plus which hop/attempt of the request's journey that dial was.
    # The request root span records them as attrs so
    # tools/trace_assemble.py can root this replica's tree under the
    # router's — "" means no upstream context (a direct client).
    trace_parent: str = ""
    trace_hop: int = 0
    trace_attempt: int = 0
    # monotonic submit time (engine-internal: queue-wait observation).
    submitted_at: float = 0.0
    # monotonic lifecycle stamps (0.0 until reached): slot assignment,
    # first emitted token (TTFT anchor), and finish.
    admitted_at: float = 0.0
    first_token_at: float = 0.0
    finished_at: float = 0.0
    # Peak per-token inter-token gap seen on this request (_observe_itl
    # maintains it).  For the short generations this engine serves the
    # per-request p99 ITL equals the max gap, so the SLO plane scores
    # this against the itl_p99 objective without a per-request
    # histogram; 0.0 until a second token lands.
    itl_peak_s: float = 0.0
    tokens: list[int] = dataclasses.field(default_factory=list)
    token_logprobs: list[float] = dataclasses.field(default_factory=list)
    done: bool = False
    # Set via ServingEngine.cancel() (client went away): a queued request
    # finishes immediately; an in-flight one is torn down at the next step
    # boundary, its slot and pages returned to the pool.
    cancelled: bool = False
