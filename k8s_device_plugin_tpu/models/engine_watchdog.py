"""Replica self-fencing inputs: hung-step watchdog + chip-health feed.

The stack can survive replica *loss* (router failover) and replica
*overload* (admission shedding), but a replica that is merely *sick*
keeps taking traffic: a hung device step (the ``engine.readback`` hang
failpoint models the real shape — a wedged DMA/readback that never
returns) freezes the owner loop with every detector blind (the step-time
anomaly monitor only sees COMPLETED steps), and the plugin daemon
marking a chip Unhealthy for the kubelet does nothing to the serving
engine already running on that chip.  Host-Side Telemetry (PAPERS.md)
argues exactly this: hang/degradation diagnosis must come from
host-side watchdogs that do not require device cooperation.

Two detectors, both stdlib-only and thread-driven so a wedged engine
owner thread cannot take the detector down with it:

- :class:`StepWatchdog` — deadlines every dispatched engine step against
  a rolling baseline of recently COMPLETED step wall times (the same
  walls the per-step profiler windows).  Compile-aware grace: steps that
  build a new jitted program, advance a prefill, or activate an
  admission get the long ``grace_deadline_s`` instead of the tight
  ``factor * baseline`` one, so a first-shape XLA compile (tens of
  seconds) never false-trips; so does everything before ``warmup``
  completed steps.  On breach it calls ``on_fence`` ONCE (re-armed via
  :meth:`rearm` after an operator unfence).
- :class:`ChipHealthFeed` — watches the chips the engine is actually
  decoding on: polls the plugin daemon's ``/debug/devices`` surface
  (authoritative — native probes, flap debounce, unplug detection) and
  falls back to direct ``/dev/accel*`` presence probes when no daemon
  URL is configured or the daemon stops answering.  A chip going
  Unhealthy or vanishing fences the replica instead of letting it serve
  garbage.

The fence itself (admission 503, ``/healthz`` -> fenced, summary
``fenced`` for the router's poll loop, stream cut for zero-drop
failover, KV-arena snapshot) lives on ``models/http_server.EngineServer``
— these classes only decide WHEN.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Callable, Optional


def visible_chip_paths(environ=None, root: str = "/") -> list[str]:
    """Device-node paths of the chips allocated to THIS pod, from the
    ``TPU_VISIBLE_CHIPS`` env the plugin's Allocate response injects
    (``"0,1"`` -> ``[/dev/accel0, /dev/accel1]``); empty off-cluster.
    ``root`` is the injectable host-tree root the rest of the plugin
    test surface uses."""
    environ = os.environ if environ is None else environ
    text = environ.get("TPU_VISIBLE_CHIPS", "") or ""
    out: list[str] = []
    for part in text.replace(",", " ").split():
        try:
            idx = int(part)
        except ValueError:
            return []
        out.append(os.path.join(root, f"dev/accel{idx}"))
    return out


class StepWatchdog:
    """Host-side deadline on every dispatched engine step.

    Protocol (engine owner thread): ``step_started()`` at the top of
    ``ServingEngine.step()``, ``note_grace(reason)`` any time during the
    step that a long stall is LEGITIMATE (new jitted program built,
    prefill chunk advanced, admission activated), ``step_finished(wall)``
    at the end.  A separate daemon thread (or a test calling
    :meth:`check` on a fake clock) compares the in-flight step's age
    against the applicable deadline:

    - grace step, or fewer than ``warmup`` completed steps:
      ``grace_deadline_s`` (a compile may run tens of seconds);
    - otherwise ``max(min_deadline_s, factor * p99(recent walls))``.

    Only non-grace, non-tripped walls feed the baseline, so neither a
    compile outlier nor the hang itself can inflate the deadline.  The
    trip fires ``on_fence(info)`` exactly once per arm; :meth:`rearm`
    (the unfence path) re-enables it.  ``clock`` is injectable so the
    unit suite drives warmup/grace/trip on a fake clock with zero
    sleeps.
    """

    def __init__(
        self,
        on_fence: Callable[[dict], None],
        *,
        clock: Callable[[], float] = time.monotonic,
        window: int = 64,
        warmup: int = 8,
        factor: float = 8.0,
        min_deadline_s: float = 1.0,
        grace_deadline_s: float = 60.0,
        poll_interval_s: float = 0.25,
        observe_deadline: Optional[Callable[[float], None]] = None,
    ):
        if warmup < 1:
            raise ValueError(f"warmup must be >= 1, got {warmup}")
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if min_deadline_s <= 0 or grace_deadline_s <= 0:
            raise ValueError("deadlines must be > 0")
        self.on_fence = on_fence
        self._clock = clock
        self._warmup = warmup
        self._factor = factor
        self._min_deadline_s = float(min_deadline_s)
        self._grace_deadline_s = float(grace_deadline_s)
        self._poll_interval_s = float(poll_interval_s)
        self._observe_deadline = observe_deadline
        self._lock = threading.Lock()
        self._walls: list[float] = []
        self._window = int(window)
        self._completed = 0
        self._in_step = False
        self._step_start = 0.0
        self._step_grace: Optional[str] = None
        self._step_tripped = False
        self.tripped = False
        self.trips = 0
        self.grace_steps = 0
        self._last_trip: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------- owner-thread hooks

    def step_started(self) -> None:
        with self._lock:
            self._in_step = True
            self._step_start = self._clock()
            self._step_grace = None
            self._step_tripped = False

    def note_grace(self, reason: str) -> None:
        """Mark the CURRENT step as legitimately slow (compile, prefill,
        activation): its deadline becomes ``grace_deadline_s`` and its
        wall never feeds the baseline."""
        with self._lock:
            if self._step_grace is None:
                self.grace_steps += 1
            self._step_grace = str(reason)

    def step_finished(self, wall_s: float) -> None:
        with self._lock:
            self._in_step = False
            if self._step_grace is None and not self._step_tripped:
                self._walls.append(float(wall_s))
                if len(self._walls) > self._window:
                    del self._walls[0]
                self._completed += 1
            deadline = self._deadline_locked()
        if self._observe_deadline is not None:
            self._observe_deadline(deadline)

    # ---------------------------------------------------------- deadline

    def _baseline_locked(self) -> float:
        """Nearest-rank p99 over the rolling window of completed walls."""
        if not self._walls:
            return 0.0
        walls = sorted(self._walls)
        return walls[min(int(0.99 * len(walls)), len(walls) - 1)]

    def _deadline_locked(self) -> float:
        if self._step_grace is not None or self._completed < self._warmup:
            return self._grace_deadline_s
        return max(self._min_deadline_s, self._factor * self._baseline_locked())

    def deadline_s(self) -> float:
        """The deadline the CURRENT (or next) step is judged against."""
        with self._lock:
            return self._deadline_locked()

    # -------------------------------------------------------------- check

    def check(self, now: Optional[float] = None) -> Optional[dict]:
        """One watchdog poll: trip (and fire ``on_fence``) when the
        in-flight step has outlived its deadline.  Returns the trip info
        dict, or None.  Fires at most once per arm."""
        with self._lock:
            if self.tripped or not self._in_step:
                return None
            now = self._clock() if now is None else now
            deadline = self._deadline_locked()
            age = now - self._step_start
            if age <= deadline:
                return None
            self.tripped = True
            self._step_tripped = True
            self.trips += 1
            info = {
                "kind": "hung_step",
                "observed_s": round(age, 3),
                "deadline_s": round(deadline, 3),
                "baseline_s": round(self._baseline_locked(), 6),
                "grace": self._step_grace,
                "completed_steps": self._completed,
            }
            self._last_trip = info
        self.on_fence(info)
        return info

    def rearm(self) -> None:
        """Re-enable tripping (the unfence path).  The in-flight flag is
        left as-is: if the step is STILL hung the next poll trips again
        — an operator unfencing a wedged replica learns immediately."""
        with self._lock:
            self.tripped = False

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "StepWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="engine-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "in_step": self._in_step,
                "completed_steps": self._completed,
                "baseline_p99_ms": round(self._baseline_locked() * 1e3, 4),
                "deadline_s": round(self._deadline_locked(), 4),
                "warmup": self._warmup,
                "factor": self._factor,
                "grace_steps": self.grace_steps,
                "tripped": self.tripped,
                "trips": self.trips,
                "last_trip": self._last_trip,
            }


class ChipHealthFeed:
    """Node-local health watch over the chips this replica decodes on.

    Primary source: the plugin daemon's ``GET /debug/devices`` snapshot
    (``url``) — per-chip ``healthy`` verdicts behind the native prober
    and the flap debounce, plus unplug detection (a yanked chip leaves
    the inventory entirely).  Fallback: after
    ``url_failures_to_fallback`` consecutive poll failures (or with no
    URL configured), direct presence probes of ``device_paths`` — the
    daemon being down is a daemon problem, but once it is down the
    devfs node is the only truth left, and a VANISHED node is
    unambiguous.  A daemon outage alone never fences (recorded as a
    ``chip_health.feed_down`` flight event instead).

    ``on_unhealthy(info)`` fires once per arm (``rearm()`` on unfence);
    drive :meth:`check_once` directly in tests, or :meth:`start` the
    poll thread in production.
    """

    def __init__(
        self,
        on_unhealthy: Callable[[dict], None],
        *,
        url: str = "",
        device_paths=(),
        poll_interval_s: float = 1.0,
        url_timeout_s: float = 2.0,
        url_failures_to_fallback: int = 3,
        flight=None,
    ):
        if not url and not device_paths:
            raise ValueError(
                "chip-health feed needs a daemon URL and/or device paths"
            )
        self.on_unhealthy = on_unhealthy
        self.url = url
        self.device_paths = [str(p) for p in device_paths]
        self._poll_interval_s = float(poll_interval_s)
        self._url_timeout_s = float(url_timeout_s)
        self._url_failures_to_fallback = int(url_failures_to_fallback)
        self.flight = flight
        self._url_failures = 0
        self._feed_down_recorded = False
        self.tripped = False
        self.checks = 0
        self._last_fault: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- probes

    def _probe_url(self) -> Optional[dict]:
        """One daemon poll; returns a fault dict, None (all healthy), or
        raises OSError/ValueError on a daemon failure."""
        with urllib.request.urlopen(
            self.url, timeout=self._url_timeout_s
        ) as resp:
            payload = json.loads(resp.read() or b"{}")
        chips = payload.get("chips") or []
        by_base = {
            os.path.basename(c.get("device_path") or ""): c for c in chips
        }
        if self.device_paths:
            for path in self.device_paths:
                base = os.path.basename(path)
                chip = by_base.get(base)
                if chip is None:
                    # Left the daemon's inventory: /dev/accel* is
                    # authoritative for existence — the chip is GONE.
                    return {
                        "kind": "unplugged", "device": base, "probe": "daemon",
                    }
                if not chip.get("healthy", False):
                    return {
                        "kind": "unhealthy", "device": base, "probe": "daemon",
                    }
            return None
        for chip in chips:
            if not chip.get("healthy", False):
                return {
                    "kind": "unhealthy",
                    "device": str(chip.get("id")),
                    "probe": "daemon",
                }
        return None

    def _probe_devfs(self) -> Optional[dict]:
        for path in self.device_paths:
            if not os.path.exists(path):
                return {
                    "kind": "unplugged",
                    "device": os.path.basename(path),
                    "probe": "devfs",
                }
        return None

    def _probe(self) -> Optional[dict]:
        if self.url:
            try:
                fault = self._probe_url()
            except (OSError, ValueError) as e:
                self._url_failures += 1
                if (
                    self.flight is not None
                    and not self._feed_down_recorded
                ):
                    self._feed_down_recorded = True
                    self.flight.record(
                        "chip_health.feed_down", url=self.url, error=str(e)
                    )
                if (
                    self.device_paths
                    and self._url_failures >= self._url_failures_to_fallback
                ):
                    # Daemon gone: devfs presence is the only truth left.
                    return self._probe_devfs()
                return None
            if self._url_failures and self.flight is not None:
                self.flight.record("chip_health.feed_up", url=self.url)
            self._url_failures = 0
            self._feed_down_recorded = False
            return fault
        return self._probe_devfs()

    # --------------------------------------------------------------- check

    def check_once(self) -> Optional[dict]:
        """One health poll; fires ``on_unhealthy(info)`` (once per arm)
        and returns the fault info when a chip is unhealthy/unplugged."""
        self.checks += 1
        fault = self._probe()
        if fault is None or self.tripped:
            return fault if not self.tripped else None
        self.tripped = True
        self._last_fault = fault
        self.on_unhealthy(fault)
        return fault

    def rearm(self) -> None:
        self.tripped = False

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ChipHealthFeed":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="chip-health-feed", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            self.check_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def snapshot(self) -> dict:
        return {
            "url": self.url or None,
            "device_paths": list(self.device_paths),
            "checks": self.checks,
            "url_failures": self._url_failures,
            "tripped": self.tripped,
            "last_fault": self._last_fault,
        }
