"""Serving-engine page-pool and device-table management.

Split out of engine.py (round 4): everything that allocates, publishes,
shares, reclaims, or frees KV-cache pages lives here, mixed into
ServingEngine (which owns the state: ``free_pages``, ``_page_refs``, the
prefix trie, the per-slot page chains, and the device cache tree).
Invariants are documented on each method; the capacity model is on the
engine module docstring.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


class PagingMixin:
    """Page allocation/free, prefix-sharing trie, frontier publication,
    windowed reclamation, and the prefill->pages graft."""

    def _graft(
        self,
        slot: int,
        dense_cache: Any,
        pages: list[int],
        plen: int,
        n_shared: int,
        row_idx: int = 0,
    ):
        """Scatter a prefilled dense cache's rows into the PRIVATE prompt
        pages and point the slot's table/length at the full chain — ONE
        page-indexed scatter per pool per layer (not per page: eager `.at`
        updates are copy-on-write, so per-page updates would round-trip
        the whole pool once per page).

        Shared prefix pages (the first ``n_shared``) are never rewritten:
        a concurrent request is reading them, and K/V from a prefill
        compiled at a different prompt length are not guaranteed bitwise
        identical — rewriting could perturb an in-flight generation.
        Private pages are written whole; tail slots past plen carry zeros,
        which later appends overwrite before any masked read can see
        them."""
        ps = self.paged.page_size
        n_cover = math.ceil(plen / ps)
        # Publish only the pages the NEXT decode step can touch: those
        # covering positions [0, plen] (the first decode write lands at
        # position plen; a speculative round writes up to plen+gamma).
        # The rest of the chain stays at scratch page 0 until the
        # frontier reaches it so the kernel's pipeline never streams
        # unwritten generation pages.  Derive-tables engines record the
        # FULL chain in the [slots, max_pages] chain array (one device
        # write) and the jitted step computes the visible prefix
        # in-program; speculative engines publish into every layer's
        # cache table here and extend via _extend_frontier.
        n_publish = min((plen + self._spec_gamma) // ps + 1, len(pages))
        self._slot_visible[slot] = n_publish
        if self._derive_tables:
            full = np.zeros((self.paged.max_pages_per_seq,), np.int32)
            full[: len(pages)] = pages
            self._chain = self._chain.at[slot].set(jnp.asarray(full))
        else:
            row = np.zeros((self.paged.max_pages_per_seq,), np.int32)
            row[:n_publish] = pages[:n_publish]
        lo_tok = n_shared * ps  # first private-covered token position
        n_priv_cover = n_cover - n_shared
        cover = jnp.asarray(pages[n_shared:n_cover], jnp.int32)
        pad = n_cover * ps - plen
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            src = dense_cache[name]["attn"]

            def paged_rows(slab):
                rows = slab[row_idx, lo_tok:plen]
                if pad:
                    rows = jnp.pad(
                        rows, ((0, pad),) + ((0, 0),) * (rows.ndim - 1)
                    )
                return rows.reshape(n_priv_cover, ps, *rows.shape[1:])

            new_att = {
                **att,
                "seq_lens": att["seq_lens"].at[slot].set(plen),
            }
            if not self._derive_tables:
                new_att["page_table"] = (
                    att["page_table"].at[slot].set(jnp.asarray(row))
                )
            if n_priv_cover > 0:
                new_att["pool_key"] = (
                    att["pool_key"].at[cover].set(paged_rows(src["cached_key"]))
                )
                new_att["pool_value"] = (
                    att["pool_value"].at[cover].set(paged_rows(src["cached_value"]))
                )
                if "pool_key_scale" in att:
                    # int8 KV: the scale rows CACHE alongside the page
                    # write — the dense prefill quantized once
                    # (quantize_kv_pair) and its scale slabs scatter
                    # here with the codes; nothing later (kernel,
                    # gather, offload, restore) re-derives a scale.
                    # Pool-byte accounting (_kv_rows_nbytes) counts the
                    # two f32 scale pools with the codes — pinned in
                    # tests/test_engine.py.
                    new_att["pool_key_scale"] = (
                        att["pool_key_scale"]
                        .at[cover]
                        .set(paged_rows(src["cached_key_scale"]))
                    )
                    new_att["pool_value_scale"] = (
                        att["pool_value_scale"]
                        .at[cover]
                        .set(paged_rows(src["cached_value_scale"]))
                    )
            self.cache[name]["attn"] = new_att

    def _clear_slot(self, slot: int):
        if self._derive_tables:
            # One chain-row zero; per-layer cache tables are derived
            # in-program and overwritten before any read.
            self._chain = self._chain.at[slot].set(0)
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            new_att = {
                **att,
                "seq_lens": att["seq_lens"].at[slot].set(0),
            }
            if not self._derive_tables:
                new_att["page_table"] = att["page_table"].at[slot].set(0)
            self.cache[name]["attn"] = new_att
        for page in self._slot_pages[slot]:
            self._release_page(page)
        self._slot_pages[slot] = []
        self.slots[slot] = None
        self._slot_last[slot] = 0
        self._slot_len[slot] = 0
        self._slot_temp[slot] = 0.0
        self._slot_topk[slot] = self.cfg.vocab_size
        self._slot_topp[slot] = 1.0
        self._slot_bias_ids[slot] = [0] * self.MAX_BIAS
        self._slot_bias_vals[slot] = [0.0] * self.MAX_BIAS
        self._slot_aid[slot] = -1
        self._slot_page_base[slot] = 0
        self._slot_visible[slot] = 0
        self._slot_ready[slot] = False
        self._slot_emit_t[slot] = 0.0
        # Slot scalars changed: the device-resident step state must be
        # rebuilt from host truth before the next dispatch (engine.py).
        self._mark_state_dirty()

    def _release_page(self, page: int) -> None:
        """Drop one reference; at zero, either RETAIN the page (trie
        links intact — the kv-cache tier 1, engine_kvcache.py: a later
        same-prefix request matches it for free, and the allocator
        reclaims it lazily when the pool runs dry) or tear down every
        trie link touching the page and return it to the pool.  The ONE
        page-free path: _clear_slot and windowed reclamation both come
        through here.  Runs under the engine lock: _update_gauges
        iterates _page_refs from the scraping/submitting threads, and a
        resize here mid-iteration would crash them."""
        with self._lock:
            self._page_refs[page] -= 1
            if self._page_refs[page] > 0:
                return
            if self._kv_retain and self._kv_retain_page(page):
                return  # refcount parks at 0; revived on the next match
            del self._page_refs[page]
            self._teardown_page_links(page)
            self.free_pages.append(page)

    def _teardown_page_links(self, page: int) -> None:  # caller holds: _lock
        """Remove every trie link touching a dying page: keys registered
        FOR it and keys in which it is the PARENT — a freed id can be
        reallocated and re-registered with different content, so a
        surviving child link would let a later prompt walk into another
        request's K/V.  Shared by the free path above and the retained-
        tier reclaim (engine_kvcache.py), which must uphold the same
        invariant.  Caller holds the engine lock."""
        for key in self._page_keys.pop(page, []):
            self._prefix_pages.pop(key, None)
            self._trie_version += 1
        for key in self._child_keys.pop(page, []):
            child = self._prefix_pages.pop(key, None)
            if child is not None:
                self._trie_version += 1
                keys = self._page_keys.get(child)
                if keys and key in keys:
                    keys.remove(key)

    @staticmethod
    def _trie_root(adapter: Optional[int]) -> int:
        """Root pseudo-parent for the prefix trie: K/V are a function of
        (params, adapter, tokens), so each adapter gets its own root (-1 =
        base model, -(2+i) = adapter i) and chains never cross adapters.
        Pseudo-roots are never real pages, so they are never freed and
        take no _child_keys bookkeeping (their links die with the child
        page, exactly like the old -1 root's)."""
        return -1 if adapter is None else -(2 + adapter)

    def _match_prefix(
        self,
        prompt: list[int],
        bucket: int,
        burst_pages: dict[int, int],
        adapter: Optional[int] = None,
    ) -> list[int]:
        """Longest chain of live registered pages whose token chunks equal
        this prompt's leading FULL pages (trie walk: O(prompt)).

        A page may only be shared once its content is guaranteed written
        before this request's first decode step: pages of ACTIVATED
        requests always qualify; pages of a still-pending prefill job do
        NOT (the owner's graft is deferred — sharing them would decode
        against zeros), EXCEPT pages admitted in this same burst with the
        same length bucket — those land in the same job, whose _activate
        grafts every item before any of them decodes.
        """
        ps = self.paged.page_size
        pages: list[int] = []
        parent = self._trie_root(adapter)
        for i in range(len(prompt) // ps):
            chunk = tuple(prompt[i * ps : (i + 1) * ps])
            page = self._prefix_pages.get((parent, chunk))
            if page is None:
                break
            if page in burst_pages:
                if burst_pages[page] != bucket:
                    break  # different bucket -> different job -> unsafe
            elif page in self._pending_pages:
                break  # owner's job from an earlier step not grafted yet
            pages.append(page)
            parent = page
        return pages

    def _register_prefix(  # caller holds: _lock
        self, eff: list[int], pages: list[int], n: int, adapter: Optional[int]
    ) -> None:
        """Register ``eff``'s first ``n`` full pages as trie links so
        later same-prefix requests can ride them (idempotent: an
        existing key wins and the walk follows the CANONICAL page, which
        in the admission path is always ``pages[i]`` itself).  Callers:
        the admission burst, the preemption snapshot (publishing a
        victim's generated pages), and restore-resume (re-linking
        restored pages).  Caller holds the engine lock."""
        ps = self.paged.page_size
        parent = self._trie_root(adapter)
        for i in range(n):
            key = (parent, tuple(eff[i * ps : (i + 1) * ps]))
            if key not in self._prefix_pages:
                self._prefix_pages[key] = pages[i]
                self._page_keys.setdefault(pages[i], []).append(key)
                self._trie_version += 1
                if parent >= 0:
                    self._child_keys.setdefault(parent, []).append(key)
            parent = self._prefix_pages[key]

    def _ensure_frontier(self, active: list[int], lookahead: int) -> list[int]:
        """Make every coming write in [len, len+lookahead] addressable for
        each active slot, then publish the covering pages.

        ``lookahead`` callers: plain synchronous decode passes 0 (only
        the next position's write), the overlapped pipeline passes 1 (the
        in-flight step's write at len+1 must be addressable BEFORE the
        host has consumed position len), decode blocks pass T-1 — or
        2T-1 with an overlapped block in flight — and speculative rounds
        run gamma lookahead through _extend_frontier directly.

        Reserve admission: pages were all allocated at admission, so this
        is pure publication.  Optimistic admission: generation pages are
        allocated HERE, on demand — processed oldest-admission-first, a
        pool shortage preempts the newest ready slot (recompute-resume:
        the victim requeues at the head and re-prefills prompt+generated),
        and if the shortage persists the starved slot itself is evicted.
        Oldest-first + newest-evicted means the oldest request can never
        be robbed, which is the liveness argument (it eventually owns
        every page its submit-time bound guarantees fit).  Returns the
        active list minus anything evicted."""
        if not self._optimistic:
            for s in active:
                self._extend_frontier(s, lookahead=lookahead)
            return active
        ps = self.paged.page_size
        for s in sorted(active, key=lambda x: self._slot_seq[x]):
            req = self.slots[s]
            if req is None or not self._slot_ready[s]:
                continue  # evicted as a victim earlier in this pass
            need = (self._slot_len[s] + lookahead) // ps + 1
            while need > self._slot_page_base[s] + len(self._slot_pages[s]):
                with self._lock:
                    if not self.free_pages and self._kv_retained:
                        # Retained pages are reclaimable-on-demand: spill
                        # one to the host tier before robbing a newer slot.
                        self._kv_reclaim(1)
                    page = (
                        self.free_pages.popleft() if self.free_pages else None
                    )
                    if page is not None:
                        self._page_refs[page] = 1
                        self._slot_pages[s].append(page)
                        if self._derive_tables:
                            # Record the grown chain; the step publishes
                            # it in-program once the frontier arrives.
                            idx = (
                                self._slot_page_base[s]
                                + len(self._slot_pages[s])
                                - 1
                            )
                            self._chain = self._chain.at[s, idx].set(page)
                        continue
                if not self._preempt_newest(newer_than=self._slot_seq[s]):
                    break
            if need > self._slot_page_base[s] + len(self._slot_pages[s]):
                self._evict_slot(s)  # starved even after preempting: resume later
                continue
            self._extend_frontier(s, lookahead=lookahead)
        return [
            s
            for s in active
            if self.slots[s] is not None and self._slot_ready[s]
        ]

    def _preempt_newest(self, newer_than: int) -> bool:
        """Evict the most recently admitted ready slot STRICTLY newer
        than ``newer_than`` to free its pages; False when none is.  A
        growing slot may only rob younger slots — never an older one —
        so the oldest request's page claim is monotone (liveness)."""
        cands = [
            s
            for s in range(self.max_slots)
            if self.slots[s] is not None
            and self._slot_ready[s]
            and self._slot_seq[s] > newer_than
        ]
        if not cands:
            return False
        self._evict_slot(max(cands, key=lambda s: self._slot_seq[s]))
        return True

    def _evict_slot(self, slot: int) -> None:
        """Preempt: tear the slot down exactly like a finish (pages,
        table row, prefix refcounts all through _clear_slot) but requeue
        the request at the queue HEAD for recompute-resume — unless the
        client already cancelled it, in which case eviction doubles as
        the teardown."""
        req = self.slots[slot]
        # Snapshot BEFORE teardown: the tail page's rows and the decode
        # state scalars (engine_kvcache.py) — _clear_slot's release then
        # RETAINS the full pages (registered below) rather than freeing
        # them, so the victim's own resume matches them device-side.  A
        # racing cancel is reconciled under the lock below.
        snapshotted = (
            self._kv_snapshot_slot(slot, req) if not req.cancelled else False
        )
        self._clear_slot(slot)
        with self._lock:
            # Atomic with cancel(): a disconnect racing this eviction
            # either finds the request still in a slot (cancel marks it;
            # we see cancelled here) or finds it back in the queue
            # (cancel removes it there) — never a cancelled request
            # silently re-admitted.
            if req.cancelled:
                if snapshotted:
                    self._kv_drop_snapshot(req.rid)
                req.done = True
                self._update_gauges()
                return
            # Only a real recompute-resume counts as a preemption: a
            # cancelled victim's eviction is ordinary teardown, and
            # operators size the pool from this counter.
            self.preemptions += 1
            if self.metrics:
                self.metrics.preemptions.inc()
            self.queue.appendleft(req)
            self._update_gauges()
        if self.flight is not None:
            self.flight.record(
                "engine.preempt",
                rid=req.rid,
                generated=len(req.tokens),
                free_pages_after=len(self.free_pages),
                snapshot=snapshotted,
            )

    def _extend_frontier(self, slot: int, lookahead: Optional[int] = None) -> None:
        """Publish every page the next step can write — up to the one
        covering position len+lookahead — into the device table the
        moment the frontier approaches it: tiny .at[slot, idx].set
        updates per layer, amortized O(1/page_size) dispatches per token.
        ``lookahead`` defaults to the speculative gamma (0 for plain
        decode: only the next position's page); decode blocks and the
        overlapped pipeline pass their furthest write via
        _ensure_frontier (see its docstring for the caller table)."""
        if lookahead is None:
            lookahead = self._spec_gamma
        need = (
            self._slot_len[slot] + lookahead
        ) // self.paged.page_size + 1
        need = min(
            need, self._slot_page_base[slot] + len(self._slot_pages[slot])
        )
        if self._derive_tables:
            # Publication happens in-program (the step derives the
            # visible prefix from the chain array); only the host-side
            # watermark advances here, for invariants and tests.
            self._slot_visible[slot] = max(self._slot_visible[slot], need)
            return
        while self._slot_visible[slot] < need:
            idx = self._slot_visible[slot]  # logical page index to publish
            page = self._slot_pages[slot][idx - self._slot_page_base[slot]]
            for name in self._layer_names:
                att = self.cache[name]["attn"]
                self.cache[name]["attn"] = {
                    **att,
                    "page_table": att["page_table"].at[slot, idx].set(page),
                }
            self._slot_visible[slot] = idx + 1

    def _reclaim_windowed(self, slot: int) -> None:
        """Free pages that scrolled fully out of a sliding attention
        window.  A query at position p sees keys in (p - window, p]; once
        every position in a page is below ``len - window`` no future query
        can see it — visibility only moves forward — so the page returns
        to the pool mid-flight (bounded cache memory for long windowed
        decodes).  Its table entry points at the scratch page: gathers of
        masked positions read garbage that the window mask discards, and
        the append frontier is always ahead of the reclaimed region."""
        window = self.cfg.attention_window
        ps = self.paged.page_size
        horizon = self._slot_len[slot] - window
        # horizon // ps = TOTAL pages ever dead for this slot; subtract the
        # already-reclaimed count (the page list is trimmed in place, so
        # reusing the total as an increment would double-free live pages —
        # caught by the windowed-oracle test).
        n_dead = max(
            0,
            min(
                horizon // ps - self._slot_page_base[slot],
                len(self._slot_pages[slot]),
            ),
        )
        if n_dead <= 0:
            return
        dead, self._slot_pages[slot] = (
            self._slot_pages[slot][:n_dead],
            self._slot_pages[slot][n_dead:],
        )
        # The logical page indices shift only in OUR bookkeeping; the
        # device table keeps absolute logical positions, so dead entries
        # are re-pointed at scratch (a sliced device update — no host
        # round-trip) rather than compacted.  A freed id may be
        # reallocated to another request immediately, so the entry MUST
        # be zeroed before the next dispatch — derive-tables engines
        # zero the chain (one array), spec engines every layer's table.
        lo = self._slot_page_base[slot]
        if self._derive_tables:
            self._chain = self._chain.at[slot, lo : lo + n_dead].set(0)
        else:
            for name in self._layer_names:
                att = self.cache[name]["attn"]
                self.cache[name]["attn"] = {
                    **att,
                    "page_table": att["page_table"].at[slot, lo : lo + n_dead].set(0),
                }
        self._slot_page_base[slot] += n_dead
        for page in dead:
            self._release_page(page)
