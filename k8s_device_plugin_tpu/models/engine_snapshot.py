"""Crash-safe warm restart of the KV host arena.

The KV tiers (models/engine_kvcache.py) make a hot prefix cheap — until
the process dies: a liveness-probe restart, a fence-triggered rollout,
or a plain pod delete throws away the retained pages AND the host arena,
so the restarted replica re-prefills every system prompt from scratch
exactly when the fleet is already degraded.  This module persists the
content-addressed arena to disk and rehydrates it at startup, so the
restarted replica's prefix restores hit warm:

- **What is saved.**  Every ``("prefix", root, tokens)`` arena entry,
  plus (optionally) the retained DEVICE pages read back through the
  same per-layer row path the offload uses — a snapshot taken at
  fence/drain time captures tier 1 too, not just what pool pressure
  already spilled.  Preemption snapshots (``("snap", rid)``) are
  deliberately excluded: they are keyed to request ids of a process
  that is about to not exist.
- **File format.**  ``MAGIC | version | header JSON | entries``, written
  to a tempfile and atomically renamed (a crash mid-write leaves the
  previous snapshot intact, never a torn one).  The header pins the
  page layout (per-layer pool shapes/dtypes, page size) and a cheap
  params fingerprint; each entry carries its own CRC32.  Arena entries
  are content-addressed by token prefix, so the ONLY way a restore can
  poison correctness is serving different weights or a different cache
  layout under the same tokens — both refuse at load.
- **Degradation contract** (pinned in tier-1): a corrupted or truncated
  snapshot — or one from a different model/layout — degrades to a CLEAN
  cold start: everything partially loaded is dropped, the load is
  metered ``outcome=corrupt`` (or ``layout_mismatch``/``params_mismatch``),
  and serving proceeds exactly as if no snapshot existed.  Never a
  poisoned cache.

The same ``MAGIC | version | header | entries`` byte stream doubles as
the **peer-transfer wire format** (ISSUE 14): a scaling-up replica
streams a warm neighbor's ``GET /debug/snapshot`` and rehydrates
through the same verification path, so a joiner enters the fleet with
the donor's hot prefixes instead of stone-cold — and the SAME
degradation contract holds: a donor dying mid-transfer, a torn stream,
or an incompatible peer (layout/params fingerprints ride HTTP headers
and refuse before any bytes land) all degrade to a clean cold start.

Failpoint sites (docs/chaos.md): ``engine.snapshot.save`` (``error``
aborts the save; ``truncate[:fraction]`` writes a torn file — the
disk-corruption shape the load contract is scored against),
``engine.snapshot.load`` (``error`` = unreadable file, ``truncate``
reads a prefix of the bytes), ``engine.snapshot.serve`` (donor side:
``error`` refuses, ``truncate`` tears the stream mid-transfer — the
donor-died-mid-send shape, ``hang`` stalls the transfer), and
``engine.snapshot.fetch`` (joiner side: ``error`` = dial failure,
``truncate`` reads a prefix of the peer's bytes).
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import time
import zlib
from typing import Any, Iterator, Optional

import numpy as np

from ..utils import failpoints

MAGIC = b"TPUKVSN1"
VERSION = 1
SNAPSHOT_NAME = "kv_arena.snapshot"

# Peer-transfer negotiation headers (GET /debug/snapshot): the joiner
# states what it can ingest; the donor refuses a mismatch with 409
# BEFORE any snapshot bytes land (and stamps its own values on the
# response either way).
LAYOUT_HEADER = "X-Snapshot-Layout"
PARAMS_HEADER = "X-Snapshot-Params"
ENTRIES_HEADER = "X-Snapshot-Entries"

# Per-leaf byte cap on the params fingerprint sample: enough to tell two
# weight sets apart, cheap enough to run at every save/load.
_FP_SAMPLE_BYTES = 4096
_FP_SAMPLE_LEAVES = 4


class SnapshotError(RuntimeError):
    """Raised internally on any parse/verify failure; the load call site
    translates it into the clean-cold-start degradation."""


def snapshot_layout(engine) -> dict:
    """The page-row layout this engine's snapshot entries must match:
    page size plus per-layer pool shapes/dtypes of ONE page's rows (the
    exact arrays ``_kv_read_page_rows`` produces).  Serialized into the
    header and compared verbatim at load — a restart with a different
    model config refuses the snapshot instead of mis-slicing blobs."""
    layers: dict[str, dict] = {}
    for name in engine._layer_names:
        att = engine.cache[name]["attn"]
        layers[name] = {
            pool: {
                "shape": [int(d) for d in att[pool].shape[1:]],
                "dtype": str(att[pool].dtype),
            }
            for pool in sorted(engine._kv_pool_names(att))
        }
    return {"page_size": int(engine.paged.page_size), "layers": layers}


def params_fingerprint(params: Any) -> str:
    """Cheap content fingerprint of a param tree: CRC32 over every
    leaf's (path, shape, dtype) plus the first bytes of a few leaves.
    Restored KV rows are only valid against the weights that produced
    them; this catches a restart that loaded different weights under
    the same architecture (same layout, different checkpoint)."""
    import jax

    crc = 0
    leaves = jax.tree_util.tree_leaves_with_path(params)
    for i, (path, leaf) in enumerate(leaves):
        desc = f"{jax.tree_util.keystr(path)}|{tuple(leaf.shape)}|{leaf.dtype}"
        crc = zlib.crc32(desc.encode(), crc)
        if i < _FP_SAMPLE_LEAVES:
            # Slice BEFORE materializing: only the sample crosses
            # device->host, not the whole (possibly multi-MB) leaf.
            flat = leaf.reshape(-1)
            n = max(1, _FP_SAMPLE_BYTES // np.dtype(flat.dtype).itemsize)
            sample = np.asarray(flat[:n])
            crc = zlib.crc32(np.ascontiguousarray(sample).tobytes(), crc)
    return f"{crc:08x}"


def layout_fingerprint(layout: dict) -> str:
    """Short stable fingerprint of a page-row layout — what the peer
    negotiation headers carry (the full layout JSON still rides the
    stream's header and is compared verbatim at parse; the header hash
    only exists to refuse before bytes move)."""
    blob = json.dumps(layout, sort_keys=True, separators=(",", ":")).encode()
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its serialized name, including the ml_dtypes family
    (bfloat16 et al.) numpy cannot resolve by string alone."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _entry_blob(rows: dict, layout: dict) -> bytes:
    """One entry's arrays concatenated in layout order (the order load
    splits by)."""
    parts: list[bytes] = []
    for layer, pools in layout["layers"].items():
        for pool in pools:
            parts.append(np.ascontiguousarray(rows[layer][pool]).tobytes())
    return b"".join(parts)


def _split_blob(blob: bytes, layout: dict) -> dict:
    rows: dict[str, dict[str, np.ndarray]] = {}
    offset = 0
    for layer, pools in layout["layers"].items():
        rows[layer] = {}
        for pool, spec in pools.items():
            dtype = _resolve_dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
            chunk = blob[offset : offset + nbytes]
            if len(chunk) != nbytes:
                raise SnapshotError("entry blob shorter than its layout")
            rows[layer][pool] = np.frombuffer(chunk, dtype=dtype).reshape(shape)
            offset += nbytes
    if offset != len(blob):
        raise SnapshotError("entry blob longer than its layout")
    return rows


def collect_entries(engine, include_device: bool = True) -> dict[tuple, dict]:
    """Every persistable prefix entry: the arena's ``("prefix", ...)``
    contents plus (with ``include_device``) the retained tier-1 device
    pages read back by cumulative prefix — the same content-addressed
    key the offload path would have used.  ``("snap", rid)`` resume
    snapshots are skipped (rid-keyed to a dying process).  Caller holds
    the engine lock; a chip-health fence passes ``include_device=False``
    (reading pages off a sick chip could persist garbage — the arena
    copy in host RAM is the trustworthy subset)."""
    entries: dict[tuple, dict] = {}
    for key, entry in engine._kv_arena._entries.items():
        if key and key[0] == "prefix":
            entries[key] = entry["rows"]
    if include_device:
        for page in list(engine._kv_retained):
            prefix = engine._kv_page_prefix(page)
            if prefix is None:
                continue
            key = ("prefix", prefix[0], prefix[1])
            if key not in entries:
                entries[key] = engine._kv_read_page_rows(page)
    return entries


def encode_preamble(layout: dict, fingerprint: str, n_entries: int) -> bytes:
    """The ``MAGIC | version | header`` stream preamble for a transfer
    of ``n_entries`` entries.  Shared by :func:`encode_snapshot` and the
    per-request prefill→decode handoff stream (engine_handoff.py), whose
    entry count is known up front (the prompt's full-page count) while
    the entries themselves arrive chunk by chunk."""
    header = json.dumps(
        {
            "version": VERSION,
            "layout": layout,
            "params_fingerprint": fingerprint,
            "entries": int(n_entries),
            # Integer milliseconds: a float's JSON length varies with
            # trailing zeros, so two same-content snapshots could differ
            # in SIZE — the byte-count invariants tier-1 pins would
            # flake on the timestamp.
            "created_unix_ms": int(time.time() * 1000),
        }
    ).encode()
    return MAGIC + struct.pack("<II", VERSION, len(header)) + header


def encode_entry(layout: dict, key: tuple, rows: dict) -> bytes:
    """One ``meta | blob`` entry record (per-entry CRC32, layout-ordered
    blob).  The ONE entry encoder behind the disk snapshot, the peer
    snapshot stream, and the per-request handoff stream — the formats
    cannot drift apart because they are the same bytes."""
    _, root, tokens = key
    blob = _entry_blob(rows, layout)
    meta = json.dumps(
        {
            "root": int(root),
            "tokens": [int(t) for t in tokens],
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            "nbytes": len(blob),
        }
    ).encode()
    return struct.pack("<I", len(meta)) + meta + blob


def encode_snapshot(
    layout: dict, fingerprint: str, entries: dict[tuple, dict]
) -> Iterator[bytes]:
    """Yield the ``MAGIC | version | header | entries`` byte stream —
    one chunk for the preamble, then one chunk per entry.  The disk
    writer and the ``GET /debug/snapshot`` peer stream share this one
    encoder, so the wire format IS the file format (bit-identical,
    pinned in tier-1)."""
    yield encode_preamble(layout, fingerprint, len(entries))
    for key, rows in entries.items():
        yield encode_entry(layout, key, rows)


def _write_snapshot(
    path: str,
    layout: dict,
    fingerprint: str,
    entries: dict[tuple, dict],
    truncate_fraction: Optional[float] = None,
) -> int:
    """Write the encoded stream to a tempfile in ``path``'s directory
    and atomically rename it over ``path``.  Returns the byte size.
    ``truncate_fraction`` (the ``engine.snapshot.save`` failpoint's
    ``truncate`` mode) tears the file AFTER the rename — the on-disk
    corruption shape (atomic rename already rules out torn writes)."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".kv_arena.", dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            for chunk in encode_snapshot(layout, fingerprint, entries):
                f.write(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    size = os.path.getsize(path)
    if truncate_fraction is not None:
        keep = int(size * truncate_fraction)
        with open(path, "r+b") as f:
            f.truncate(keep)
        size = keep
    return size


def _read_exact(f, n: int) -> bytes:
    data = f.read(n)
    if len(data) != n:
        raise SnapshotError("snapshot truncated")
    return data


def read_snapshot(
    path: str, expected_layout: Optional[dict] = None,
    expected_fingerprint: Optional[str] = None,
) -> tuple[dict, list[tuple[tuple, dict, int]]]:
    """Parse + verify one snapshot file; returns (header, entries) where
    entries are ``(("prefix", root, tokens), rows, nbytes)``.  Raises
    :class:`SnapshotError` on ANY corruption, truncation, or
    layout/fingerprint mismatch — the caller degrades to cold.  The
    ``engine.snapshot.load`` failpoint: ``error`` = unreadable file,
    ``truncate[:fraction]`` reads only a prefix of the bytes."""
    hit = failpoints.fire("engine.snapshot.load")
    if hit is not None and hit.mode == "truncate":
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            data = f.read(int(size * (float(hit.arg) if hit.arg else 0.5)))
        import io

        f = io.BytesIO(data)
        return _parse_snapshot(f, expected_layout, expected_fingerprint)
    with open(path, "rb") as f:
        return _parse_snapshot(f, expected_layout, expected_fingerprint)


def _parse_snapshot(f, expected_layout, expected_fingerprint):
    if _read_exact(f, len(MAGIC)) != MAGIC:
        raise SnapshotError("bad magic")
    version, header_len = struct.unpack("<II", _read_exact(f, 8))
    if version != VERSION:
        raise SnapshotError(f"unsupported snapshot version {version}")
    try:
        header = json.loads(_read_exact(f, header_len))
    except ValueError as e:
        raise SnapshotError(f"bad header: {e}") from None
    layout = header.get("layout")
    if expected_layout is not None and layout != expected_layout:
        raise SnapshotError("layout_mismatch")
    if (
        expected_fingerprint is not None
        and header.get("params_fingerprint") != expected_fingerprint
    ):
        raise SnapshotError("params_mismatch")
    entries: list[tuple[tuple, dict, int]] = []
    for _ in range(int(header.get("entries", 0))):
        (meta_len,) = struct.unpack("<I", _read_exact(f, 4))
        try:
            meta = json.loads(_read_exact(f, meta_len))
        except ValueError as e:
            raise SnapshotError(f"bad entry meta: {e}") from None
        blob = _read_exact(f, int(meta["nbytes"]))
        if (zlib.crc32(blob) & 0xFFFFFFFF) != int(meta["crc32"]):
            raise SnapshotError("entry checksum mismatch")
        rows = _split_blob(blob, layout)
        key = ("prefix", int(meta["root"]), tuple(int(t) for t in meta["tokens"]))
        entries.append((key, rows, len(blob)))
    return header, entries


def _admit_entries(engine, entries) -> int:
    """Re-enter parsed entries through ``HostKVArena.put`` (budget
    honored) under the engine lock; the next same-prefix admission then
    restores device-side instead of recomputing."""
    restored = 0
    with engine._lock:
        for key, rows, nbytes in entries:
            engine._kv_arena.put(key, {"rows": rows}, nbytes)
            restored += 1
    return restored


# ----------------------------------------------------------- engine wiring


def save_arena_snapshot(
    engine, path: str, include_device: bool = True, trigger: str = "manual"
) -> dict:
    """Persist the engine's warm-prefix state to ``path`` (atomic).
    Meters ``tpu_engine_snapshot_saves_total{outcome}`` + the
    ``engine.snapshot.saved`` flight event; an armed
    ``engine.snapshot.save`` error failpoint (or a real I/O error)
    returns ``ok=False`` without touching the previous snapshot."""
    t0 = time.perf_counter()
    try:
        hit = failpoints.fire("engine.snapshot.save")
        truncate_fraction = None
        if hit is not None and hit.mode == "truncate":
            truncate_fraction = float(hit.arg) if hit.arg else 0.5
        with engine._lock:
            layout = snapshot_layout(engine)
            fingerprint = params_fingerprint(engine.params)
            entries = collect_entries(engine, include_device=include_device)
        size = _write_snapshot(
            path, layout, fingerprint, entries, truncate_fraction
        )
    except (failpoints.FailpointError, OSError, ValueError) as e:
        if engine.metrics:
            engine.metrics.snapshot_saves.inc(outcome="error")
        if engine.flight is not None:
            engine.flight.record(
                "engine.snapshot.save_failed", trigger=trigger, error=str(e)
            )
        return {"ok": False, "reason": str(e), "trigger": trigger}
    result = {
        "ok": True,
        "entries": len(entries),
        "bytes": size,
        "ms": round((time.perf_counter() - t0) * 1e3, 3),
        "trigger": trigger,
    }
    if engine.metrics:
        engine.metrics.snapshot_saves.inc(outcome="ok")
        engine.metrics.snapshot_bytes.set(size)
    if engine.flight is not None:
        engine.flight.record("engine.snapshot.saved", **result)
    return result


def load_arena_snapshot(engine, path: str) -> dict:
    """Rehydrate the host arena from ``path``.  Every entry re-enters
    through ``HostKVArena.put`` (budget respected), so the next
    same-prefix admission restores device-side instead of recomputing.
    ANY verification failure clears whatever was partially admitted and
    reports a clean cold start (``outcome=corrupt`` / ``layout_mismatch``
    / ``params_mismatch``); a missing file is the ordinary first boot
    (``outcome=missing``, not an error)."""
    if not os.path.exists(path):
        if engine.metrics:
            engine.metrics.snapshot_loads.inc(outcome="missing")
        return {"ok": False, "reason": "missing", "restored": 0}
    if not engine._kv_arena.enabled:
        if engine.metrics:
            engine.metrics.snapshot_loads.inc(outcome="disabled")
        return {"ok": False, "reason": "arena_disabled", "restored": 0}
    t0 = time.perf_counter()
    with engine._lock:
        expected_layout = snapshot_layout(engine)
        expected_fp = params_fingerprint(engine.params)
    try:
        header, entries = read_snapshot(path, expected_layout, expected_fp)
        restored = _admit_entries(engine, entries)
    except (failpoints.FailpointError, SnapshotError, OSError, ValueError) as e:
        reason = str(e)
        outcome = (
            reason
            if reason in ("layout_mismatch", "params_mismatch")
            else "corrupt"
        )
        # Clean cold start, never a poisoned cache: drop EVERYTHING the
        # arena holds (at startup that is exactly the partial load).
        with engine._lock:
            engine._kv_arena.clear()
        if engine.metrics:
            engine.metrics.snapshot_loads.inc(outcome=outcome)
        if engine.flight is not None:
            engine.flight.record(
                "engine.snapshot.load_failed", reason=reason, outcome=outcome
            )
        return {"ok": False, "reason": reason, "restored": 0}
    result = {
        "ok": True,
        "restored": restored,
        "bytes": engine._kv_arena.bytes,
        "ms": round((time.perf_counter() - t0) * 1e3, 3),
    }
    if engine.metrics:
        engine.metrics.snapshot_loads.inc(outcome="ok")
    if engine.flight is not None:
        engine.flight.record("engine.snapshot.loaded", **result)
    return result


# ------------------------------------------------------ peer warm join


def fetch_peer_snapshot(engine, peer: str, timeout_s: float = 30.0) -> dict:
    """Warm-join: stream ``peer``'s (``"host:port"``) live arena over
    ``GET /debug/snapshot`` and rehydrate this engine's host arena from
    it — call BEFORE first admission, exactly like
    :func:`load_arena_snapshot`.

    The joiner states its layout/params fingerprints as request headers
    so an incompatible donor refuses (409) before any snapshot bytes
    move; the body then parses through the SAME verification the disk
    path uses (per-entry CRC, full layout compare, entry count), so a
    donor dying mid-stream, a torn transfer, or a lying peer all land in
    the one degradation contract: everything partially admitted is
    dropped and the joiner cold-starts clean — never a poisoned arena.
    Meters ``tpu_engine_snapshot_fetches_total{outcome}``; the
    ``engine.snapshot.fetch`` failpoint injects dial failure (``error``)
    or a truncated read (``truncate[:fraction]``)."""
    import http.client
    import io

    if not engine._kv_arena.enabled:
        if engine.metrics:
            engine.metrics.snapshot_fetches.inc(outcome="disabled")
        return {"ok": False, "reason": "arena_disabled", "restored": 0,
                "peer": peer}
    t0 = time.perf_counter()
    with engine._lock:
        expected_layout = snapshot_layout(engine)
        expected_fp = params_fingerprint(engine.params)
    host, _, port = peer.rpartition(":")
    outcome = "corrupt"
    try:
        hit = failpoints.fire("engine.snapshot.fetch", peer=peer)
        outcome = "unreachable"  # failures below here until parse starts
        conn = http.client.HTTPConnection(
            host, int(port), timeout=timeout_s
        )
        try:
            conn.request(
                "GET",
                "/debug/snapshot",
                headers={
                    LAYOUT_HEADER: layout_fingerprint(expected_layout),
                    PARAMS_HEADER: expected_fp,
                },
            )
            resp = conn.getresponse()
            if resp.status != 200:
                outcome = "refused"
                raise SnapshotError(
                    f"peer refused snapshot: HTTP {resp.status}"
                )
            outcome = "corrupt"  # transport/parse failures from here on
            reader = resp
            if hit is not None and hit.mode == "truncate":
                data = resp.read()
                frac = float(hit.arg) if hit.arg else 0.5
                reader = io.BytesIO(data[: int(len(data) * frac)])
            header, entries = _parse_snapshot(
                reader, expected_layout, expected_fp
            )
        finally:
            conn.close()
        restored = _admit_entries(engine, entries)
    except (failpoints.FailpointError, SnapshotError, OSError, ValueError) as e:
        reason = str(e)
        if reason in ("layout_mismatch", "params_mismatch"):
            outcome = reason
        # Clean cold start, never a poisoned arena: at join time the
        # arena holds exactly the partial admit (plus any disk restore
        # the operator layered first — rebuilt by traffic, never worth
        # trusting next to a torn transfer).
        with engine._lock:
            engine._kv_arena.clear()
        if engine.metrics:
            engine.metrics.snapshot_fetches.inc(outcome=outcome)
        if engine.flight is not None:
            engine.flight.record(
                "engine.snapshot.fetch_failed",
                peer=peer, reason=reason, outcome=outcome,
            )
        return {"ok": False, "reason": reason, "outcome": outcome,
                "restored": 0, "peer": peer}
    result = {
        "ok": True,
        "peer": peer,
        "restored": restored,
        "bytes": engine._kv_arena.bytes,
        "ms": round((time.perf_counter() - t0) * 1e3, 3),
    }
    if engine.metrics:
        engine.metrics.snapshot_fetches.inc(outcome="ok")
    if engine.flight is not None:
        engine.flight.record("engine.snapshot.fetched", **result)
    return result


def donor_for(joiner: str, peers, vnodes: int = 64) -> Optional[str]:
    """The warm-up donor: the peer owning the ring segments adjacent to
    where ``joiner`` lands — i.e. the replica whose keyspace (and
    therefore whose warm prefixes) the joiner inherits most of under
    the router's consistent hashing (router/ring.py, same vnode scheme
    and hash, so this answer matches the router's remapping exactly).
    Deterministic; None when no other peer exists."""
    from ..router.ring import HashRing, _hash64

    candidates = sorted({p for p in peers if p and p != joiner})
    if not candidates:
        return None
    ring = HashRing(candidates, vnodes=vnodes)
    counts: dict[str, int] = {}
    for i in range(vnodes):
        owner = ring.lookup(_hash64(f"{joiner}#{i}".encode()))
        if owner is not None:
            counts[owner] = counts.get(owner, 0) + 1
    # Deterministic tie-break: count first, then name order.
    return max(sorted(counts), key=lambda n: counts[n])


def fleet_members(router_url: str, timeout_s: float = 5.0) -> list[str]:
    """The fleet membership as the router sees it (``GET /debug/fleet``,
    falling back to ``/debug/router`` — both carry a ``replicas`` map).
    The joiner resolves its warm-up donor from this view instead of
    needing fleet config of its own."""
    import urllib.request

    base = router_url.rstrip("/")
    if not base.startswith("http"):
        base = f"http://{base}"
    last_error: Optional[Exception] = None
    for path in ("/debug/fleet", "/debug/router"):
        try:
            with urllib.request.urlopen(base + path, timeout=timeout_s) as r:
                payload = json.loads(r.read() or b"{}")
            return sorted((payload.get("replicas") or {}).keys())
        except (OSError, ValueError) as e:
            last_error = e
    raise SnapshotError(f"fleet membership unavailable: {last_error}")
