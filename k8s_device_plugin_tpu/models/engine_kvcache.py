"""Serving-engine KV cache tiering: retained pages + host-RAM offload.

The prefix trie (engine_paging.py) only shares KV pages while some live
request still references them — ``_release_page`` frees a page the
instant its refcount hits zero, so a hot system prompt is recomputed
whenever request lifetimes don't overlap, and every preemption throws
away all generated K/V for a full recompute-resume.  This module turns
both recomputes into restores with two tiers layered UNDER the existing
page lifecycle (mixed into ServingEngine like the other engine_* files):

- **Tier 1 — retained device pages.**  When a prefix-registered page's
  refcount drops to zero it moves to an LRU "retained" set instead of
  the free pool; its trie links stay live, so a later same-prefix
  request (or the same request resuming after preemption) matches it
  through the ordinary ``_match_prefix`` walk for free.  The allocator
  reclaims retained pages lazily — LRU order, leaf-first so surviving
  chains stay walkable — and only when ``free_pages`` alone cannot
  satisfy a request, which preserves the pool's liveness guarantee
  (a retained page is always one reclaim away from being free).

- **Tier 2 — host-RAM offload.**  Before a retained page is reclaimed
  its per-layer K/V rows are copied into a bounded numpy arena
  (byte-budgeted via ``--kv-host-cache-mb``; LRU-evicted).  Arena
  entries are keyed by the CUMULATIVE token prefix the page covers —
  content-addressed, so a restore can never alias another request's
  K/V even across page-id reallocation — and a trie walk that runs
  past the device tiers continues into the arena: each hit is restored
  into a fresh device page with one sliced ``.at[pages].set`` per pool
  per layer (no new jit shapes, no recompute) and re-linked into the
  trie.

- **Preemption restore-resume.**  ``_evict_slot`` publishes the
  victim's full pages into the trie (so tier 1 retains them) and
  snapshots the partial tail page plus the tiny decode state (consumed
  length, last emitted token) under the request id.  When the victim
  reaches the queue head again, ``_kv_try_restore_resume`` rebuilds the
  slot EXACTLY as it was — pages matched from the retained tier and/or
  restored from the arena, tail rows written back, seq_lens/table row
  set — and skips prefill entirely: the next ordinary decode step feeds
  the last token at its old position, which is bit-identical to never
  having been evicted.  Any coverage gap (arena evicted the entries)
  falls back to the ordinary recompute-resume path.

Correctness bar, enforced by tests/test_engine_kvcache.py: token
streams are bit-identical with tiering on vs off (restored rows are the
bytes the original graft/appends wrote, and recompute at the same
length bucket writes the same bytes), and a freed-then-reallocated page
id is never reachable through a retained trie link (reclaim runs the
same teardown as a free, and leaf-first ordering plus the existing
parent-death child-unlink rule cover every interleaving).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class HostKVArena:
    """Bounded host-RAM store for offloaded KV pages and resume snapshots.

    One ``OrderedDict`` doubles as storage and LRU order; ``put`` evicts
    oldest-first until the byte budget holds.  Keys are content-shaped
    tuples: ``("prefix", trie_root, tokens)`` for offloaded full pages
    (shareable across requests) and ``("snap", rid)`` for a preempted
    request's private tail + decode state.  All access happens under the
    engine lock (owner thread plus locked debug readers), so the arena
    itself carries no lock.
    """

    def __init__(self, budget_bytes: int = 0):
        self.budget_bytes = int(budget_bytes)
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self.bytes = 0
        self.evictions = 0
        # Monotonic mutation counter: bumped on every put/pop/clear and
        # per eviction.  The fabric digest (engine_handoff.py) caches
        # its bloom against this + the trie version, so the cheap
        # summary poll never rebuilds an unchanged filter.
        self.version = 0

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        return key in self._entries

    def get(self, key: tuple, bump: bool = True) -> Optional[dict]:
        entry = self._entries.get(key)
        if entry is not None and bump:
            self._entries.move_to_end(key)
        return entry

    def put(self, key: tuple, entry: dict, nbytes: int) -> int:
        """Insert (or refresh) one entry; returns how many LRU entries
        the byte budget evicted to make room.  An entry larger than the
        whole budget is refused rather than wiping the arena for it."""
        if not self.enabled or nbytes > self.budget_bytes:
            return 0
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes -= old["nbytes"]
        entry = {**entry, "nbytes": int(nbytes)}
        self._entries[key] = entry
        self.bytes += entry["nbytes"]
        self.version += 1
        evicted = 0
        while self.bytes > self.budget_bytes:
            _, victim = self._entries.popitem(last=False)
            self.bytes -= victim["nbytes"]
            self.evictions += 1
            self.version += 1
            evicted += 1
        return evicted

    def prefix_keys(self) -> list[tuple]:
        """Content keys of the offloaded full-page ``("prefix", ...)``
        entries — the fabric digest's arena contribution (snapshot
        donors iterate ``_entries`` directly).  Caller holds the engine
        lock like every other arena access."""
        return [key for key in self._entries if key[0] == "prefix"]

    def pop(self, key: tuple) -> Optional[dict]:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.bytes -= entry["nbytes"]
            self.version += 1
        return entry

    def clear(self) -> None:
        self._entries.clear()
        self.bytes = 0
        self.version += 1


class KVCacheMixin:
    """Tiered KV cache lifecycle, mixed into ServingEngine.

    Hooks into the page lifecycle at exactly three seams: the
    refcount-zero branch of ``_release_page`` (retain instead of free),
    the two pool-dry points (``_admit`` and ``_ensure_frontier`` reclaim
    lazily before blocking/preempting), and ``_evict_slot``/``_admit``
    for the preemption snapshot/restore pair.  Everything here runs on
    the owner thread under the engine lock except ``kvcache_state``,
    which takes the lock itself for debug readers.
    """

    def _init_kvcache(self, kv_retain: bool, kv_host_cache_mb: float) -> None:
        if kv_host_cache_mb < 0:
            raise ValueError(
                f"kv_host_cache_mb must be >= 0, got {kv_host_cache_mb}"
            )
        self._kv_retain = bool(kv_retain)
        self._kv_arena = HostKVArena(int(kv_host_cache_mb * 1024 * 1024))  # guarded by: _lock
        # Retained tier: page id -> None, insertion order = LRU order
        # (move_to_end on retain refreshes recency).  Only refcount-zero,
        # trie-linked pages ever live here.
        self._kv_retained: "OrderedDict[int, None]" = OrderedDict()  # guarded by: _lock
        # Host-visible counters (exported via metrics when wired, and
        # through kvcache_state / the perf ledger).
        self.kv_retained_hits = 0
        self.kv_host_hits = 0
        self.kv_restores = 0  # host->device page restores
        self.kv_reclaims = 0  # retained pages returned to the free pool
        self.kv_offloads = 0  # pages copied into the host arena
        self.kv_resumes_restored = 0
        self.kv_resumes_recompute = 0
        self.kv_resume_restored_tokens = 0
        self.kv_resume_recomputed_tokens = 0

    # ------------------------------------------------------------- tier 1

    def _kv_retain_page(self, page: int) -> bool:  # caller holds: _lock
        """Refcount just hit zero: keep the page (trie links intact) when
        it is reachable — i.e. registered in the trie.  Unregistered
        pages (generation tails, orphaned by a dead parent) hold nothing
        a future request could match, so they fall through to the free
        pool.  Caller holds the lock."""
        if not self._page_keys.get(page):
            return False
        self._kv_retained[page] = None
        self._kv_retained.move_to_end(page)
        return True

    def _kv_revive(self, page: int) -> None:  # caller holds: _lock
        """A retained page was matched and re-referenced (0 -> 1): pin it
        out of the reclaimable set.  Caller holds the lock."""
        if page in self._kv_retained:
            del self._kv_retained[page]
            self.kv_retained_hits += 1
            if self.metrics:
                self.metrics.kvcache_hits.inc(tier="retained")

    def _kv_pick_reclaim(self, protect: frozenset) -> Optional[int]:
        """Oldest retained page that is not the parent of another
        retained page — leaf-first keeps surviving chains walkable for
        as long as possible (reclaiming a parent unlinks every retained
        descendant via the teardown's child-key sweep).  Falls back to
        pure LRU when every candidate parents another (cannot happen in
        a forest, but the fallback keeps reclaim total)."""
        fallback = None
        for page in self._kv_retained:
            if page in protect:
                continue
            if fallback is None:
                fallback = page
            has_retained_child = any(
                self._prefix_pages.get(key) in self._kv_retained
                for key in self._child_keys.get(page, [])
            )
            if not has_retained_child:
                return page
        return fallback

    def _kv_reclaim_page(self, page: int) -> None:  # caller holds: _lock
        """Demote one retained page: offload its rows to the host arena
        (tier 2, content-keyed) when enabled, then run the SAME teardown
        a free runs — every trie link touching the page dies, so a
        reallocated id can never be reached through a stale retained
        link.  Caller holds the lock."""
        self._kv_retained.pop(page, None)
        offloaded = self._kv_offload_page(page)
        self._teardown_page_links(page)
        del self._page_refs[page]
        self.free_pages.append(page)
        self.kv_reclaims += 1
        if self.metrics:
            self.metrics.kvcache_evictions.inc(tier="retained")
        if self.flight is not None:
            self.flight.record(
                "kvcache.evict",
                tier="retained",
                page=page,
                offloaded=offloaded,
                retained_after=len(self._kv_retained),
            )

    def _kv_reclaim(self, need: int, protect: frozenset = frozenset()) -> int:
        """Free up to ``need`` retained pages into the pool (LRU,
        leaf-first); returns how many were freed.  ``protect`` pins
        pages a caller has matched but not yet re-referenced (the
        admission shared list) so reclaim cannot free a page that is
        about to be revived.  Caller holds the lock."""
        freed = 0
        while freed < need and self._kv_retained:
            page = self._kv_pick_reclaim(protect)
            if page is None:
                break
            self._kv_reclaim_page(page)
            freed += 1
        return freed

    # ------------------------------------------------- device <-> host rows

    def _kv_pool_names(self, att: dict) -> list[str]:
        """Every per-page pool in one layer's attention cache (K/V, plus
        int8 scale pools when quant_kv is on)."""
        return [name for name in att if name.startswith("pool_")]

    def _kv_read_page_rows(self, page: int) -> dict:
        """One page's rows across every layer and pool, device -> host.
        Whole-page reads: rows past a partial tail carry garbage exactly
        like a graft's padding — masked until an append overwrites them."""
        rows: dict[str, dict[str, np.ndarray]] = {}
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            rows[name] = {
                pool: np.asarray(att[pool][page])
                for pool in self._kv_pool_names(att)
            }
        return rows

    @staticmethod
    def _kv_rows_nbytes(rows: dict) -> int:
        return sum(
            arr.nbytes for pools in rows.values() for arr in pools.values()
        )

    def _kv_write_page_rows(self, pages: list[int], rows_list: list[dict]) -> None:
        """Restore host rows into device pages: ONE page-indexed scatter
        per pool per layer (the _graft discipline — per-page eager
        ``.at`` updates would round-trip the whole pool once per page).
        Under tensor parallelism the update rows are device_put with the
        pool's own kv-heads spec BEFORE the scatter, so a sharded pool
        round-trips through the host arena without resharding churn (the
        scatter's operands agree on layout and the result keeps the
        pool's placement)."""
        idx = self._rep(jnp.asarray(pages, jnp.int32))
        if self.mesh is not None:
            from ..parallel.serving import cache_leaf_spec
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            new_att = dict(att)
            for pool in self._kv_pool_names(att):
                stacked = jnp.asarray(
                    np.stack([rows[name][pool] for rows in rows_list])
                )
                if self.mesh is not None:
                    # The contract's spec for this pool, applied to the
                    # update rows (same rank: [pages, ...] slices).
                    stacked = jax.device_put(
                        stacked,
                        jax.sharding.NamedSharding(
                            self.mesh,
                            cache_leaf_spec(
                                pool, stacked, self.tp_size, self._tp_axis
                            ),
                        ),
                    )
                new_att[pool] = att[pool].at[idx].set(stacked)
            self.cache[name]["attn"] = new_att

    # ------------------------------------------------------------- tier 2

    def _kv_page_prefix(self, page: int) -> Optional[tuple[int, tuple]]:
        """The cumulative (trie_root, tokens) prefix a registered page
        covers, recovered by walking its ancestry keys — no extra state
        to keep coherent.  None when any ancestor lost its registration
        (the page is trie-unreachable and not worth offloading)."""
        chunks: list[tuple] = []
        node = page
        for _ in range(self.paged.num_pages):
            keys = self._page_keys.get(node)
            if not keys:
                return None
            parent, chunk = keys[0]
            chunks.append(chunk)
            node = parent
            if node < 0:  # pseudo-root: -1 base model, -(2+i) adapter i
                tokens = tuple(t for c in reversed(chunks) for t in c)
                return node, tokens
        return None

    def _kv_offload_page(self, page: int) -> bool:  # caller holds: _lock
        """Copy one retained page's rows into the host arena keyed by its
        cumulative prefix; True when stored.  Caller holds the lock."""
        if not self._kv_arena.enabled:
            return False
        prefix = self._kv_page_prefix(page)
        if prefix is None:
            return False
        root, tokens = prefix
        rows = self._kv_read_page_rows(page)
        evicted = self._kv_arena.put(
            ("prefix", root, tokens), {"rows": rows}, self._kv_rows_nbytes(rows)
        )
        self.kv_offloads += 1
        if self.metrics:
            if evicted:
                self.metrics.kvcache_evictions.inc(evicted, tier="host")
        if evicted and self.flight is not None:
            self.flight.record(
                "kvcache.evict",
                tier="host",
                entries=evicted,
                host_bytes=self._kv_arena.bytes,
            )
        return True

    def _kv_match_host(
        self, eff: list[int], adapter: Optional[int], start: int, stop: int
    ) -> list[dict]:
        """Continue a trie walk into the host arena: consecutive full-page
        entries for eff's pages [start, stop), stopping at the first
        miss (a chain hole cannot be bridged — later pages' K/V depend
        on the missing positions only through content equality, which
        the cumulative key already guarantees, but a hole means the
        device page for it would be unwritten).  Returns the entries in
        page order."""
        if not self._kv_arena.enabled:
            return []
        ps = self.paged.page_size
        root = self._trie_root(adapter)
        out: list[dict] = []
        for i in range(start, stop):
            entry = self._kv_arena.get(("prefix", root, tuple(eff[: (i + 1) * ps])))
            if entry is None:
                break
            out.append(entry)
        return out

    def _kv_restore_pages(self, pages: list[int], rows_list: list[dict]) -> None:
        """Write host-held page rows into freshly allocated device pages
        and meter the restore (counter, latency histogram, flight)."""
        # The page-indexed scatter compiles per page-count shape on first
        # use: grace the hung-step deadline for this step.
        self._wd_grace("kv_restore")
        t0 = time.perf_counter()
        self._kv_write_page_rows(pages, rows_list)
        dt = time.perf_counter() - t0
        self.kv_restores += len(pages)
        self.kv_host_hits += len(pages)
        if self.metrics:
            self.metrics.kvcache_hits.inc(len(pages), tier="host")
            self.metrics.kvcache_restores.inc(len(pages))
            self.metrics.kvcache_restore_seconds.observe(dt)
        if self.flight is not None:
            self.flight.record(
                "kvcache.restore",
                pages=len(pages),
                ms=round(dt * 1e3, 3),
                host_bytes=self._kv_arena.bytes,
            )

    # -------------------------------------------- preemption snapshot/resume

    def _kv_snapshot_slot(self, slot: int, req: Any) -> bool:
        """Preemption epilogue: publish the victim's full pages into the
        trie (so _clear_slot's release RETAINS them — the device stays
        the first tier for its own resume) and snapshot the partial tail
        page plus the decode state under the request id.  True when a
        snapshot was stored (restore-resume becomes possible)."""
        if not self._kv_retain:
            return False
        if self._slot_page_base[slot]:
            return False  # windowed reclaim dropped leading pages: no full chain
        with self._lock:
            L = self._slot_len[slot]
            ps = self.paged.page_size
            n_full = L // ps
            eff = req.prompt + req.tokens
            if self.prefix_sharing and n_full:
                # Publish the full pages (prompt AND generated content)
                # into the trie even when the host arena is off: the
                # release below then retains them, and the resume's
                # ordinary prefix match rides them — a recompute-resume
                # still skips their graft writes.
                self._register_prefix(eff, self._slot_pages[slot], n_full, req.adapter)
            if not self._kv_arena.enabled:
                return False  # no tail/state snapshot -> recompute-resume
            tail = None
            nbytes = 256  # state scalars; tail rows dominate when present
            if L % ps and n_full < len(self._slot_pages[slot]):
                tail = self._kv_read_page_rows(self._slot_pages[slot][n_full])
                nbytes += self._kv_rows_nbytes(tail)
            evicted = self._kv_arena.put(
                ("snap", req.rid),
                {"len": L, "last": self._slot_last[slot], "tail": tail},
                nbytes,
            )
            if evicted and self.metrics:
                self.metrics.kvcache_evictions.inc(evicted, tier="host")
            return ("snap", req.rid) in self._kv_arena

    def _kv_drop_snapshot(self, rid: int) -> None:  # caller holds: _lock
        self._kv_arena.pop(("snap", rid))

    def _kv_try_restore_resume(self, slot: int, req: Any) -> bool:
        """Admission fast path for a preempted request at the queue head:
        rebuild the slot from the tiers and SKIP prefill entirely.

        Requires full coverage — every full page matched live/retained
        (device) or present in the arena, plus the tail snapshot — and
        enough pool pages after a lazy reclaim; anything short returns
        False and the ordinary recompute-resume path runs (restored
        pages still shrink its graft through the shared-prefix count).
        The rebuilt slot is EXACTLY the pre-eviction decode state (same
        consumed length, same pending last token), so the next decode
        step continues bit-identically to never having been evicted.
        Caller holds the lock."""
        snap = self._kv_arena.get(("snap", req.rid), bump=False)
        if snap is None:
            return False
        L = snap["len"]
        ps = self.paged.page_size
        eff = req.prompt + req.tokens
        if L + 1 != len(eff):  # stale snapshot (should not happen): recompute
            self._kv_drop_snapshot(req.rid)
            return False
        n_full = L // ps
        n_pages = n_full + 1  # content pages + the page position L writes into
        if n_pages > self.paged.max_pages_per_seq:
            return False
        bucket = min(1 << (len(eff) - 1).bit_length(), self.paged.max_len)
        shared = (
            self._match_prefix(eff, bucket, {}, req.adapter)[:n_full]
            if self.prefix_sharing
            else []
        )
        host = self._kv_match_host(eff, req.adapter, len(shared), n_full)
        if len(shared) + len(host) < n_full:
            # Arena budget evicted part of the chain: recompute-resume.
            self._kv_drop_snapshot(req.rid)
            return False
        tail = snap["tail"]
        if L % ps and tail is None:
            self._kv_drop_snapshot(req.rid)
            return False
        n_private = n_pages - len(shared)
        if n_private > len(self.free_pages):
            self._kv_reclaim(
                n_private - len(self.free_pages), protect=frozenset(shared)
            )
        if n_private > len(self.free_pages):
            return False  # pool-blocked: keep the snapshot, retry next step
        self.queue.popleft()
        req.admitted_at = time.monotonic()
        private = [self.free_pages.popleft() for _ in range(n_private)]
        pages = shared + private
        for page in shared:
            self._page_refs[page] += 1
            if self._page_refs[page] == 1:
                self._kv_revive(page)
        for page in private:
            self._page_refs[page] = 1
        restore_pages, restore_rows = [], []
        if host:
            restore_pages += private[: len(host)]
            restore_rows += [e["rows"] for e in host]
        if tail is not None:
            restore_pages.append(pages[n_full])
            restore_rows.append(tail)
        if restore_pages:
            self._kv_restore_pages(restore_pages, restore_rows)
        if self.prefix_sharing and n_full:
            self._register_prefix(eff, pages, n_full, req.adapter)
        self._kv_drop_snapshot(req.rid)

        # Slot state: the _graft/_activate table discipline without the
        # pool writes (the rows are already in place) or the admission
        # token (req.tokens already carries it — it is the pending last
        # token the next decode step feeds at position L).
        n_publish = min((L + self._spec_gamma) // ps + 1, len(pages))
        if self._derive_tables:
            full = np.zeros((self.paged.max_pages_per_seq,), np.int32)
            full[: len(pages)] = pages
            self._chain = self._chain.at[slot].set(jnp.asarray(full))
        else:
            row = np.zeros((self.paged.max_pages_per_seq,), np.int32)
            row[:n_publish] = pages[:n_publish]
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            new_att = {**att, "seq_lens": att["seq_lens"].at[slot].set(L)}
            if not self._derive_tables:
                new_att["page_table"] = (
                    att["page_table"].at[slot].set(jnp.asarray(row))
                )
            self.cache[name]["attn"] = new_att
        self.slots[slot] = req
        self._slot_pages[slot] = pages
        self._slot_page_base[slot] = 0
        self._slot_visible[slot] = n_publish
        self._slot_len[slot] = L
        self._slot_last[slot] = snap["last"]
        self._slot_seq[slot] = self._seq_counter
        self._seq_counter += 1
        self._set_slot_sampler(slot, req)
        self._slot_ready[slot] = True
        self._slot_emit_t[slot] = time.monotonic()
        self._mark_state_dirty()

        self.kv_resumes_restored += 1
        self.kv_resume_restored_tokens += L
        if self.metrics:
            self.metrics.resumes.inc(mode="restored")
            self.metrics.resume_restored_tokens.inc(L)
        if self.flight is not None:
            self.flight.record(
                "engine.resume",
                rid=req.rid,
                mode="restored",
                restored_tokens=L,
                recomputed_tokens=0,
                pages_shared=len(shared),
                pages_restored=len(restore_pages),
            )
        self._update_gauges()
        return True

    # ------------------------------------------------------------ interface

    def kvcache_clear(self) -> None:
        """Drop both tiers: reclaim every retained page into the free
        pool (no offload — the point is a clean slate) and empty the
        arena.  Benchmarks and tests use this to compare recompute vs
        restore over identical traffic; counters survive."""
        with self._lock:
            for page in list(self._kv_retained):
                self._kv_retained.pop(page, None)
                self._teardown_page_links(page)
                del self._page_refs[page]
                self.free_pages.append(page)
            self._kv_arena.clear()
            self._update_gauges()

    def kvcache_state(self) -> dict:
        """JSON-safe tier snapshot: the body of ``GET /debug/kvcache``
        and the ``kvcache`` block of ``debug_state()``."""
        with self._lock:
            return {
                "retain": self._kv_retain,
                "retained_pages": len(self._kv_retained),
                "host": {
                    "enabled": self._kv_arena.enabled,
                    "budget_bytes": self._kv_arena.budget_bytes,
                    "bytes": self._kv_arena.bytes,
                    "entries": len(self._kv_arena),
                    "evictions": self._kv_arena.evictions,
                },
                "hits": {
                    "retained": self.kv_retained_hits,
                    "host": self.kv_host_hits,
                },
                "restores": self.kv_restores,
                "reclaims": self.kv_reclaims,
                "offloads": self.kv_offloads,
                "resumes": {
                    "restored": self.kv_resumes_restored,
                    "recompute": self.kv_resumes_recompute,
                    "restored_tokens": self.kv_resume_restored_tokens,
                    "recomputed_tokens": self.kv_resume_recomputed_tokens,
                },
            }
