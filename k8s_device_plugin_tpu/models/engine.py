"""Continuous-batching serving engine over the paged KV cache.

The reference stops at mounting device nodes into a pod (reference
main.go:139-159); this is the workload-side request server that runs ON
those chips.  Design split, TPU-shaped:

- **Device side** (jitted once): a fixed-[slots] single-token decode step
  over the paged cache (models/transformer.py ``PagedConfig``) — every
  slot advances every step, idle slots compute masked garbage into the
  reserved scratch page.  Static shapes, no recompiles as requests come
  and go.
- **Host side** (this module, plain Python between steps): admission,
  page allocation/free, per-slot bookkeeping.  State edits are row-wise
  ``.at[slot].set`` updates on the cache tree — O(layers) small
  dispatches per request event, never per token.

Prefill bridges through the dense path: an admitted prompt runs the
ordinary dense-cache prefill (one MXU-shaped pass, compiled per prompt
length), and its K/V rows are grafted into the allocated pages.  Decode
then proceeds fully paged.  Page 0 is reserved as the idle-slot scratch
target: idle rows keep appending there (their page-table rows are zero
and gather indices clamp), so they can never collide with a live page.

Capacity model: a request needs ``ceil((prompt + max_new) / page_size)``
pages, allocated at admission (no mid-flight allocation → no deadlock);
requests queue when the pool is dry and admit as finished requests free
their pages — continuous batching.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.metrics import MetricsRegistry
from .transformer import GPTConfig, PagedConfig, TransformerLM, decode_cache_spec


class EngineMetrics:
    """Prometheus series for the serving engine (same registry machinery
    the plugin daemon exposes on its --metrics-port).  Pass a shared
    registry to co-expose with other subsystems, or let each engine own
    one and mount it on a utils.metrics.MetricsServer."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.requests = registry.counter(
            "tpu_engine_requests_total",
            "Requests admitted into a decode slot",
        )
        self.tokens = registry.counter(
            "tpu_engine_tokens_total", "Tokens emitted across all requests"
        )
        self.steps = registry.counter(
            "tpu_engine_steps_total", "Jitted decode steps executed"
        )
        self.active_slots = registry.gauge(
            "tpu_engine_active_slots", "Slots currently serving a request"
        )
        self.queued = registry.gauge(
            "tpu_engine_queued_requests", "Requests waiting for slots/pages"
        )
        self.free_pages = registry.gauge(
            "tpu_engine_free_pages", "Unallocated KV-cache pages"
        )
        self.shared_pages = registry.gauge(
            "tpu_engine_shared_pages",
            "Pages currently referenced by more than one request (prefix sharing)",
        )


@dataclasses.dataclass
class Request:
    """One generation request and, when finished, its output tokens.

    ``temperature`` 0 means greedy; > 0 samples that request's tokens at
    that temperature (slots mix freely in one jitted step)."""

    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    rid: int = -1
    tokens: list[int] = dataclasses.field(default_factory=list)
    done: bool = False


class ServingEngine:
    """Batch-continuous greedy decoding server (single host, one model).

    ``cfg`` is the model config WITHOUT paging; the engine derives the
    paged decode config.  ``params`` may be any serving tree the config
    accepts (bf16, or int8 via ``cfg.quant``).
    """

    def __init__(
        self,
        cfg: GPTConfig,
        params: Any,
        paged: PagedConfig,
        *,
        max_slots: int = 4,
        eos_id: Optional[int] = None,
        prefix_sharing: bool = True,
        rng: Optional[jax.Array] = None,
        metrics: Optional[EngineMetrics] = None,
    ):
        if cfg.paged is not None:
            raise ValueError("pass the base config; the engine adds paging")
        if paged.use_kernel and cfg.attention_window is not None:
            # Fail at the config boundary, not at the first jitted decode
            # step after pools were allocated and prompts prefetched.
            raise ValueError(
                "PagedConfig.use_kernel is full-causal; unset "
                "attention_window or use the gather path"
            )
        self.paged = paged
        self.cfg = dataclasses.replace(cfg, paged=paged)
        # Dense prefill bridge shares max_seq with the paged logical view.
        self.dense_cfg = dataclasses.replace(cfg, paged=None, max_seq=paged.max_len)
        self.params = params
        self.max_slots = max_slots
        self.eos_id = eos_id

        model = TransformerLM(self.cfg, decode=True)
        spec = decode_cache_spec(model, max_slots)
        self.cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), spec)
        self._layer_names = [f"layer_{i}" for i in range(cfg.num_layers)]

        @jax.jit
        def step(params, cache, tokens, positions, temps, key):
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                tokens,
                positions,
                mutable=["cache"],
            )
            row = logits[:, -1, :]
            greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
            # One categorical over the batch samples each row independently;
            # temp<=0 rows take the argmax (their scaled logits are unused).
            scaled = row / jnp.where(temps > 0, temps, 1.0)[:, None]
            sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return nxt, mut["cache"]

        self._step = step
        self._dense = TransformerLM(self.dense_cfg, decode=True)

        # Page 0 is the idle-slot scratch target — never allocated.
        self.free_pages: deque[int] = deque(range(1, paged.num_pages))
        self.slots: list[Optional[Request]] = [None] * max_slots
        self._slot_pages: list[list[int]] = [[] for _ in range(max_slots)]
        self._slot_last: list[int] = [0] * max_slots  # last emitted token
        self._slot_len: list[int] = [0] * max_slots  # consumed positions
        self._slot_temp: list[float] = [0.0] * max_slots  # 0 = greedy
        # Logical index of _slot_pages[s][0] in the device table row (> 0
        # once leading pages were reclaimed by a sliding window).
        self._slot_page_base: list[int] = [0] * max_slots
        self.queue: deque[Request] = deque()
        self._next_rid = 0
        self._prefill_cache: dict[int, Any] = {}
        self._rng = jax.random.PRNGKey(0) if rng is None else rng
        self.metrics = metrics
        # Prefix sharing: K/V are a deterministic function of (params,
        # prompt tokens), so FULL pages covering a common prompt prefix are
        # byte-identical across requests and can be shared read-only —
        # decode only ever writes at the growing frontier, which lives in a
        # private page.  The registry is a per-page trie keyed
        # (parent_page, page_chunk) — O(prompt) to match/register, vs
        # O(prompt²/page_size) for whole-prefix keys — with -1 as the root
        # parent.  Pages are refcounted and registry links die with their
        # last user (this serves the concurrent shared-system-prompt case,
        # not a persistent prompt cache; freed-parent links cannot go
        # stale: any sequence holding a child page holds its whole prefix
        # chain, so a child always dies no later than its parent).
        self.prefix_sharing = prefix_sharing
        self._page_refs: dict[int, int] = {}
        self._prefix_pages: dict[tuple[int, tuple], int] = {}
        self._page_keys: dict[int, list[tuple[int, tuple]]] = {}
        # Keys in which a page is the PARENT: windowed reclamation can free
        # a parent before its children, and a freed id may be reallocated
        # and re-registered with different content — surviving child links
        # would then form a stale chain, so they die with the parent.
        self._child_keys: dict[int, list[tuple[int, tuple]]] = {}

    # ------------------------------------------------------------- admission

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0) -> Request:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        need = len(prompt) + max_new_tokens
        if need > self.paged.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"paged max_len {self.paged.max_len}"
            )
        # Admissibility, not just addressability: the request must fit the
        # ALLOCATABLE pool (page 0 is reserved), else it would block the
        # FIFO head forever.
        allocatable = (self.paged.num_pages - 1) * self.paged.page_size
        if need > allocatable:
            raise ValueError(
                f"request needs {need} cache slots but the pool only ever "
                f"has {allocatable} ({self.paged.num_pages - 1} allocatable "
                f"pages x {self.paged.page_size})"
            )
        req = Request(prompt, max_new_tokens, temperature, rid=self._next_rid)
        self._next_rid += 1
        self.queue.append(req)
        # Scrapes happen on the MetricsServer thread: reflect queue
        # pressure immediately, not at the owner's next step().
        self._update_gauges()
        return req

    def _prefill_fn(self, bucket_len: int):
        """Jitted dense prefill for one LENGTH BUCKET, cached on THIS
        instance (a process-global lru_cache would pin the engine — params
        tree and page pools included — beyond its lifetime)."""
        fn = self._prefill_cache.get(bucket_len)
        if fn is not None:
            return fn
        spec = decode_cache_spec(self._dense, 1)

        def run(params, prompt, last_idx):
            cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), spec)
            pos = jnp.arange(bucket_len)[None, :]
            logits, mut = self._dense.apply(
                {"params": params, "cache": cache}, prompt, pos, mutable=["cache"]
            )
            # Slice the true last position INSIDE the program (last_idx is
            # a traced scalar, so one compiled program serves every length
            # in the bucket while XLA returns a single [vocab] row instead
            # of materializing [bucket, vocab]).  The sampler (greedy or
            # per-request temperature) stays the host's choice at
            # admission.
            return logits[0, last_idx], mut["cache"]

        fn = jax.jit(run)
        self._prefill_cache[bucket_len] = fn
        return fn

    def _prefill(self, prompt: list[int]):
        """Run the dense prefill at the next power-of-two length bucket.

        Padding is sound because attention is causal — positions >= plen
        cannot influence logits[plen-1] — and _graft copies only rows
        [:plen] into pages, so the padded tail's garbage K/V never leaves
        the throwaway dense cache.  Bucketing bounds the number of
        compiled prefill programs at O(log max_len) for arbitrary
        request-length mixes.
        """
        plen = len(prompt)
        bucket = min(1 << (plen - 1).bit_length(), self.paged.max_len)
        padded = prompt + [0] * (bucket - plen)
        return self._prefill_fn(bucket)(
            self.params,
            jnp.asarray(padded, jnp.int32)[None, :],
            jnp.asarray(plen - 1, jnp.int32),
        )

    def _graft(
        self,
        slot: int,
        dense_cache: Any,
        pages: list[int],
        plen: int,
        n_shared: int,
    ):
        """Scatter a prefilled dense cache's rows into the PRIVATE prompt
        pages and point the slot's table/length at the full chain — ONE
        page-indexed scatter per pool per layer (not per page: eager `.at`
        updates are copy-on-write, so per-page updates would round-trip
        the whole pool once per page).

        Shared prefix pages (the first ``n_shared``) are never rewritten:
        a concurrent request is reading them, and K/V from a prefill
        compiled at a different prompt length are not guaranteed bitwise
        identical — rewriting could perturb an in-flight generation.
        Private pages are written whole; tail slots past plen carry zeros,
        which later appends overwrite before any masked read can see
        them."""
        ps = self.paged.page_size
        n_cover = math.ceil(plen / ps)
        row = np.zeros((self.paged.max_pages_per_seq,), np.int32)
        row[: len(pages)] = pages
        lo_tok = n_shared * ps  # first private-covered token position
        n_priv_cover = n_cover - n_shared
        cover = jnp.asarray(pages[n_shared:n_cover], jnp.int32)
        pad = n_cover * ps - plen
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            src = dense_cache[name]["attn"]

            def paged_rows(slab):
                rows = slab[0, lo_tok:plen]
                if pad:
                    rows = jnp.pad(rows, ((0, pad), (0, 0), (0, 0)))
                return rows.reshape(n_priv_cover, ps, *rows.shape[1:])

            new_att = {
                **att,
                "page_table": att["page_table"].at[slot].set(jnp.asarray(row)),
                "seq_lens": att["seq_lens"].at[slot].set(plen),
            }
            if n_priv_cover > 0:
                new_att["pool_key"] = (
                    att["pool_key"].at[cover].set(paged_rows(src["cached_key"]))
                )
                new_att["pool_value"] = (
                    att["pool_value"].at[cover].set(paged_rows(src["cached_value"]))
                )
            self.cache[name]["attn"] = new_att

    def _clear_slot(self, slot: int):
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "page_table": att["page_table"].at[slot].set(0),
                "seq_lens": att["seq_lens"].at[slot].set(0),
            }
        for page in self._slot_pages[slot]:
            self._release_page(page)
        self._slot_pages[slot] = []
        self.slots[slot] = None
        self._slot_last[slot] = 0
        self._slot_len[slot] = 0
        self._slot_temp[slot] = 0.0
        self._slot_page_base[slot] = 0

    def _release_page(self, page: int) -> None:
        """Drop one reference; at zero, tear down every trie link touching
        the page (keys registered FOR it and keys in which it is the
        PARENT — a freed id can be reallocated and re-registered with
        different content, so a surviving child link would let a later
        prompt walk into another request's K/V) and return it to the
        pool.  The ONE page-free path: _clear_slot and windowed
        reclamation both come through here."""
        self._page_refs[page] -= 1
        if self._page_refs[page] > 0:
            return
        del self._page_refs[page]
        for key in self._page_keys.pop(page, []):
            self._prefix_pages.pop(key, None)
        for key in self._child_keys.pop(page, []):
            child = self._prefix_pages.pop(key, None)
            if child is not None:
                keys = self._page_keys.get(child)
                if keys and key in keys:
                    keys.remove(key)
        self.free_pages.append(page)

    def _match_prefix(self, prompt: list[int]) -> list[int]:
        """Longest chain of live registered pages whose token chunks equal
        this prompt's leading FULL pages (trie walk: O(prompt))."""
        ps = self.paged.page_size
        pages: list[int] = []
        parent = -1
        for i in range(len(prompt) // ps):
            chunk = tuple(prompt[i * ps : (i + 1) * ps])
            page = self._prefix_pages.get((parent, chunk))
            if page is None:
                break
            pages.append(page)
            parent = page
        return pages

    def _admit(self) -> list[Request]:
        """Admit queued requests into free slots; returns any that finished
        at admission already (EOS or max_new_tokens == 1 on the prefill
        token) so step() can report them."""
        finished = []
        for slot in range(self.max_slots):
            if self.slots[slot] is not None or not self.queue:
                continue
            req = self.queue[0]
            plen = len(req.prompt)
            n_pages = math.ceil(
                (plen + req.max_new_tokens) / self.paged.page_size
            )
            shared = self._match_prefix(req.prompt) if self.prefix_sharing else []
            n_private = n_pages - len(shared)
            if n_private > len(self.free_pages):
                break  # FIFO: wait for pages rather than starving the head
            self.queue.popleft()
            private = [self.free_pages.popleft() for _ in range(n_private)]
            pages = shared + private
            for page in shared:
                self._page_refs[page] += 1
            for page in private:
                self._page_refs[page] = 1
            if self.prefix_sharing:
                # Register this prompt's full pages (shared or fresh) as
                # trie links so later same-prefix requests can ride them.
                ps = self.paged.page_size
                parent = -1
                for i in range(plen // ps):
                    key = (parent, tuple(req.prompt[i * ps : (i + 1) * ps]))
                    if key not in self._prefix_pages:
                        self._prefix_pages[key] = pages[i]
                        self._page_keys.setdefault(pages[i], []).append(key)
                        if parent != -1:
                            self._child_keys.setdefault(parent, []).append(key)
                    parent = pages[i]
            last_logits, dense_cache = self._prefill(req.prompt)
            self._graft(slot, dense_cache, pages, plen, len(shared))
            self.slots[slot] = req
            self._slot_pages[slot] = pages
            if req.temperature > 0:
                self._rng, sub = jax.random.split(self._rng)
                first = int(
                    jax.random.categorical(sub, last_logits / req.temperature)
                )
            else:
                first = int(jnp.argmax(last_logits))
            req.tokens.append(first)
            self._slot_last[slot] = first
            self._slot_len[slot] = plen
            self._slot_temp[slot] = req.temperature
            if self.metrics:
                self.metrics.requests.inc()
                self.metrics.tokens.inc()
            self._maybe_finish(slot)
            if req.done:
                finished.append(req)
        return finished

    def _maybe_finish(self, slot: int):
        req = self.slots[slot]
        if req is None:
            return
        if len(req.tokens) >= req.max_new_tokens or (
            self.eos_id is not None and req.tokens and req.tokens[-1] == self.eos_id
        ):
            req.done = True
            self._clear_slot(slot)

    # ----------------------------------------------------------------- steps

    def step(self) -> list[Request]:
        """Admit what fits, advance every active slot one token; returns
        every request that finished this step (including ones done at
        admission — EOS/max_new on the prefill token)."""
        finished = self._admit()
        active = [s for s in range(self.max_slots) if self.slots[s] is not None]
        if not active:
            self._update_gauges()
            return finished
        tokens = jnp.asarray(self._slot_last, jnp.int32)[:, None]
        positions = jnp.asarray(self._slot_len, jnp.int32)[:, None]
        temps = jnp.asarray(self._slot_temp, jnp.float32)
        self._rng, sub = jax.random.split(self._rng)
        nxt, self.cache = self._step(
            self.params, self.cache, tokens, positions, temps, sub
        )
        nxt = np.asarray(nxt)
        for s in active:
            req = self.slots[s]
            tok = int(nxt[s])
            req.tokens.append(tok)
            self._slot_last[s] = tok
            self._slot_len[s] += 1
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            elif self.cfg.attention_window is not None:
                self._reclaim_windowed(s)
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(len(active))
        self._update_gauges()
        return finished

    def _reclaim_windowed(self, slot: int) -> None:
        """Free pages that scrolled fully out of a sliding attention
        window.  A query at position p sees keys in (p - window, p]; once
        every position in a page is below ``len - window`` no future query
        can see it — visibility only moves forward — so the page returns
        to the pool mid-flight (bounded cache memory for long windowed
        decodes).  Its table entry points at the scratch page: gathers of
        masked positions read garbage that the window mask discards, and
        the append frontier is always ahead of the reclaimed region."""
        window = self.cfg.attention_window
        ps = self.paged.page_size
        horizon = self._slot_len[slot] - window
        # horizon // ps = TOTAL pages ever dead for this slot; subtract the
        # already-reclaimed count (the page list is trimmed in place, so
        # reusing the total as an increment would double-free live pages —
        # caught by the windowed-oracle test).
        n_dead = max(
            0,
            min(
                horizon // ps - self._slot_page_base[slot],
                len(self._slot_pages[slot]),
            ),
        )
        if n_dead <= 0:
            return
        dead, self._slot_pages[slot] = (
            self._slot_pages[slot][:n_dead],
            self._slot_pages[slot][n_dead:],
        )
        # The logical page indices shift only in OUR bookkeeping; the
        # device table keeps absolute logical positions, so dead entries
        # are re-pointed at scratch (a sliced device update — no host
        # round-trip) rather than compacted.
        lo = self._slot_page_base[slot]
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "page_table": att["page_table"].at[slot, lo : lo + n_dead].set(0),
            }
        self._slot_page_base[slot] += n_dead
        for page in dead:
            self._release_page(page)

    def _update_gauges(self) -> None:
        if not self.metrics:
            return
        self.metrics.active_slots.set(
            sum(1 for s in self.slots if s is not None)
        )
        self.metrics.queued.set(len(self.queue))
        self.metrics.free_pages.set(len(self.free_pages))
        self.metrics.shared_pages.set(
            sum(1 for c in self._page_refs.values() if c > 1)
        )

    def run(self, requests: list[tuple[list[int], int]]) -> list[Request]:
        """Submit all, step until drained, return in submission order."""
        subs = [self.submit(p, n) for p, n in requests]
        guard = 0
        while not all(r.done for r in subs):
            self.step()
            guard += 1
            if guard > 100_000:
                raise RuntimeError("engine failed to drain")
        return subs


def main(argv: Optional[list[str]] = None) -> None:
    """In-pod serving demo/benchmark (≙ the per-family benchmark pods in
    deploy/): synthetic weights + synthetic request stream through the
    continuous-batching engine; prints one JSON summary line.

    ``k8s-pod-serve-gpt.yaml`` runs this against allocated chips; the same
    command works on any backend (tiny CPU smoke by default).
    """
    import argparse
    import json
    import sys
    import time

    from ..utils.platform import honor_jax_platforms_env
    from .benchmark import _positive_int

    # Empty JAX_PLATFORMS in a pod spec is a no-op, not a platform reset.
    honor_jax_platforms_env(
        empty_is_auto=False, log=lambda m: print(m, file=sys.stderr)
    )

    p = argparse.ArgumentParser(prog="tpu-serving-engine")
    p.add_argument("--hidden", type=_positive_int, default=512)
    p.add_argument("--layers", type=_positive_int, default=4)
    p.add_argument("--heads", type=_positive_int, default=8)
    p.add_argument("--kv-heads", type=_positive_int, default=4)
    p.add_argument("--vocab", type=_positive_int, default=32000)
    p.add_argument("--quant", choices=["w8", "w8a8"], default=None)
    p.add_argument("--page-size", type=_positive_int, default=16)
    p.add_argument("--num-pages", type=_positive_int, default=128)
    p.add_argument("--max-pages-per-seq", type=_positive_int, default=16)
    p.add_argument("--slots", type=_positive_int, default=4)
    p.add_argument("--requests", type=_positive_int, default=8)
    p.add_argument("--prompt-len", type=_positive_int, default=32)
    p.add_argument("--max-new", type=_positive_int, default=32)
    args = p.parse_args(argv)

    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_heads=args.heads,
        intermediate_size=args.hidden * 3,
        max_seq=args.page_size * args.max_pages_per_seq,
        num_kv_heads=args.kv_heads,
    )
    rng = jax.random.PRNGKey(0)
    params = TransformerLM(cfg).init(rng, jnp.zeros((1, 2), jnp.int32))["params"]
    if args.quant:
        from ..ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
        cfg = dataclasses.replace(cfg, quant=args.quant)
    paged = PagedConfig(args.page_size, args.num_pages, args.max_pages_per_seq)
    eng = ServingEngine(cfg, params, paged, max_slots=args.slots)

    # Half the stream shares a system-prompt prefix (exercises page sharing).
    common = list(range(1, args.prompt_len // 2 + 1))
    jobs = []
    for i in range(args.requests):
        tail = [(37 * i + j) % args.vocab for j in range(args.prompt_len // 2)]
        prompt = (common + tail) if i % 2 == 0 else [(11 * i + j) % args.vocab for j in range(args.prompt_len)]
        jobs.append((prompt, args.max_new))

    # Warmup: compile the fixed-slot step and EVERY distinct prompt-length
    # prefill OUTSIDE the timed region (max_new=2 forces one decode step),
    # so the JSON line reports steady-state serving throughput, not XLA
    # compilation — the same honesty rule every bench in this repo follows
    # (BASELINE.md "Measurement methodology").
    warm_lens: dict[int, list[int]] = {}
    for prompt, _ in jobs:
        warm_lens.setdefault(len(prompt), prompt)
    eng.run([(prompt, 2) for prompt in warm_lens.values()])

    t0 = time.time()
    done = eng.run(jobs)
    dt = time.time() - t0
    tokens = sum(len(r.tokens) for r in done)
    print(
        json.dumps(
            {
                "metric": "engine_decode_tokens_per_sec",
                "value": round(tokens / dt, 2),
                "unit": "tokens/sec",
                "requests": len(done),
                "slots": args.slots,
                "quant": args.quant,
                "tokens": tokens,
                "wall_s": round(dt, 2),
            }
        ),
        file=sys.stdout,
        flush=True,
    )


if __name__ == "__main__":
    main()
