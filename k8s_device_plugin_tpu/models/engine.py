"""Continuous-batching serving engine over the paged KV cache.

The reference stops at mounting device nodes into a pod (reference
main.go:139-159); this is the workload-side request server that runs ON
those chips.  Design split, TPU-shaped:

- **Device side** (jitted once): a fixed-[slots] single-token decode step
  over the paged cache (models/transformer.py ``PagedConfig``) — every
  slot advances every step, idle slots compute masked garbage into the
  reserved scratch page.  Static shapes, no recompiles as requests come
  and go.
- **Host side** (plain Python between steps): admission, page
  allocation/free, per-slot bookkeeping.  State edits are row-wise
  ``.at[slot].set`` updates on the cache tree — O(layers) small
  dispatches per request event, never per token.

Prefill bridges through the dense path: an admitted prompt runs the
ordinary dense-cache prefill (one MXU-shaped pass, compiled per prompt
length), and its K/V rows are grafted into the allocated pages.  Decode
then proceeds fully paged.  Page 0 is reserved as the idle-slot scratch
target: idle rows keep appending there (their page-table rows are zero
and gather indices clamp), so they can never collide with a live page.

Capacity model: a request needs ``ceil((prompt + max_new) / page_size)``
pages, allocated at admission (no mid-flight allocation → no deadlock);
requests queue when the pool is dry and admit as finished requests free
their pages — continuous batching.

Module layout (round-4 split; this module remains the import surface):

- engine_types.py      — ``Request``, ``EngineMetrics``
- engine_sampling.py   — top-k/top-p filter, jitted step/block builders
- engine_admission.py  — submit/cancel, batched chunked prefill, admission
- engine_paging.py     — page pool, prefix trie, frontier, reclamation
- engine_kvcache.py    — KV cache tiering: retained dead-but-valid pages
  (LRU, reclaimed lazily under pool pressure) + bounded host-RAM offload
  with restore-instead-of-recompute for repeated prefixes and
  preemption resumes
- engine_spec.py       — speculative round builders + host consumption
- here                 — ``ServingEngine`` wiring, step loop (split
  dispatch/consume halves with one decode round in flight — the
  overlapped pipeline; ``overlap_steps=0`` restores the strictly
  synchronous loop), CLI ``main``
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .engine_admission import AdmissionMixin
from .engine_handoff import HandoffMixin
from .engine_kvcache import KVCacheMixin
from .engine_paging import PagingMixin
from .engine_sampling import (  # noqa: F401  (re-export: public surface)
    _token_logprob,
    build_block_fn,
    build_step_fn,
    filter_top_k_top_p,
    variant_names,
)
from .engine_spec import SpeculativeMixin, build_spec_rounds
from .engine_types import (  # noqa: F401  (re-export: public surface)
    EngineMetrics,
    Request,
    _pow2_int,
)
from ..utils import failpoints
from ..utils.anomaly import AnomalyMonitor
from ..utils.flight import FlightRecorder
from ..utils.spans import ENGINE_TRACE, SpanRecorder
from .engine_profiler import EngineProfiler
from .transformer import (
    GPTConfig,
    PagedConfig,
    TransformerLM,
    decode_cache_spec,
)


class ServingEngine(
    AdmissionMixin, PagingMixin, KVCacheMixin, HandoffMixin, SpeculativeMixin
):
    """Batch-continuous greedy decoding server (single host, one model).

    ``MAX_BIAS``: per-request logit_bias entries are padded to this fixed
    width so they trace into the jitted step as [slots, MAX_BIAS] arrays
    (no recompiles as biased requests come and go).

    ``cfg`` is the model config WITHOUT paging; the engine derives the
    paged decode config.  ``params`` may be any serving tree the config
    accepts (bf16, or int8 via ``cfg.quant``).
    """

    MAX_BIAS = 16
    # Stop-sequence caps (OpenAI allows 4 stops; 8 is generous).  Checked in
    # submit() so the unauthenticated HTTP path can't make _hit_stop's
    # per-token Python scan unbounded.
    MAX_STOPS = 8
    MAX_STOP_LEN = 32

    def __init__(
        self,
        cfg: GPTConfig,
        params: Any,
        paged: PagedConfig,
        *,
        max_slots: int = 4,
        eos_id: Optional[int] = None,
        prefix_sharing: bool = True,
        rng: Optional[jax.Array] = None,
        metrics: Optional[EngineMetrics] = None,
        spec_gamma: int = 0,
        draft_params: Any = None,
        draft_cfg: Optional[GPTConfig] = None,
        prefill_chunk: Optional[int] = None,
        decode_block: int = 1,
        overlap_steps: int = 1,
        admission: str = "reserve",
        overload=None,
        slo=None,
        kv_retain: bool = False,
        kv_host_cache_mb: float = 0,
        role: str = "unified",
        mesh: Optional[Mesh] = None,
        tp_axis: str = "tp",
        racecheck: bool = False,
        spans: Optional[SpanRecorder] = None,
        flight: Optional[FlightRecorder] = None,
        anomaly: Optional[AnomalyMonitor] = None,
        profiler: Optional[EngineProfiler] = None,
    ):
        if cfg.paged is not None:
            raise ValueError("pass the base config; the engine adds paging")
        if spec_gamma < 0:
            raise ValueError(f"spec_gamma must be >= 0, got {spec_gamma}")
        if decode_block < 1 or (decode_block & (decode_block - 1)):
            # Power of two: the host down-buckets the block to the largest
            # power of two that fits every active slot's remaining budget,
            # so compiled block programs stay O(log decode_block).
            raise ValueError(
                f"decode_block must be a power of two >= 1, got {decode_block}"
            )
        if decode_block > 1 and spec_gamma > 0:
            # Both amortize dispatches over multi-token device rounds with
            # incompatible schedules (scan of exact steps vs draft+verify).
            raise ValueError("decode_block > 1 is not supported with spec_gamma")
        if admission not in ("reserve", "optimistic"):
            raise ValueError(
                f"admission must be 'reserve' or 'optimistic', got {admission!r}"
            )
        if overlap_steps not in (0, 1):
            raise ValueError(
                f"overlap_steps must be 0 or 1, got {overlap_steps}"
            )
        if cfg.lora_serve and spec_gamma > 0:
            # The self-draft is the same model int8-quantized, and quant is
            # mutually exclusive with LoRA (quantize after merging) — there
            # is no coherent draft for a multi-adapter batch.
            raise ValueError("lora_serve is not supported with spec_gamma")
        if prefill_chunk is not None and (
            prefill_chunk < 1 or prefill_chunk & (prefill_chunk - 1)
        ):
            # Power of two so chunks tile every power-of-two length bucket.
            raise ValueError(
                f"prefill_chunk must be a power of two, got {prefill_chunk}"
            )
        self._prefill_chunk = prefill_chunk
        if spec_gamma > 0:
            # Shared-pool speculation: the draft writes its (approximate)
            # K/V at the frontier and the verify pass overwrites those
            # same positions with exact target K/V before any later read,
            # so the draft needs NO cache of its own — but that only
            # works when both models address the pool identically, i.e.
            # same architecture (self-speculation: the draft is the same
            # model quantized, ops/quant.py).
            if draft_params is None:
                raise ValueError("spec_gamma > 0 requires draft_params")
            if draft_cfg is None:
                draft_cfg = dataclasses.replace(cfg, quant="w8")
            # Only the WEIGHT format may differ: quant_kv is part of the
            # shared pool's storage format (int8 pools + scale pools), so
            # a draft/target mismatch would have the draft writing the
            # wrong dtype into — and reading raw codes out of — the very
            # pages the target owns.
            same = dataclasses.replace(draft_cfg, quant=None) == (
                dataclasses.replace(cfg, quant=None)
            )
            if not same:
                raise ValueError(
                    "engine speculation is shared-pool self-speculation: "
                    "draft_cfg must match the target architecture and "
                    "cache format (only quant may differ)"
                )
        self._spec_gamma = spec_gamma
        self.draft_params = draft_params
        self.paged = paged
        self.cfg = dataclasses.replace(cfg, paged=paged)
        # Dense prefill bridge shares max_seq with the paged logical view.
        self.dense_cfg = dataclasses.replace(cfg, paged=None, max_seq=paged.max_len)
        self.params = params
        self.max_slots = max_slots
        self.eos_id = eos_id

        # Tensor parallelism (ISSUE 6): an explicit sharding contract for
        # the whole engine state dict (parallel/serving.py) over a 1-axis
        # ``tp`` mesh — normally built from the chips the plugin
        # allocated (parallel/mesh.mesh_from_allocation).  Params follow
        # the Megatron path rules (parallel/tensor.py), KV pools split on
        # the kv-heads axis, page tables / seq_lens / the step dict
        # replicate.  Placement happens HERE and on every _dev=None
        # rebuild (_rep), never implicitly: a rebuild that re-derived
        # placement per leaf would reshard multi-MB pools mid-serve.
        self.mesh = mesh
        self._tp_axis = tp_axis
        self.tp_size = 1
        self._rep_sharding: Optional[NamedSharding] = None
        if mesh is not None:
            axes = dict(mesh.shape)
            if tp_axis not in axes:
                raise ValueError(
                    f"engine mesh has no {tp_axis!r} axis (axes: {axes})"
                )
            self.tp_size = axes[tp_axis]
            if self.tp_size > 1 and cfg.kv_heads % self.tp_size:
                raise ValueError(
                    f"tp={self.tp_size} does not divide "
                    f"num_kv_heads={cfg.kv_heads}: KV pools shard on the "
                    "kv-heads axis — pick a tp degree dividing the kv "
                    "head count (or a config with more kv heads)"
                )
            from ..parallel.tensor import tp_param_sharding

            self._rep_sharding = NamedSharding(mesh, PartitionSpec())
            self.params = jax.device_put(
                params, tp_param_sharding(params, mesh, tp_axis)
            )
            if draft_params is not None:
                self.draft_params = jax.device_put(
                    draft_params, tp_param_sharding(draft_params, mesh, tp_axis)
                )

        model = TransformerLM(self.cfg, decode=True)
        spec = decode_cache_spec(model, max_slots)
        self.cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), spec)
        if mesh is not None:
            from ..parallel.serving import cache_sharding

            self.cache = jax.device_put(
                self.cache, cache_sharding(self.cache, mesh, tp_axis)
            )
        self._layer_names = [f"layer_{i}" for i in range(cfg.num_layers)]

        # Single-token decode steps are built lazily per (filtered,
        # want_lp) — like _block_fn — so the common greedy/temperature
        # path never compiles the top-k/top-p sort and never computes the
        # [slots, vocab] log-softmax that only logprobs requests read
        # (jit programs compile on first use: a variant that is never
        # requested costs nothing).
        #
        # The cache is donated: the engine reassigns self.cache from the
        # step's output, so the input pool buffers are dead the moment the
        # call is issued — without donation every step transiently holds
        # TWO copies of every layer's page pool in HBM (a pool sized near
        # HBM capacity would OOM at the first step) and pays a pool-sized
        # copy.  Host-side .at[slot].set bookkeeping always runs on the
        # returned tree, never the donated argument.
        self._step_fns: dict = {}
        # Decode blocks (decode_block > 1): when the engine is in pure
        # decode — no admission work, every slot past prefill — the host
        # dispatches ONE program that scans T exact single-token steps
        # (same math, T fresh subkeys), then consumes/rewinds on sync.
        # Each dispatch costs one host round-trip instead of T, which is
        # the serving bottleneck at small batch (per-step dispatch is
        # ~100us on a local TPU VM and ~90ms through this relay).  Jitted
        # per (T, filtered) lazily; T down-buckets by powers of two so at
        # most O(log decode_block) programs ever compile.
        self._decode_block = decode_block
        self._decode_model = model
        self._block_fns: dict = {}
        # ALL prefill runs through the multi-token CACHED append (the
        # speculative verifier's path): each chunk attends against the
        # K/V of every previous chunk via position masks, so a prompt can
        # be consumed across several bounded dispatches — or one.  One
        # model per LENGTH BUCKET: the throwaway dense cache is sized to
        # the bucket, not paged.max_len, so a short prompt's chunks score
        # [chunk, bucket] instead of [chunk, max_len] — up to
        # max_len/bucket x less prefill attention work in long-context
        # engines (positions past the bucket were masked anyway, so
        # outputs are identical).
        self._dense_chunk_models: dict[int, TransformerLM] = {}

        if spec_gamma > 0:
            draft_model = TransformerLM(
                dataclasses.replace(draft_cfg, paged=paged), decode=True
            )
            self._spec_round, self._spec_round_plain = build_spec_rounds(
                model, draft_model, self._layer_names, spec_gamma
            )
        # Host-visible speculation counters (also exported via metrics):
        # acceptance rate = accepted / proposed, the gamma-tuning signal.
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Optimistic admission: allocate prompt pages only at admission and
        # grow generation pages on demand; a pool shortage preempts the
        # NEWEST ready slot (recompute-resume via the effective prompt).
        self._optimistic = admission == "optimistic"
        self.preemptions = 0
        self._seq_counter = 0
        # Set by each _admit pass; read by the decode-block gate.
        self._admit_page_blocked = False

        # In-program table derivation (non-speculative engines): the full
        # allocated page chain lives in ONE [slots, max_pages_per_seq]
        # device array, and the jitted step computes the visible prefix
        # from it (engine_sampling._derived_tables) — no per-layer host
        # publication scatters, and graft/teardown/reclaim edit one array
        # instead of num_layers cache tables.  Speculative engines keep
        # host-published cache tables (their round programs read the
        # table as carried cache state).
        self._derive_tables = spec_gamma == 0
        self._chain = self._rep(
            jnp.zeros((max_slots, paged.max_pages_per_seq), jnp.int32)
        )
        # Page 0 is the idle-slot scratch target — never allocated.
        self.free_pages: deque[int] = deque(range(1, paged.num_pages))  # guarded by: _lock
        self.slots: list[Optional[Request]] = [None] * max_slots  # guarded by: _lock
        self._slot_pages: list[list[int]] = [[] for _ in range(max_slots)]
        self._slot_last: list[int] = [0] * max_slots  # last emitted token
        self._slot_len: list[int] = [0] * max_slots  # consumed positions
        self._slot_temp: list[float] = [0.0] * max_slots  # 0 = greedy
        # Per-slot adapter id (-1 = base model); traced into the step so
        # slots switch adapters with no recompile (multi-LoRA serving).
        self._slot_aid: list[int] = [-1] * max_slots
        # Per-slot sampler restrictions; vocab / 1.0 mean "off" so idle
        # slots are no-ops in the shared filter.
        self._slot_topk: list[int] = [cfg.vocab_size] * max_slots
        self._slot_topp: list[float] = [1.0] * max_slots
        # Per-slot sparse logit bias: up to MAX_BIAS (id, value) pairs,
        # padded with (0, 0.0) — a zero bias is a no-op whatever the id.
        self._slot_bias_ids: list[list[int]] = [
            [0] * self.MAX_BIAS for _ in range(max_slots)
        ]
        self._slot_bias_vals: list[list[float]] = [
            [0.0] * self.MAX_BIAS for _ in range(max_slots)
        ]
        # Logical index of _slot_pages[s][0] in the device table row (> 0
        # once leading pages were reclaimed by a sliding window).
        self._slot_page_base: list[int] = [0] * max_slots
        # Logical page count PUBLISHED to the device table per slot.  The
        # full allocated chain includes not-yet-written generation pages;
        # publishing those at admission would make the kernel's pipeline
        # fetch them every step (pl.when gates compute, not the block
        # copies), so table entries stay at scratch page 0 until the write
        # frontier reaches them — per-row traffic is O(len), not
        # O(allocated).
        self._slot_visible: list[int] = [0] * max_slots
        self._slot_seq: list[int] = [0] * max_slots
        # A reserved slot decodes only after its prefill job grafted it
        # (chunked prefill spans several step() calls; until ready the
        # slot behaves exactly like an idle one in the jitted step).
        self._slot_ready: list[bool] = [False] * max_slots
        self._pending: list[dict] = []  # in-flight prefill jobs
        # Private pages of not-yet-grafted requests: the prefix-sharing
        # match refuses them (see _match_prefix) until _activate removes
        # them post-graft.
        self._pending_pages: set[int] = set()
        self.queue: deque[Request] = deque()  # guarded by: _lock
        # submit() is documented callable from other threads (the serving
        # topology: an RPC handler enqueues while the owner thread loops
        # step(), and MetricsServer scrapes concurrently) — the queue and
        # gauge updates are the shared state, so both sides take this lock.
        # Reentrant: submit() updates gauges while already holding it.
        self._lock = threading.RLock()
        self._next_rid = 0
        self._prefill_cache: dict[int, Any] = {}
        self._rng = self._rep(jax.random.PRNGKey(0) if rng is None else rng)
        # Device-resident step state: the per-slot arrays the jitted step
        # consumes (tokens/positions/temps/aids/filters/biases/key) live
        # on device between steps, with tokens/positions/key fed forward
        # from the previous step's OUTPUTS.  Rebuilt from the host lists
        # only when slot structure changes (_mark_state_dirty: admission,
        # teardown, speculative rounds) — in steady-state decode a step
        # costs ZERO host->device uploads and no separate key-split
        # dispatch, which is what matters on a real TPU VM where device
        # step time (~100us) is comparable to one transfer.
        self._dev: Optional[dict] = None
        # Overlapped decode pipeline: with overlap_steps == 1 the loop
        # dispatches step N+1 from the fed-forward device state BEFORE
        # consuming step N's readback, so per-token host work (EOS/stop
        # checks, frontier extension, metrics) executes while the
        # accelerator computes the next step instead of idling through
        # it.  ``_inflight`` holds the pending dispatch's record; its
        # validity token is the identity of the device-state dict it fed
        # forward (any _mark_state_dirty breaks it — see _take_inflight).
        # Speculative engines never overlap: a round's host consumption
        # DECIDES the next dispatch's inputs (data-dependent acceptance),
        # so there is nothing to dispatch ahead.
        self._overlap_steps = 0 if spec_gamma else overlap_steps
        self._inflight: Optional[dict] = None
        self.overlap_hits = 0
        self.overlap_discards = 0
        self._inflight_guard = None
        self.metrics = metrics
        if metrics:
            metrics.tp_size.set(self.tp_size)
        # Forensics layer (always on — a production incident cannot ask
        # for instrumentation retroactively, and all three pieces are
        # stdlib-cheap): a bounded flight-recorder black box of typed
        # events, an EWMA anomaly monitor emitting incident records with
        # the surrounding flight window attached (GET /debug/incidents),
        # and a per-step phase profiler (GET /debug/profile).  Callers
        # may pass shared/preconfigured instances (the serving main
        # registers the flight box for SIGUSR2 dumps).
        self.flight = (
            flight
            if flight is not None
            else FlightRecorder(capacity=1024, name="engine")
        )
        if anomaly is None:
            anomaly = AnomalyMonitor(
                flight=self.flight,
                on_incident=(
                    (lambda m: metrics.incidents.inc(metric=m))
                    if metrics
                    else None
                ),
            )
        self.anomaly = anomaly
        # configure() is get-or-create: a caller-preconfigured monitor
        # keeps its thresholds.  Step time warms over ~2 windows of
        # steady decode; one-sided high (fast steps are never incidents).
        self.anomaly.configure(
            "engine.step_seconds", warmup=50, z_threshold=6.0, sustain=3
        )
        self.anomaly.configure(
            "engine.ttft_seconds", warmup=20, z_threshold=6.0, sustain=2
        )
        # Split-K paged-attention kernel routing (ops/paged_attention.py,
        # ops/tuning.py): resolve the config's tri-state ONCE, export it,
        # and surface the two ctor-time fallback decisions an operator
        # would otherwise discover in a profile — a kernel-on spec engine
        # still gathers for its multi-token verify pass (single-token
        # draft/decode steps keep the kernel), and a kernel-on engine on
        # an unswept TPU generation runs the conservative fallback split
        # row until a hardware round records a real one.
        self.kernel_on = paged.kernel_enabled(cfg.quant_kv)
        if metrics:
            metrics.kernel_enabled.set(int(self.kernel_on))
        if self.kernel_on:
            from ..ops import tuning as _kernel_tuning

            fallback = None
            if spec_gamma > 0:
                fallback = "spec_verify"
            elif (
                jax.default_backend() == "tpu"
                and not _kernel_tuning.has_row()
            ):
                fallback = "untuned_generation"
            if fallback is not None:
                if metrics:
                    metrics.kernel_fallbacks.inc(reason=fallback)
                self.flight.record(
                    "kernel.fallback",
                    reason=fallback,
                    generation=_kernel_tuning.device_generation(),
                    splits=paged.kernel_num_splits,
                )
        self.profiler = (
            profiler
            if profiler is not None
            else EngineProfiler(
                flight=self.flight,
                observe_step=lambda s: self.anomaly.observe(
                    "engine.step_seconds", s
                ),
            )
        )
        self._prof_timer = None
        self._step_tokens = 0  # tokens emitted by the step in flight
        # Hung-step watchdog (models/engine_watchdog.py), installed by
        # the serving server (EngineServer wires it to its fence path).
        # The engine only feeds it: step start/finish stamps plus grace
        # marks on legitimately-slow events (new jitted program built,
        # prefill advanced, admission activated) so first-shape compiles
        # never false-trip.  None = off, zero cost.
        self.watchdog = None
        # Overload control (models/engine_overload.py): deadline expiry,
        # priority + per-tenant-fair admission order, and the AIMD
        # concurrency limiter.  Library default OFF (``overload=None`` —
        # the queue stays strictly FIFO and streams are bit-identical to
        # every prior round); the serving CLIs default it ON, matching
        # the kv-retain convention.  Pass True for the default config or
        # an OverloadConfig for tuned thresholds.
        self.overload = None
        if overload:
            from .engine_overload import OverloadConfig, OverloadController

            self.overload = OverloadController(
                max_slots,
                overload if isinstance(overload, OverloadConfig) else None,
                metrics=metrics,
                flight=self.flight,
            )
        # SLO accounting (utils/slo.py, ISSUE 16): per-request SLI
        # verdicts (TTFT / per-request ITL p99 / availability) into
        # sliding-window error budgets, plus per-tenant usage meters.
        # Library default OFF like overload (``slo=None`` — zero cost);
        # the serving CLIs default it ON.  Pass True for the default
        # objectives, a dict of threshold overrides
        # (``{"ttft_target_s": ..., "itl_p99_target_s": ...}``), or a
        # prebuilt SLOTracker.  Both mutate only under the engine lock.
        self.slo = None
        self.usage = None
        if slo:
            from ..utils.slo import SLOTracker, UsageMeter, default_objectives

            if isinstance(slo, SLOTracker):
                self.slo = slo
            elif isinstance(slo, dict):
                self.slo = SLOTracker(objectives=default_objectives(**slo))
            else:
                self.slo = SLOTracker()
            self.usage = UsageMeter()
        # Request-scoped tracing (utils/spans.py): None = off, zero cost.
        # Per-slot monotonic stamp of the slot's last emitted token — the
        # inter-token-latency anchor (reset at activation and teardown).
        self.spans = spans
        self._slot_emit_t: list[float] = [0.0] * max_slots
        # Prefix sharing: K/V are a deterministic function of (params,
        # prompt tokens), so FULL pages covering a common prompt prefix are
        # byte-identical across requests and can be shared read-only —
        # decode only ever writes at the growing frontier, which lives in a
        # private page.  The registry is a per-page trie keyed
        # (parent_page, page_chunk) — O(prompt) to match/register, vs
        # O(prompt²/page_size) for whole-prefix keys — with -1 as the root
        # parent.  Pages are refcounted and registry links die with their
        # last user (this serves the concurrent shared-system-prompt case,
        # not a persistent prompt cache; freed-parent links cannot go
        # stale: any sequence holding a child page holds its whole prefix
        # chain, so a child always dies no later than its parent).
        self.prefix_sharing = prefix_sharing
        self._page_refs: dict[int, int] = {}
        self._prefix_pages: dict[tuple[int, tuple], int] = {}
        self._page_keys: dict[int, list[tuple[int, tuple]]] = {}
        # Keys in which a page is the PARENT: windowed reclamation can free
        # a parent before its children, and a freed id may be reallocated
        # and re-registered with different content — surviving child links
        # would then form a stale chain, so they die with the parent.
        self._child_keys: dict[int, list[tuple[int, tuple]]] = {}
        # Trie mutation counter (register/teardown bump it): the fabric
        # digest cache (engine_handoff.py) keys on this + the arena
        # version so an unchanged trie never rebuilds the bloom.
        self._trie_version = 0  # guarded by: _lock
        # KV cache tiering (engine_kvcache.py): with kv_retain, a
        # prefix-registered page whose refcount hits zero is RETAINED
        # (trie links live, reclaimed lazily under pool pressure)
        # instead of freed, and kv_host_cache_mb > 0 adds the bounded
        # host-RAM arena that reclaimed pages and preemption snapshots
        # spill into — repeated prefixes and preemption resumes then
        # restore instead of recomputing.  Library default OFF (the
        # exact-pool accounting other subsystems and tests rely on);
        # the serving CLIs default it ON.
        self._init_kvcache(kv_retain, kv_host_cache_mb)
        # Disaggregated prefill/decode roles (models/engine_handoff.py):
        # "unified" (default) is today's engine byte-for-byte; "prefill"
        # serves POST /v1/prefill probes and publishes finished pages
        # into the content-addressed arena; "decode" restores handed-off
        # prefixes and SKIPS the prefill chunks they cover.
        self._init_handoff(role)
        if racecheck:
            # Lock-discipline detection (utils/racecheck.py): every
            # mutation of the cross-thread state must hold the engine
            # lock, and with this flag a violation RAISES at the faulty
            # call site instead of corrupting state probabilistically.
            # The stress suites run with it on; production engines skip
            # the per-op check.
            from ..utils.racecheck import GuardedDeque, GuardedDict, OwnerGuard

            # The in-flight overlap record is owner-thread-only by
            # contract (the step loop dispatches and consumes it while
            # submit/cancel mutate slots under the lock); the guard
            # raises if any other thread touches the handoff off-lock.
            self._inflight_guard = OwnerGuard(
                lock=self._lock, name="_inflight"
            )
            self.free_pages = GuardedDeque(
                self.free_pages, lock=self._lock, name="free_pages"
            )
            self.queue = GuardedDeque(
                self.queue, lock=self._lock, name="queue"
            )
            self._page_refs = GuardedDict(
                self._page_refs, lock=self._lock, name="_page_refs"
            )

    def _dense_chunk_model(self, bucket: int) -> TransformerLM:
        """The cached-append prefill model for one length bucket (cache
        sized to the bucket; see __init__ note).  Cached per bucket —
        O(log max_len) instances ever exist."""
        model = self._dense_chunk_models.get(bucket)
        if model is None:
            self._wd_grace(f"compile:prefill_bucket_{bucket}")
            model = TransformerLM(
                dataclasses.replace(self.dense_cfg, max_seq=bucket),
                decode=True,
                append_mode="cached",
            )
            self._dense_chunk_models[bucket] = model
        return model

    def _wd_grace(self, reason: str) -> None:
        """Mark the in-flight step as legitimately slow for the hung-step
        watchdog (a fresh XLA compile or admission/prefill work may run
        orders of magnitude past the decode baseline).  No-op without a
        watchdog installed."""
        if self.watchdog is not None:
            self.watchdog.note_grace(reason)

    # ----------------------------------------------------------------- steps

    def _rep(self, x):
        """Place one host-built array REPLICATED on the engine mesh
        (identity off-mesh).  Every fresh device array the host feeds the
        jitted step — state rebuilds, seq_lens realigns, the PRNG key —
        goes through here, so a ``_dev=None`` rebuild re-applies the
        sharding contract instead of re-deriving placement (an unplaced
        single-device array under a donated sharded step would reshard
        every dispatch)."""
        if self._rep_sharding is None:
            return x
        return jax.device_put(x, self._rep_sharding)

    def assert_sharded(self) -> int:
        """Sharding-coverage lint (parallel/serving.py): every leaf of
        the engine state dict — params, cache, chain, and the
        device-resident step dict when built — must carry an explicit
        placement on the engine mesh, and KV pools must actually be
        partitioned (no silent replication of multi-MB pools).  Raises
        AssertionError naming the offending path; returns the leaf count
        checked.  Meaningless without a mesh."""
        if self.mesh is None:
            raise ValueError(
                "engine has no mesh: build it with mesh= to lint sharding"
            )
        from ..parallel.serving import assert_explicit_sharding

        tree: dict = {
            "params": self.params,
            "cache": self.cache,
            "chain": self._chain,
            "rng": self._rng,
        }
        if self._dev is not None:
            tree["dev"] = {
                k: v for k, v in self._dev.items() if isinstance(v, jax.Array)
            }
        return assert_explicit_sharding(
            tree, self.mesh, tp_axis=self._tp_axis
        )

    def _mark_state_dirty(self) -> None:
        """Invalidate the device-resident step state: the next dispatch
        rebuilds every per-slot array from the host lists.  Called on any
        event that changes a slot's scalars (activation, teardown) or
        moves lengths by a data-dependent amount (speculative rounds)."""
        self._dev = None

    def _device_state(self) -> dict:
        """The per-slot arrays the next dispatch consumes, on device.
        Fresh-built from host truth when dirty; otherwise whatever the
        previous step fed forward (tokens/positions/key) plus the cached
        uploads (temps/aids/filters/biases, which only change via dirty
        events)."""
        dev = self._dev
        if dev is None:
            if self.metrics:
                self.metrics.state_rebuilds.inc()
            self._rng, sub = jax.random.split(self._rng)
            # _rep: the rebuild re-applies the sharding contract (mesh
            # engines replicate these per-slot vectors explicitly; the
            # no-mesh path is identity).
            dev = self._dev = {
                "tokens": self._rep(
                    jnp.asarray(self._slot_last, jnp.int32)[:, None]
                ),
                "positions": self._rep(
                    jnp.asarray(self._slot_len, jnp.int32)[:, None]
                ),
                "temps": self._rep(jnp.asarray(self._slot_temp, jnp.float32)),
                "aids": self._rep(jnp.asarray(self._slot_aid, jnp.int32)),
                "key": self._rep(sub),
            }
            # Step-variant selector flags ride the state dict: they are a
            # function of the occupied slots' sampler settings, which only
            # ever change through an activation/teardown — events that
            # dirty the whole state — so ONE slot scan per rebuild
            # replaces three full-slot scans per step in the hot loop.
            filtered = want_lp = biased = False
            for s in range(self.max_slots):
                req = self.slots[s]
                if req is None:
                    continue
                if (
                    self._slot_topk[s] < self.cfg.vocab_size
                    or self._slot_topp[s] < 1.0
                ):
                    filtered = True
                if req.logprobs:
                    want_lp = True
                if req.logit_bias:
                    biased = True
            dev["filtered"] = filtered
            dev["want_lp"] = want_lp
            dev["biased"] = biased
        return dev

    def _feed_forward(self, dev: dict, tokens, positions, key) -> dict:
        """Install the step's returned next-inputs as the new device
        state (flags and cached variant arrays carry over).  Runs BEFORE
        host consumption: a finish in consumption tears the slot down
        through _clear_slot, which marks the state dirty again —
        ordering keeps both paths correct.  Returns the installed dict
        (the overlap pipeline's in-flight validity token)."""
        self._dev = {
            **dev, "tokens": tokens, "positions": positions, "key": key,
        }
        return self._dev

    def _variant_arrays(self, dev: dict, filtered: bool, biased: bool) -> list:
        """The optional per-slot arrays matching
        engine_sampling.variant_names.  Built lazily into the device
        state on first need: a greedy-only server rebuilds its state on
        every admission/finish, and uploading filter/bias arrays no
        compiled variant consumes would defeat the variant-signature
        split (engine_sampling.py).  Safe to cache: any change to a
        slot's sampler settings rides an activation/teardown, which
        marks the whole state dirty."""
        arrays = []
        if filtered:
            if "topks" not in dev:
                dev["topks"] = self._rep(
                    jnp.asarray(self._slot_topk, jnp.int32)
                )
                dev["topps"] = self._rep(
                    jnp.asarray(self._slot_topp, jnp.float32)
                )
            arrays += [dev["topks"], dev["topps"]]
        if biased:
            if "bias_ids" not in dev:
                dev["bias_ids"] = self._rep(
                    jnp.asarray(self._slot_bias_ids, jnp.int32)
                )
                dev["bias_vals"] = self._rep(
                    jnp.asarray(self._slot_bias_vals, jnp.float32)
                )
            arrays += [dev["bias_ids"], dev["bias_vals"]]
        return arrays

    def _step_fn(self, filtered: bool, want_lp: bool, biased: bool = False):
        """The jitted single-token decode step, built lazily once per
        (filtered, want_lp, biased) — engine_sampling.build_step_fn —
        and cached on THIS instance (a process-global cache would pin
        params/pools beyond the engine's lifetime)."""
        key_ = (filtered, want_lp, biased)
        if key_ not in self._step_fns:
            self._wd_grace("compile:step")
            self._step_fns[key_] = build_step_fn(
                self._decode_model, filtered, want_lp, biased,
                derive_tables=self._derive_tables,
            )
        return self._step_fns[key_]

    def _block_fn(self, T: int, filtered: bool, want_lp: bool, biased: bool = False):
        """The jitted T-step decode block, built lazily once per
        (T, filtered, want_lp, biased) — engine_sampling.build_block_fn."""
        key_ = (T, filtered, want_lp, biased)
        if key_ not in self._block_fns:
            self._wd_grace(f"compile:block_{T}")
            self._block_fns[key_] = build_block_fn(
                self._decode_model, T, filtered, want_lp, biased,
                derive_tables=self._derive_tables,
            )
        return self._block_fns[key_]

    def _chain_args(self) -> list:
        """The chain operand for derive-tables step variants (leading
        entry of the *rest signature; empty for speculative engines)."""
        return [self._chain] if self._derive_tables else []

    # --------------------------------------------- overlapped decode pipeline
    #
    # The loop's split dispatch/consume halves.  State machine, per
    # step() call on the decode path:
    #
    #   no in-flight   -> dispatch N; if overlap allowed, dispatch N+1
    #                     from N's fed-forward state; consume N.
    #   valid in-flight-> dispatch N+1 from its fed-forward state FIRST
    #                     (keep the device busy), then consume N while
    #                     N+1 computes (the host_gap profiler phase).
    #   stale in-flight-> discard (one wasted lane): any event that calls
    #                     _mark_state_dirty (admission, finish, cancel,
    #                     preemption, spec round) invalidated the inputs
    #                     it was dispatched from.  A torn-down slot
    #                     already behaves as idle in the jitted step and
    #                     discarded K/V writes are overwritten before any
    #                     masked read can see them, so the only device
    #                     state a discard must repair is seq_lens (the
    #                     paged append writes at the CARRIED seq_lens,
    #                     not the traced positions) — one vector write
    #                     per layer back to host truth.

    def _overlap_allowed(self) -> bool:
        """Whether dispatching one decode round ahead of host consumption
        pays off right now.  Overlap is guaranteed-wasted work whenever
        the queue head could actually admit this step (the activation
        would invalidate the in-flight dispatch — the same reasoning as
        the decode-block gate) or while a chunked prefill is streaming
        in (its activation lands within a few steps), so those degrade
        to the synchronous loop."""
        if not self._overlap_steps or self._pending:
            return False
        return (
            not self.queue
            or all(s is not None for s in self.slots)
            or self._admit_page_blocked
        )

    def _guard_inflight(self, op: str) -> None:
        if self._inflight_guard is not None:
            self._inflight_guard.check(op)

    def _dispatch_decode(self, active: list[int], T: int = 1) -> dict:
        """Enqueue one decode dispatch (a single step, or a T-step block)
        from the current device state and install its fed-forward outputs
        as the new state.  Returns the record consumption needs: the
        packed readback handle, the want_lp flag it was compiled with,
        and (slot, request) pairs pinned at dispatch time so a consumer
        can skip lanes whose slot was evicted between dispatch and sync.
        ``dev`` in the record is the state dict this dispatch installed —
        identity-compared against self._dev at consume time, which makes
        it the in-flight validity token (every _mark_state_dirty breaks
        the identity)."""
        self._guard_inflight("dispatch")
        dev = self._device_state()
        filtered, want_lp, biased = (
            dev["filtered"], dev["want_lp"], dev["biased"],
        )
        fn = (
            self._step_fn(filtered, want_lp, biased)
            if T == 1
            else self._block_fn(T, filtered, want_lp, biased)
        )
        out, ff_tok, ff_pos, ff_key, self.cache = fn(
            self.params, self.cache, dev["tokens"], dev["positions"],
            dev["temps"], dev["aids"], dev["key"],
            *self._chain_args(),
            *self._variant_arrays(dev, filtered, biased),
        )
        return {
            "T": T,
            "out": out,
            "want_lp": want_lp,
            "active": list(active),
            "reqs": [self.slots[s] for s in active],
            "dev": self._feed_forward(dev, ff_tok, ff_pos, ff_key),
        }

    def _take_inflight(self, T: int) -> Optional[dict]:
        """Pop the in-flight record if it is still consumable: nothing
        invalidated the device state it fed forward (dev identity) and
        the loop is consuming the same dispatch shape it carries (T).
        Anything else discards it — one wasted lane."""
        inflight = self._inflight
        if inflight is None:
            return None
        self._guard_inflight("consume")
        self._inflight = None
        if self._dev is None or inflight["dev"] is not self._dev:
            self._discard(inflight, "state_dirty")
            return None
        if inflight["T"] != T:
            self._discard(inflight, "shape_switch")
            return None
        return inflight

    def _discard(self, inflight: dict, reason: str) -> None:
        """Throw away an overlapped dispatch.  Its K/V writes are
        harmless (a torn-down slot's pages are overwritten by the next
        owner before any visible read; a surviving slot's re-dispatch
        overwrites position L with identical rows), but the dispatch
        advanced every row's carried seq_lens past host truth — re-align
        in one vector write per layer.  A FRESH array per layer: sharing
        one would hand the next dispatch's donation the same buffer
        twice, which XLA rejects (see the identical note in _spec_step).
        The fed-forward state derives from outputs the host never
        consumed, so it is dropped too: the next dispatch rebuilds from
        the host lists."""
        self._dev = None
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "seq_lens": self._rep(jnp.array(self._slot_len, jnp.int32)),
            }
        self.overlap_discards += 1
        if self.metrics:
            self.metrics.overlap_discards.inc()
        if self.flight is not None:
            self.flight.record(
                "overlap.discard",
                reason=reason,
                T=inflight["T"],
                slots=len(inflight["active"]),
            )

    def _drop_stale_inflight(self, reason: str) -> None:
        """Discard the pending overlap dispatch (if any) after a dirty
        event that its consumption itself caused (finish/cancel found
        during consume)."""
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            self._guard_inflight("discard")
            self._discard(inflight, reason)

    @staticmethod
    def _unpack(rec: dict):
        """Split a record's packed device→host readback (ONE transfer —
        engine_sampling packs tokens with logprobs as float32 rows when
        a slot asked, and ships the token vector alone otherwise)."""
        # Chaos seam (docs/chaos.md): delay stalls the readback sync —
        # the injected step-time blowup the engine.step_seconds anomaly
        # detector must catch; error escapes step() and kills the owner
        # loop (the engine-death shape: /healthz flips 503); corrupt
        # flips bytes of the synced token buffer IN PLACE — the stream
        # keeps flowing with wrong tokens, the silent-data-corruption
        # ground truth the canary prober's bit-exactness verdict is
        # scored against.  Disarmed cost is one dict truthiness check
        # per step.
        hit = failpoints.fire("engine.readback")
        arr = np.asarray(rec["out"])
        if rec["want_lp"]:
            toks, lps = arr[0].astype(np.int64), arr[1]
        else:
            toks, lps = arr, None
        if hit is not None and hit.mode == "corrupt":
            # Flip nbytes low-order bytes of the token buffer (int64
            # little-endian: byte 0 is token 0's LSB, so 1 byte = one
            # off-by-one wrong token) — applied AFTER any logprob
            # unpack so the flip always lands on token integers, never
            # rounds away in a float conversion.
            nbytes = int(hit.arg) if hit.arg else 1
            toks = np.array(toks, dtype=np.int64)
            flat = toks.view(np.uint8).reshape(-1)
            flat[: max(1, min(nbytes, flat.size))] ^= 0x01
        return toks, lps

    def _record_hit(self) -> None:
        self.overlap_hits += 1
        if self.metrics:
            self.metrics.overlap_hits.inc()

    def _block_room(self, active: list[int]) -> int:
        """Smallest remaining token budget over the active slots — the
        bound on how many tokens any dispatch chain may run ahead."""
        return min(
            self.slots[s].max_new_tokens - len(self.slots[s].tokens)
            for s in active
        )

    def _block_step(
        self, active: list[int], finished: list[Request], T: int
    ) -> list[Request]:
        """Advance every active slot up to T tokens in ONE dispatch (the
        pure-decode fast path of step()).  A slot that hits EOS/max_new
        mid-block wastes its tail iterations (their K/V writes land past
        the row's final length and are masked forever after the rewind —
        the speculative round's exact discipline); everything the host
        consumes is identical to T single steps.  With overlap on, the
        NEXT block is dispatched before this one's readback (gated on
        room >= 2T so the overlapped block cannot overrun any slot's
        budget) — same state machine as the single-step pipeline."""
        overlap = self._overlap_allowed() and self._block_room(active) >= 2 * T
        rec = self._take_inflight(T)
        if rec is None:
            # Cold (or just-invalidated) pipeline: the frontier ensure
            # covers this block's writes — and the overlapped block's
            # too (lookahead 2T-1) when one will follow.
            active = self._ensure_frontier(
                active, 2 * T - 1 if overlap else T - 1
            )
            if not active:
                self._update_gauges()
                return finished
            rec = self._dispatch_decode(active, T)
            if overlap:
                self._inflight = self._dispatch_decode(active, T)
            self._mark("dispatch")
        else:
            self._record_hit()
            if overlap:
                active = self._ensure_frontier(active, 2 * T - 1)
                # An eviction inside the ensure dirtied the state: then
                # this step consumes what it has and re-primes next call.
                if active and self._dev is rec["dev"]:
                    self._inflight = self._dispatch_decode(active, T)
            self._mark("dispatch")
        return self._consume_block(rec, finished)

    def _consume_block(
        self, rec: dict, finished: list[Request]
    ) -> list[Request]:
        """Host half of one decode block: sync the packed readback, then
        per-slot consumption — under overlap this work executes while
        the next block computes on device (the host_gap phase)."""
        T = rec["T"]
        toks, lps = self._unpack(rec)
        self._mark("readback")
        now = time.monotonic()
        emitted_total = 0
        for s, req in zip(rec["active"], rec["reqs"]):
            if self.slots[s] is not req or not self._slot_ready[s]:
                continue  # evicted between dispatch and sync
            consumed = 0
            for j in range(T):
                tok = int(toks[s, j])
                # Logprob BEFORE token: a streaming handler thread that
                # snapshots between the two appends must never see a
                # token whose logprob is missing.
                if req.logprobs:
                    req.token_logprobs.append(float(lps[s, j]))
                req.tokens.append(tok)
                self._slot_last[s] = tok
                consumed += 1
                emitted_total += 1
                if (
                    len(req.tokens) >= req.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or self._hit_stop(req)
                ):
                    break
            self._slot_len[s] += consumed
            self._observe_itl(s, consumed, now)
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        # The block left every row's device length at L+T (at L+2T with
        # an overlapped block in flight).  When every active slot
        # consumed all T tokens that IS the host truth (the in-flight
        # block accounts for its own +T when it is consumed); a
        # mid-block finish tore its slot down (_clear_slot -> state
        # dirty), and only then do device lengths disagree — the
        # in-flight discard re-aligns them, or the direct vector write
        # below does when nothing was in flight.
        if self._dev is None:
            if self._inflight is not None:
                self._drop_stale_inflight("slot_teardown")
            else:
                for name in self._layer_names:
                    att = self.cache[name]["attn"]
                    self.cache[name]["attn"] = {
                        **att,
                        "seq_lens": self._rep(
                            jnp.array(self._slot_len, jnp.int32)
                        ),
                    }
        self._mark("host_gap" if self._inflight is not None else "sample")
        self._step_tokens += emitted_total
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(emitted_total)
        self._update_gauges()
        return finished

    def _mark(self, phase: str) -> None:
        """Attribute the time since the previous mark of the CURRENT step
        to ``phase`` (engine_profiler.PHASES); no-op outside step()."""
        if self._prof_timer is not None:
            self._prof_timer.mark(phase)

    def step(self) -> list[Request]:
        """Admit what fits, advance every active slot one token; returns
        every request that finished this step (including ones done at
        admission — EOS/max_new on the prefill token)."""
        span = (
            self.spans.span("engine.step", trace_id=ENGINE_TRACE)
            if self.spans
            else contextlib.nullcontext()
        )
        timer = self._prof_timer = self.profiler.timer()
        self._step_tokens = 0
        hits0, discards0 = self.overlap_hits, self.overlap_discards
        kv_hits0 = self.kv_retained_hits + self.kv_host_hits
        kv_restores0 = self.kv_restores
        wd = self.watchdog
        if wd is not None:
            wd.step_started()
        try:
            with span:
                if self.metrics:
                    with self.metrics.step_seconds.time():
                        return self._step_inner()
                return self._step_inner()
        finally:
            self._prof_timer = None
            with self._lock:
                active = sum(1 for s in self.slots if s is not None)
                queued = len(self.queue)
                allocatable = self.paged.num_pages - 1
                util = (
                    1.0 - len(self.free_pages) / allocatable
                    if allocatable
                    else 0.0
                )
            wall = self.profiler.finish_step(
                timer,
                active_slots=active,
                max_slots=self.max_slots,
                queued=queued,
                kv_page_utilization=util,
                tokens=self._step_tokens,
                overlap_hits=self.overlap_hits - hits0,
                overlap_discards=self.overlap_discards - discards0,
                kvcache_hits=(
                    self.kv_retained_hits + self.kv_host_hits - kv_hits0
                ),
                kvcache_restores=self.kv_restores - kv_restores0,
            )
            if wd is not None:
                wd.step_finished(wall)

    def _step_inner(self) -> list[Request]:
        # Overload sweeps run BEFORE admission: an expired queued request
        # must shed (without ever touching pages) rather than admit, and
        # an infeasible slot must be marked so the cancel sweep below
        # frees it for the queue head.
        finished = self._overload_sweep() if self.overload is not None else []
        finished += self._admit()
        # Cancelled slots tear down BEFORE the dispatch (no farewell
        # token).  Only ready slots: a cancelled request mid-prefill
        # keeps its job's slot/pages intact until activation, whose own
        # _maybe_finish call then finishes it (this sweep catches
        # requests cancelled after they were already live).
        for s in range(self.max_slots):
            req = self.slots[s]
            if req is not None and req.cancelled and self._slot_ready[s]:
                self._maybe_finish(s)
                finished.append(req)
        self._mark("schedule")
        # Advance every in-flight prefill job by ONE chunk (an unchunked
        # job completes right here, in the same step() it was admitted):
        # chunking bounds how long active slots stall per step while a
        # long prompt streams in.
        for job in list(self._pending):
            if self._advance_prefill(job):
                self._pending.remove(job)
                finished.extend(self._activate(job))
        self._mark("prefill")
        active = [
            s
            for s in range(self.max_slots)
            if self.slots[s] is not None and self._slot_ready[s]
        ]
        if not active:
            self._update_gauges()
            return finished
        if self._spec_gamma:
            return self._spec_step(active, finished)
        if (
            self._decode_block > 1
            and not self._pending  # no prompt mid-stream: keep chunking
            # Queued work argues for fine-grained steps ONLY while the
            # head could actually admit: a SATURATED engine (every slot
            # occupied — the steady operating point of a loaded server)
            # or a PAGE-BLOCKED head (this step's _admit broke on the
            # pool; only a finish or reclamation frees pages) cannot
            # admit until something releases, so it keeps blocking — a
            # mid-block finish truncates that slot's tail and the next
            # step() admits from the queue.  Otherwise stay fine-grained
            # so the queue head lands immediately.
            and (
                not self.queue
                or all(s is not None for s in self.slots)
                or self._admit_page_blocked
            )
        ):
            # Largest power-of-two block that no active slot's remaining
            # budget truncates (so no slot can overrun max_new mid-block).
            room = min(
                self.slots[s].max_new_tokens - len(self.slots[s].tokens)
                for s in active
            )
            T = min(self._decode_block, 1 << max(0, room.bit_length() - 1))
            if T > 1:
                return self._block_step(active, finished, T)
        overlap = self._overlap_allowed()
        rec = self._take_inflight(1)
        if rec is None:
            # Cold (or just-invalidated) pipeline: dispatch this step,
            # then prime the overlap from its fed-forward state.  The
            # next write (position len) must be addressable — and the
            # overlapped write (len+1) too when one will follow, hence
            # the one-token frontier lookahead; _block_step/_spec_step
            # run their own ensure with their larger lookaheads.
            if self._optimistic or overlap:
                active = self._ensure_frontier(active, 1 if overlap else 0)
                if not active:
                    self._update_gauges()
                    return finished
            rec = self._dispatch_decode(active)
            if overlap:
                self._inflight = self._dispatch_decode(active)
            self._mark("dispatch")
        else:
            self._record_hit()
            if overlap:
                # Keep one step in flight: ensure the NEXT write is
                # addressable, then dispatch before the (blocking)
                # readback of the consumed step.  An eviction inside the
                # ensure dirtied the state — then this step consumes
                # what it has and re-primes next call.
                active = self._ensure_frontier(active, 1)
                if active and self._dev is rec["dev"]:
                    self._inflight = self._dispatch_decode(active)
            self._mark("dispatch")
        return self._consume_step(rec, finished)

    def _consume_step(
        self, rec: dict, finished: list[Request]
    ) -> list[Request]:
        """Host half of one single-token step: sync the packed readback,
        then per-slot consumption (EOS/stop checks, frontier extension,
        reclamation, metrics) — under overlap this host work executes
        while the next step computes on device (the host_gap phase)."""
        toks, lps = self._unpack(rec)
        self._mark("readback")
        now = time.monotonic()
        consumed = 0
        for s, req in zip(rec["active"], rec["reqs"]):
            if self.slots[s] is not req or not self._slot_ready[s]:
                continue  # evicted between dispatch and sync
            tok = int(toks[s])
            # Logprob BEFORE token (see _consume_block note).
            if req.logprobs:
                req.token_logprobs.append(float(lps[s]))
            req.tokens.append(tok)
            self._slot_last[s] = tok
            self._slot_len[s] += 1
            consumed += 1
            self._observe_itl(s, 1, now)
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        if self._dev is None:
            # A finish/cancel tore a slot down mid-consume: whatever is
            # still in flight was dispatched from pre-teardown state.
            self._drop_stale_inflight("slot_teardown")
        self._mark("host_gap" if self._inflight is not None else "sample")
        self._step_tokens += consumed
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(consumed)
        self._update_gauges()
        return finished

    def _observe_itl(self, slot: int, consumed: int, now: float) -> None:
        """Observe inter-token latency for ``consumed`` tokens that landed
        at ``now`` on this slot.  Multi-token dispatches (decode blocks,
        speculative rounds) emit several tokens in one host round-trip:
        each observes the amortized gap dt/consumed, so the histogram sum
        stays wall-accurate and per-token quantiles stay meaningful."""
        last = self._slot_emit_t[slot]
        self._slot_emit_t[slot] = now
        if consumed <= 0 or last <= 0.0:
            return
        per = (now - last) / consumed
        req = self.slots[slot]
        if req is not None and per > req.itl_peak_s:
            # Per-request peak gap: the SLO plane's per-request ITL p99
            # stand-in (engine_types.Request.itl_peak_s).
            req.itl_peak_s = per
        if self.overload is not None:
            # The feasibility predicate's input: measured per-token
            # latency decides whether a deadline can still be met.
            self.overload.observe_itl(per)
        if not self.metrics:
            return
        for _ in range(consumed):
            self.metrics.itl_seconds.observe(per)

    def _update_gauges(self) -> None:
        if not self.metrics:
            return
        with self._lock:
            self.metrics.active_slots.set(
                sum(1 for s in self.slots if s is not None)
            )
            self.metrics.queued.set(len(self.queue))
            self.metrics.free_pages.set(len(self.free_pages))
            self.metrics.shared_pages.set(
                sum(1 for c in self._page_refs.values() if c > 1)
            )
            allocatable = self.paged.num_pages - 1  # page 0 is scratch
            self.metrics.page_utilization.set(
                1.0 - len(self.free_pages) / allocatable if allocatable else 0.0
            )
            self.metrics.kvcache_retained_pages.set(len(self._kv_retained))
            self.metrics.kvcache_host_bytes.set(self._kv_arena.bytes)

    def debug_state(self) -> dict:
        """JSON-safe engine snapshot for the /debug/state endpoint: what
        an operator needs to see DURING an incident — slot occupancy,
        queue depth, pool pressure, speculation counters — without
        attaching a debugger to the serving loop.  Token CONTENT is
        deliberately excluded (prompts are tenant data; lengths are not).
        Thread-safe: reads the cross-thread state under the engine lock
        (host lists owned by the step thread are read racily but are
        plain scalars/lists — a torn read shows one step's drift)."""
        with self._lock:
            slots = []
            for s in range(self.max_slots):
                req = self.slots[s]
                if req is None:
                    slots.append(None)
                    continue
                slots.append(
                    {
                        "rid": req.rid,
                        "trace_id": req.trace_id,
                        "prompt_tokens": len(req.prompt),
                        "generated": len(req.tokens),
                        "max_new_tokens": req.max_new_tokens,
                        "ready": self._slot_ready[s],
                        "pages": len(self._slot_pages[s]),
                        "cancelled": req.cancelled,
                    }
                )
            allocatable = self.paged.num_pages - 1
            return {
                "slots": slots,
                "queue_depth": len(self.queue),
                "pending_prefills": len(self._pending),
                "free_pages": len(self.free_pages),
                "allocatable_pages": allocatable,
                "page_utilization": round(
                    1.0 - len(self.free_pages) / allocatable, 4
                )
                if allocatable
                else 0.0,
                "shared_pages": sum(
                    1 for c in self._page_refs.values() if c > 1
                ),
                "preemptions": self.preemptions,
                "overlap": {
                    "steps": self._overlap_steps,
                    "in_flight": self._inflight is not None,
                    "hits": self.overlap_hits,
                    "discards": self.overlap_discards,
                },
                "tp": {
                    "size": self.tp_size,
                    "axis": self._tp_axis if self.mesh is not None else None,
                    "mesh": dict(self.mesh.shape)
                    if self.mesh is not None
                    else None,
                    "devices": [str(d) for d in self.mesh.devices.flat]
                    if self.mesh is not None
                    else None,
                },
                "spec": {
                    "gamma": self._spec_gamma,
                    "proposed": self.spec_proposed,
                    "accepted": self.spec_accepted,
                },
                "overload": (
                    self.overload.snapshot()
                    if self.overload is not None
                    else {"enabled": False}
                ),
                "slo": (
                    self.slo.snapshot()
                    if self.slo is not None
                    else {"enabled": False}
                ),
                "kvcache": self.kvcache_state(),
                "disagg": self.handoff_state(),
                "config": {
                    "role": self.role,
                    "max_slots": self.max_slots,
                    "page_size": self.paged.page_size,
                    "num_pages": self.paged.num_pages,
                    "max_pages_per_seq": self.paged.max_pages_per_seq,
                    "kernel": self.kernel_on,
                    "kernel_splits": self.paged.kernel_num_splits,
                    "decode_block": self._decode_block,
                    "admission": "optimistic" if self._optimistic else "reserve",
                    "prefix_sharing": self.prefix_sharing,
                },
            }

    def overload_state(self) -> dict:
        """JSON-safe overload-controller snapshot for GET
        /debug/admission (``{"enabled": False}`` when the engine runs
        without a controller)."""
        with self._lock:
            if self.overload is None:
                return {"enabled": False}
            return self.overload.snapshot()

    def slo_state(self) -> dict:
        """JSON-safe SLO-plane snapshot for GET /debug/slo: objectives,
        window counts, burn rates, budget remaining, active alerts
        (``{"enabled": False}`` when the plane is off)."""
        with self._lock:
            if self.slo is None:
                return {"enabled": False}
            snap = self.slo.snapshot()
            snap["enabled"] = True
            return snap

    def usage_state(self) -> dict:
        """JSON-safe per-tenant usage snapshot for GET /debug/usage
        (``{"enabled": False}`` when the SLO plane is off)."""
        with self._lock:
            if self.usage is None:
                return {"enabled": False}
            snap = self.usage.snapshot()
            snap["enabled"] = True
            return snap

    def run(self, requests: list[tuple[list[int], int]], **submit_kw) -> list[Request]:
        """Submit all (``submit_kw`` — temperature/top_k/top_p — applies to
        every request), step until drained, return in submission order."""
        subs = [self.submit(p, n, **submit_kw) for p, n in requests]
        guard = 0
        while not all(r.done for r in subs):
            self.step()
            guard += 1
            if guard > 100_000:
                raise RuntimeError("engine failed to drain")
        return subs


def main(argv: Optional[list[str]] = None) -> None:
    """In-pod serving demo/benchmark (≙ the per-family benchmark pods in
    deploy/): synthetic weights + synthetic request stream through the
    continuous-batching engine; prints one JSON summary line.

    ``k8s-pod-serve-gpt.yaml`` runs this against allocated chips; the same
    command works on any backend (tiny CPU smoke by default).
    """
    import argparse
    import json
    import sys
    import time

    from ..utils.platform import honor_jax_platforms_env
    from .benchmark import _positive_int

    # Empty JAX_PLATFORMS in a pod spec is a no-op, not a platform reset.
    honor_jax_platforms_env(
        empty_is_auto=False, log=lambda m: print(m, file=sys.stderr)
    )

    p = argparse.ArgumentParser(prog="tpu-serving-engine")
    p.add_argument("--hidden", type=_positive_int, default=512)
    p.add_argument("--layers", type=_positive_int, default=4)
    p.add_argument("--heads", type=_positive_int, default=8)
    p.add_argument("--kv-heads", type=_positive_int, default=4)
    p.add_argument("--vocab", type=_positive_int, default=32000)
    p.add_argument("--quant", choices=["w8", "w8a8"], default=None)
    p.add_argument(
        "--quant-kv",
        action="store_true",
        help="int8 paged KV pools (halved cache bandwidth; gather path)",
    )
    p.add_argument("--page-size", type=_positive_int, default=16)
    p.add_argument("--num-pages", type=_positive_int, default=128)
    p.add_argument("--max-pages-per-seq", type=_positive_int, default=16)
    p.add_argument("--slots", type=_positive_int, default=4)
    p.add_argument("--requests", type=_positive_int, default=8)
    p.add_argument("--prompt-len", type=_positive_int, default=32)
    p.add_argument("--max-new", type=_positive_int, default=32)
    p.add_argument(
        "--use-kernel",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="decode through the split-K flash-decode paged-attention "
        "kernel instead of the gather path (ops/paged_attention.py; "
        "fused int8 dequant, per-generation split tables in "
        "ops/tuning.py); default auto — gather everywhere until a "
        "hardware round proves the split-K Mosaic lowering "
        "(docs/kernels.md)",
    )
    p.add_argument(
        "--kernel-splits",
        type=_positive_int,
        default=None,
        help="pin the paged kernel's split-K degree (default: the "
        "per-generation tuning table, ops/tuning.py — 1 on CPU smoke "
        "and short contexts)",
    )
    p.add_argument(
        "--temperature",
        type=float,
        default=0.0,
        help="sample every request at this temperature (0 = greedy)",
    )
    p.add_argument(
        "--top-k", type=_positive_int, default=None,
        help="restrict sampling to the k highest logits per step",
    )
    p.add_argument(
        "--top-p", type=float, default=None,
        help="restrict sampling to the smallest nucleus with mass >= p",
    )
    p.add_argument(
        "--spec-gamma",
        type=int,
        default=0,
        help="speculative decoding: gamma int8 self-draft proposals per "
        "verify pass (shared-pool; greedy slots emit exactly the greedy "
        "decode, sampled slots marginally exact filtered samples). "
        "Incompatible with --quant.",
    )
    p.add_argument(
        "--prefill-chunk",
        type=_pow2_int,
        default=None,
        help="stream prompts into the prefill in chunks of this many "
        "tokens (power of two), bounding how long active slots stall "
        "per step during a long admission",
    )
    p.add_argument(
        "--decode-block",
        type=_pow2_int,
        default=1,
        help="in pure decode (no admission work), advance every slot up "
        "to this many tokens per dispatch via one scanned program "
        "(power of two) — amortizes the per-step host round-trip; under "
        "saturation a finishing request's slot is refilled at the next "
        "step boundary, so blocks add up to block-size steps of "
        "first-token wait; incompatible with --spec-gamma",
    )
    p.add_argument(
        "--overlap-steps",
        type=int,
        choices=[0, 1],
        default=1,
        help="decode dispatches kept in flight ahead of host consumption "
        "(1: dispatch step N+1 before consuming step N's readback, hiding "
        "per-token host work behind device compute; invalidating events — "
        "admission, finish, cancel, preemption — discard the in-flight "
        "step at the cost of one wasted lane; 0: strictly synchronous "
        "loop; speculative engines always run synchronously)",
    )
    p.add_argument(
        "--admission",
        choices=["reserve", "optimistic"],
        default="reserve",
        help="reserve: allocate each request's worst-case page chain at "
        "admission (no preemption ever); optimistic: allocate prompt "
        "pages only and grow on demand, preempting the newest slot for "
        "recompute-resume when the pool runs dry — higher concurrency "
        "when generations finish early",
    )
    p.add_argument(
        "--overload",
        type=int,
        choices=[0, 1],
        default=1,
        help="overload control (models/engine_overload.py): priority + "
        "deadline-aware admission with per-tenant fair sharing, expiry "
        "sweeping, and an AIMD concurrency limiter driven by measured "
        "queue wait (default on; 0 restores the plain FIFO queue — "
        "streams are bit-identical either way for deadline-free "
        "uniform-priority traffic)",
    )
    p.add_argument(
        "--overload-target-wait",
        type=float,
        default=0.5,
        help="AIMD setpoint: the queue wait (seconds) the overload "
        "limiter steers admitted concurrency toward",
    )
    p.add_argument(
        "--overload-max-queue",
        type=int,
        default=512,
        help="hard queue cap: submits past this depth shed immediately "
        "with 503 + Retry-After regardless of priority",
    )
    p.add_argument(
        "--slo",
        type=int,
        choices=[0, 1],
        default=1,
        help="SLO plane (utils/slo.py): per-request SLI verdicts (TTFT, "
        "per-request ITL p99, availability) into sliding-window error "
        "budgets with burn-rate alerting, plus per-tenant usage meters "
        "(default on; 0 disables all accounting — zero per-request cost)",
    )
    p.add_argument(
        "--slo-ttft-target",
        type=float,
        default=2.0,
        help="TTFT objective threshold (seconds): a request whose first "
        "token lands later counts against the ttft error budget",
    )
    p.add_argument(
        "--slo-itl-target",
        type=float,
        default=0.25,
        help="per-request ITL p99 objective threshold (seconds): a "
        "request whose worst inter-token gap exceeds this counts "
        "against the itl_p99 error budget",
    )
    p.add_argument(
        "--kv-retain",
        type=int,
        choices=[0, 1],
        default=1,
        help="KV cache tier 1: keep dead-but-valid prefix pages on an "
        "LRU instead of freeing them, so a repeated prompt prefix (or a "
        "preemption resume) restores from the page pool instead of "
        "recomputing; retained pages are reclaimed lazily whenever the "
        "free pool alone cannot satisfy a request (default on)",
    )
    p.add_argument(
        "--kv-host-cache-mb",
        type=float,
        default=64,
        help="KV cache tier 2: byte budget (MiB) of the host-RAM arena "
        "that reclaimed retained pages and preemption snapshots spill "
        "into; matched entries restore device-side with sliced page "
        "writes — no recompute, no new compiled shapes (0 disables the "
        "host tier; default 64)",
    )
    p.add_argument(
        "--tp",
        type=_positive_int,
        default=1,
        help="tensor-parallel degree: shard params (Megatron path rules) "
        "and KV pools (kv-heads axis) over a mesh built from the chips "
        "the plugin allocated — TPU_VISIBLE_CHIPS in physical ICI snake "
        "order (parallel/mesh.mesh_from_allocation); must equal the "
        "granted chip count on-cluster, and kv-heads must divide by it; "
        "off-cluster falls back to the first N jax.devices(); 1 = "
        "single-chip (default)",
    )
    args = p.parse_args(argv)
    if args.spec_gamma and args.quant:
        raise SystemExit(
            "--spec-gamma uses the int8 SELF-draft against the bf16 "
            "target; an already-quantized target (--quant) leaves nothing "
            "to verify against — drop one of the flags"
        )

    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_heads=args.heads,
        intermediate_size=args.hidden * 3,
        max_seq=args.page_size * args.max_pages_per_seq,
        num_kv_heads=args.kv_heads,
    )
    rng = jax.random.PRNGKey(0)
    params = TransformerLM(cfg).init(rng, jnp.zeros((1, 2), jnp.int32))["params"]
    if args.quant:
        from ..ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
        cfg = dataclasses.replace(cfg, quant=args.quant)
    if args.quant_kv:
        cfg = dataclasses.replace(cfg, quant_kv=True)
    paged = PagedConfig(
        args.page_size,
        args.num_pages,
        args.max_pages_per_seq,
        use_kernel=args.use_kernel,
        kernel_num_splits=args.kernel_splits,
    )
    spec_kw = {}
    if args.spec_gamma:
        from ..ops.quant import quantize_lm_params

        spec_kw = dict(
            spec_gamma=args.spec_gamma,
            draft_params=quantize_lm_params(params),
        )
    from ..utils.metrics import MetricsRegistry

    mesh = None
    if args.tp > 1:
        from ..parallel.mesh import mesh_from_allocation

        mesh = mesh_from_allocation(args.tp)
        print(
            f"tensor parallel: tp={args.tp} over "
            f"{[str(d) for d in mesh.devices.flat]}",
            file=sys.stderr,
        )
    registry = MetricsRegistry()
    overload_cfg = None
    if args.overload:
        from .engine_overload import OverloadConfig

        overload_cfg = OverloadConfig(
            target_queue_wait_s=args.overload_target_wait,
            max_queue=args.overload_max_queue,
        )
    slo_cfg = None
    if args.slo:
        slo_cfg = {
            "ttft_target_s": args.slo_ttft_target,
            "itl_p99_target_s": args.slo_itl_target,
        }
    eng = ServingEngine(
        cfg, params, paged, max_slots=args.slots,
        metrics=EngineMetrics(registry),
        prefill_chunk=args.prefill_chunk, decode_block=args.decode_block,
        overlap_steps=args.overlap_steps,
        admission=args.admission,
        overload=overload_cfg,
        slo=slo_cfg,
        kv_retain=bool(args.kv_retain),
        kv_host_cache_mb=args.kv_host_cache_mb,
        mesh=mesh,
        **spec_kw,
    )
    sample_kw = dict(
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p
    )

    # Half the stream shares a system-prompt prefix (exercises page sharing).
    common = list(range(1, args.prompt_len // 2 + 1))
    jobs = []
    for i in range(args.requests):
        tail = [(37 * i + j) % args.vocab for j in range(args.prompt_len // 2)]
        prompt = (common + tail) if i % 2 == 0 else [(11 * i + j) % args.vocab for j in range(args.prompt_len)]
        jobs.append((prompt, args.max_new))

    # Warmup: compile the fixed-slot step and EVERY distinct prompt-length
    # prefill OUTSIDE the timed region (max_new=2 forces one decode step),
    # so the JSON line reports steady-state serving throughput, not XLA
    # compilation — the same honesty rule every bench in this repo follows
    # (BASELINE.md "Measurement methodology").
    warm_lens: dict[int, list[int]] = {}
    for prompt, _ in jobs:
        warm_lens.setdefault(len(prompt), prompt)
    eng.run([(prompt, 2) for prompt in warm_lens.values()], **sample_kw)
    # Warmup rounds ran real speculative traffic; the reported acceptance
    # must cover the timed region only (same warmup-exclusion rule as the
    # throughput number).
    eng.spec_proposed = eng.spec_accepted = 0
    # Latency percentiles come back from the SAME registry histograms
    # operators scrape — snapshotted here so warmup (compile-dominated
    # TTFTs of seconds) is subtracted from the reported quantiles.
    ttft_h, itl_h = eng.metrics.ttft_seconds, eng.metrics.itl_seconds
    ttft_snap, itl_snap = ttft_h.snapshot(), itl_h.snapshot()

    def _ms(value):
        return None if value is None else round(value * 1e3, 3)

    t0 = time.time()
    done = eng.run(jobs, **sample_kw)
    dt = time.time() - t0
    tokens = sum(len(r.tokens) for r in done)
    print(
        json.dumps(
            {
                "metric": "engine_decode_tokens_per_sec",
                "value": round(tokens / dt, 2),
                "unit": "tokens/sec",
                "requests": len(done),
                "slots": args.slots,
                "tp": args.tp,
                "quant": args.quant,
                "kernel": paged.kernel_enabled(cfg.quant_kv),
                "sampler": "greedy"
                if args.temperature <= 0
                else f"temperature={args.temperature},top_k={args.top_k},"
                f"top_p={args.top_p}",
                "spec_gamma": args.spec_gamma,
                "spec_acceptance": round(
                    eng.spec_accepted / max(eng.spec_proposed, 1), 3
                )
                if args.spec_gamma
                else None,
                "tokens": tokens,
                "wall_s": round(dt, 2),
                "overlap_steps": args.overlap_steps,
                "overlap_hits": eng.overlap_hits,
                "overlap_discards": eng.overlap_discards,
                "kv_retain": bool(args.kv_retain),
                "kv_retained_hits": eng.kv_retained_hits,
                "kv_host_hits": eng.kv_host_hits,
                "ttft_p50_ms": _ms(ttft_h.quantile(0.5, since=ttft_snap)),
                "ttft_p99_ms": _ms(ttft_h.quantile(0.99, since=ttft_snap)),
                "itl_p50_ms": _ms(itl_h.quantile(0.5, since=itl_snap)),
                "itl_p99_ms": _ms(itl_h.quantile(0.99, since=itl_snap)),
            }
        ),
        file=sys.stdout,
        flush=True,
    )


if __name__ == "__main__":
    main()
