"""Continuous-batching serving engine over the paged KV cache.

The reference stops at mounting device nodes into a pod (reference
main.go:139-159); this is the workload-side request server that runs ON
those chips.  Design split, TPU-shaped:

- **Device side** (jitted once): a fixed-[slots] single-token decode step
  over the paged cache (models/transformer.py ``PagedConfig``) — every
  slot advances every step, idle slots compute masked garbage into the
  reserved scratch page.  Static shapes, no recompiles as requests come
  and go.
- **Host side** (this module, plain Python between steps): admission,
  page allocation/free, per-slot bookkeeping.  State edits are row-wise
  ``.at[slot].set`` updates on the cache tree — O(layers) small
  dispatches per request event, never per token.

Prefill bridges through the dense path: an admitted prompt runs the
ordinary dense-cache prefill (one MXU-shaped pass, compiled per prompt
length), and its K/V rows are grafted into the allocated pages.  Decode
then proceeds fully paged.  Page 0 is reserved as the idle-slot scratch
target: idle rows keep appending there (their page-table rows are zero
and gather indices clamp), so they can never collide with a live page.

Capacity model: a request needs ``ceil((prompt + max_new) / page_size)``
pages, allocated at admission (no mid-flight allocation → no deadlock);
requests queue when the pool is dry and admit as finished requests free
their pages — continuous batching.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import threading
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.metrics import MetricsRegistry
from .transformer import (
    NEG_LOGIT,
    GPTConfig,
    PagedConfig,
    TransformerLM,
    decode_cache_spec,
)


def _pow2_int(text: str) -> int:
    """argparse type: positive power of two (chunk sizes must tile the
    power-of-two length buckets)."""
    import argparse

    value = int(text)
    if value < 1 or value & (value - 1):
        raise argparse.ArgumentTypeError(
            f"must be a positive power of two, got {value}"
        )
    return value


def _token_logprob(row, nxt):
    """The emitted token's logprob under the UNSCALED model distribution
    (sampler-independent semantics — temperature/top-k reshape what gets
    PICKED, not what is reported).  Compiled into a step variant only
    when a request asks (the ``want_lp`` key of _step_fn/_block_fn), so
    engines that never serve logprobs never compute it."""
    lp = jax.nn.log_softmax(row.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(lp, nxt[:, None], axis=1)[:, 0]


def filter_top_k_top_p(scaled, top_k, top_p):
    """Mask ``scaled`` logits [batch, vocab] to each row's top-k tokens and
    smallest nucleus with mass >= top_p — with PER-ROW traced ``top_k``
    (int32, vocab = disabled) and ``top_p`` (float32, 1.0 = disabled), so
    slots with different sampler settings mix in one jitted step.

    `lax.top_k` needs a static k, so this uses one descending sort per row
    and reads thresholds out of it: the k-th value for top-k, and the
    smallest value still inside the nucleus for top-p (computed on the
    top-k-filtered distribution, the HF/vLLM filter order).  Keeping
    ``scaled >= threshold`` admits ties, matching sample_generate's
    static-k semantics (transformer.py).  O(vocab log vocab) on a
    [slots, vocab] array — noise next to the model forward.
    """
    vocab = scaled.shape[-1]
    s_sorted = jnp.sort(scaled, axis=-1)[:, ::-1]
    ranks = jnp.arange(vocab)[None, :]
    kth = jnp.take_along_axis(
        s_sorted, jnp.clip(top_k, 1, vocab)[:, None] - 1, axis=-1
    )
    in_k = ranks < jnp.clip(top_k, 1, vocab)[:, None]
    probs = jax.nn.softmax(jnp.where(in_k, s_sorted, NEG_LOGIT), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # A rank is in the nucleus while the mass BEFORE it is < p (so the
    # first token is always kept); p = 1.0 keeps every unmasked rank.
    in_p = jnp.logical_and(in_k, (cum - probs) < top_p[:, None])
    p_min = jnp.min(
        jnp.where(in_p, s_sorted, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(
        scaled >= jnp.maximum(kth, p_min), scaled, NEG_LOGIT
    )


class EngineMetrics:
    """Prometheus series for the serving engine (same registry machinery
    the plugin daemon exposes on its --metrics-port).  Pass a shared
    registry to co-expose with other subsystems, or let each engine own
    one and mount it on a utils.metrics.MetricsServer."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.requests = registry.counter(
            "tpu_engine_requests_total",
            "Requests admitted into a decode slot",
        )
        self.tokens = registry.counter(
            "tpu_engine_tokens_total", "Tokens emitted across all requests"
        )
        self.steps = registry.counter(
            "tpu_engine_steps_total", "Jitted decode steps executed"
        )
        self.active_slots = registry.gauge(
            "tpu_engine_active_slots", "Slots currently serving a request"
        )
        self.queued = registry.gauge(
            "tpu_engine_queued_requests", "Requests waiting for slots/pages"
        )
        self.free_pages = registry.gauge(
            "tpu_engine_free_pages", "Unallocated KV-cache pages"
        )
        self.shared_pages = registry.gauge(
            "tpu_engine_shared_pages",
            "Pages currently referenced by more than one request (prefix sharing)",
        )
        self.spec_proposed = registry.counter(
            "tpu_engine_spec_proposed_total",
            "Draft tokens proposed by speculative rounds",
        )
        self.spec_accepted = registry.counter(
            "tpu_engine_spec_accepted_total",
            "Draft tokens the target accepted (rate = accepted/proposed)",
        )
        self.preemptions = registry.counter(
            "tpu_engine_preemptions_total",
            "Slots evicted for recompute-resume under optimistic admission",
        )
        self.step_seconds = registry.histogram(
            "tpu_engine_step_seconds",
            "Wall time of one engine step() call (admission + dispatch + "
            "consume); histogram_quantile() gives serving-step p50/p99",
        )
        self.wait_seconds = registry.histogram(
            "tpu_engine_request_wait_seconds",
            "Queue-to-first-token wait per request (admission latency "
            "under load)",
            # Wider than the step buckets: overload pushes waits far past
            # 10s, and a saturated top bucket would clamp the p99 exactly
            # when the metric matters.
            buckets=(
                0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0, 120.0, 300.0,
            ),
        )


@dataclasses.dataclass
class Request:
    """One generation request and, when finished, its output tokens.

    ``temperature`` 0 means greedy; > 0 samples that request's tokens at
    that temperature.  ``top_k``/``top_p`` restrict sampling to the k
    highest logits / the smallest nucleus with mass >= p (None = off;
    only meaningful with temperature > 0).  Slots with different sampler
    settings mix freely in one jitted step."""

    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # Multi-LoRA serving (cfg.lora_serve > 0): which stacked adapter this
    # request decodes through; None = base model.
    adapter: Optional[int] = None
    # Sparse logit bias: {token_id: added_logit} applied BEFORE greedy
    # argmax and sampling (OpenAI semantics: -100 bans, +100 forces);
    # capped at ServingEngine.MAX_BIAS entries.  Reported logprobs stay
    # UNBIASED (bias changes what gets picked, not what is scored).
    logit_bias: Optional[dict] = None
    # Stop sequences (token-id lists): generation ends when the output's
    # tail equals any of them; the matched suffix is EXCLUDED from
    # ``tokens`` (eos_id, by contrast, is included — the id itself is the
    # terminator, a stop sequence is a content sentinel).
    stop: Optional[list[list[int]]] = None
    # Latched by the engine when a stop sequence matched (the matched
    # suffix is truncated away, so the flag — not the tail — records it).
    stopped: bool = False
    # Record each emitted token's logprob under the unscaled model
    # distribution in ``token_logprobs`` (parallel to ``tokens``).
    # Sampler settings change what gets picked, never what is reported.
    logprobs: bool = False
    rid: int = -1
    # monotonic submit time (engine-internal: queue-wait observation).
    submitted_at: float = 0.0
    tokens: list[int] = dataclasses.field(default_factory=list)
    token_logprobs: list[float] = dataclasses.field(default_factory=list)
    done: bool = False
    # Set via ServingEngine.cancel() (client went away): a queued request
    # finishes immediately; an in-flight one is torn down at the next step
    # boundary, its slot and pages returned to the pool.
    cancelled: bool = False


class ServingEngine:
    """Batch-continuous greedy decoding server (single host, one model).

    ``MAX_BIAS``: per-request logit_bias entries are padded to this fixed
    width so they trace into the jitted step as [slots, MAX_BIAS] arrays
    (no recompiles as biased requests come and go).

    ``cfg`` is the model config WITHOUT paging; the engine derives the
    paged decode config.  ``params`` may be any serving tree the config
    accepts (bf16, or int8 via ``cfg.quant``).
    """

    MAX_BIAS = 16
    # Stop-sequence caps (OpenAI allows 4 stops; 8 is generous).  Checked in
    # submit() so the unauthenticated HTTP path can't make _hit_stop's
    # per-token Python scan unbounded.
    MAX_STOPS = 8
    MAX_STOP_LEN = 32

    def __init__(
        self,
        cfg: GPTConfig,
        params: Any,
        paged: PagedConfig,
        *,
        max_slots: int = 4,
        eos_id: Optional[int] = None,
        prefix_sharing: bool = True,
        rng: Optional[jax.Array] = None,
        metrics: Optional[EngineMetrics] = None,
        spec_gamma: int = 0,
        draft_params: Any = None,
        draft_cfg: Optional[GPTConfig] = None,
        prefill_chunk: Optional[int] = None,
        decode_block: int = 1,
        admission: str = "reserve",
    ):
        if cfg.paged is not None:
            raise ValueError("pass the base config; the engine adds paging")
        if spec_gamma < 0:
            raise ValueError(f"spec_gamma must be >= 0, got {spec_gamma}")
        if decode_block < 1 or (decode_block & (decode_block - 1)):
            # Power of two: the host down-buckets the block to the largest
            # power of two that fits every active slot's remaining budget,
            # so compiled block programs stay O(log decode_block).
            raise ValueError(
                f"decode_block must be a power of two >= 1, got {decode_block}"
            )
        if decode_block > 1 and spec_gamma > 0:
            # Both amortize dispatches over multi-token device rounds with
            # incompatible schedules (scan of exact steps vs draft+verify).
            raise ValueError("decode_block > 1 is not supported with spec_gamma")
        if admission not in ("reserve", "optimistic"):
            raise ValueError(
                f"admission must be 'reserve' or 'optimistic', got {admission!r}"
            )
        if cfg.lora_serve and spec_gamma > 0:
            # The self-draft is the same model int8-quantized, and quant is
            # mutually exclusive with LoRA (quantize after merging) — there
            # is no coherent draft for a multi-adapter batch.
            raise ValueError("lora_serve is not supported with spec_gamma")
        if prefill_chunk is not None and (
            prefill_chunk < 1 or prefill_chunk & (prefill_chunk - 1)
        ):
            # Power of two so chunks tile every power-of-two length bucket.
            raise ValueError(
                f"prefill_chunk must be a power of two, got {prefill_chunk}"
            )
        self._prefill_chunk = prefill_chunk
        if spec_gamma > 0:
            # Shared-pool speculation: the draft writes its (approximate)
            # K/V at the frontier and the verify pass overwrites those
            # same positions with exact target K/V before any later read,
            # so the draft needs NO cache of its own — but that only
            # works when both models address the pool identically, i.e.
            # same architecture (self-speculation: the draft is the same
            # model quantized, ops/quant.py).
            if draft_params is None:
                raise ValueError("spec_gamma > 0 requires draft_params")
            if draft_cfg is None:
                draft_cfg = dataclasses.replace(cfg, quant="w8")
            # Only the WEIGHT format may differ: quant_kv is part of the
            # shared pool's storage format (int8 pools + scale pools), so
            # a draft/target mismatch would have the draft writing the
            # wrong dtype into — and reading raw codes out of — the very
            # pages the target owns.
            same = dataclasses.replace(draft_cfg, quant=None) == (
                dataclasses.replace(cfg, quant=None)
            )
            if not same:
                raise ValueError(
                    "engine speculation is shared-pool self-speculation: "
                    "draft_cfg must match the target architecture and "
                    "cache format (only quant may differ)"
                )
        self._spec_gamma = spec_gamma
        self.draft_params = draft_params
        self.paged = paged
        self.cfg = dataclasses.replace(cfg, paged=paged)
        # Dense prefill bridge shares max_seq with the paged logical view.
        self.dense_cfg = dataclasses.replace(cfg, paged=None, max_seq=paged.max_len)
        self.params = params
        self.max_slots = max_slots
        self.eos_id = eos_id

        model = TransformerLM(self.cfg, decode=True)
        spec = decode_cache_spec(model, max_slots)
        self.cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), spec)
        self._layer_names = [f"layer_{i}" for i in range(cfg.num_layers)]

        # Single-token decode steps are built lazily per (filtered,
        # want_lp) — like _block_fn — so the common greedy/temperature
        # path never compiles the top-k/top-p sort and never computes the
        # [slots, vocab] log-softmax that only logprobs requests read
        # (jit programs compile on first use: a variant that is never
        # requested costs nothing).
        #
        # The cache is donated: the engine reassigns self.cache from the
        # step's output, so the input pool buffers are dead the moment the
        # call is issued — without donation every step transiently holds
        # TWO copies of every layer's page pool in HBM (a pool sized near
        # HBM capacity would OOM at the first step) and pays a pool-sized
        # copy.  Host-side .at[slot].set bookkeeping always runs on the
        # returned tree, never the donated argument.
        self._step_fns: dict = {}
        # Decode blocks (decode_block > 1): when the engine is in pure
        # decode — no admission work, every slot past prefill — the host
        # dispatches ONE program that scans T exact single-token steps
        # (same math, T fresh subkeys), then consumes/rewinds on sync.
        # Each dispatch costs one host round-trip instead of T, which is
        # the serving bottleneck at small batch (per-step dispatch is
        # ~100us on a local TPU VM and ~90ms through this relay).  Jitted
        # per (T, filtered) lazily; T down-buckets by powers of two so at
        # most O(log decode_block) programs ever compile.
        self._decode_block = decode_block
        self._decode_model = model
        self._block_fns: dict = {}
        # ALL prefill runs through the multi-token CACHED append (the
        # speculative verifier's path): each chunk attends against the
        # K/V of every previous chunk via position masks, so a prompt can
        # be consumed across several bounded dispatches — or one.
        self._dense_chunk = TransformerLM(
            self.dense_cfg, decode=True, append_mode="cached"
        )

        if spec_gamma > 0:
            draft_model = TransformerLM(
                dataclasses.replace(draft_cfg, paged=paged), decode=True
            )
            # Local alias: the jitted closure must not capture self.
            layer_names = self._layer_names
            gamma = spec_gamma

            @functools.partial(jax.jit, donate_argnums=(2,))
            def spec_round(
                params, dparams, cache, tokens, positions, temps, topks,
                topps, key,
            ):
                """One speculative round for every slot at once.

                tokens/positions: [slots, 1] (positions = each row's
                current length L).  gamma draft steps propose
                d_1..d_gamma per slot (writing draft K/V at L..L+gamma-1),
                then ONE (gamma+1)-token target pass scores
                [last, d_1..d_gamma] at L..L+gamma — overwriting every
                draft-written slot with exact target K/V, which is what
                makes the shared pool sound.

                Greedy slots (temp <= 0) use longest-agreeing-prefix
                verification (output exactly the greedy decode); sampled
                slots use Leviathan/Chen acceptance-rejection over the
                SAME per-slot temperature/top-k/top-p filter the ordinary
                step applies (accept d w.p. min(1, P(d)/Q(d)); first
                rejection resamples the residual max(0, P-Q), full accept
                samples the bonus from P) — marginally exact filtered
                target sampling, mixed freely in one batch.

                Returns (emitted [slots, gamma+1], a [slots], cache):
                row s's round tokens are emitted[s, :a[s]+1]; length
                rewind is host bookkeeping.
                """
                kd, ka, kt = jax.random.split(key, 3)
                sampling = temps > 0  # [slots]
                safe_t = jnp.where(sampling, temps, 1.0)[:, None]

                def d_step(carry, i):
                    c, tok = carry
                    logits, mut = draft_model.apply(
                        {"params": dparams, "cache": c},
                        tok,
                        positions + i,
                        mutable=["cache"],
                    )
                    row = logits[:, -1, :]
                    greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
                    filt = filter_top_k_top_p(row / safe_t, topks, topps)
                    samp = jax.random.categorical(
                        jax.random.fold_in(kd, i), filt
                    ).astype(jnp.int32)
                    nxt = jnp.where(sampling, samp, greedy)[:, None]
                    q = jax.nn.softmax(filt, axis=-1)  # draft dist Q_i
                    return (mut["cache"], nxt), (nxt[:, 0], q)

                (cache, _), (props_t, q_t) = jax.lax.scan(
                    d_step, (cache, tokens), jnp.arange(gamma)
                )
                props = props_t.T  # [slots, gamma]
                qs = jnp.moveaxis(q_t, 0, 1)  # [slots, gamma, vocab]
                # The draft advanced every row's seq_lens to L+gamma;
                # rewind to L so the verify append writes L..L+gamma.
                L = positions[:, 0]
                cache = {
                    name: {
                        **cache[name],
                        "attn": {**cache[name]["attn"], "seq_lens": L},
                    }
                    for name in layer_names
                }
                block = jnp.concatenate([tokens, props], axis=1)
                block_pos = positions + jnp.arange(gamma + 1)[None, :]
                v_logits, mut = model.apply(
                    {"params": params, "cache": cache},
                    block,
                    block_pos,
                    mutable=["cache"],
                )  # [slots, gamma+1, vocab]
                slots, vocab = v_logits.shape[0], v_logits.shape[2]
                v_filt = filter_top_k_top_p(
                    (v_logits / safe_t[..., None]).reshape(-1, vocab),
                    jnp.repeat(topks, gamma + 1),
                    jnp.repeat(topps, gamma + 1),
                ).reshape(slots, gamma + 1, vocab)
                p = jax.nn.softmax(v_filt, axis=-1)  # target dist P_j

                # Greedy acceptance: longest prefix agreeing with argmax.
                t_greedy = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
                match_g = (props == t_greedy[:, :gamma]).astype(jnp.int32)
                a_g = jnp.sum(jnp.cumprod(match_g, axis=1), axis=1)
                # Sampling acceptance-rejection.
                p_d = jnp.take_along_axis(
                    p[:, :gamma], props[..., None], axis=-1
                )[..., 0]
                q_d = jnp.take_along_axis(qs, props[..., None], axis=-1)[
                    ..., 0
                ]
                u = jax.random.uniform(ka, (slots, gamma))
                accept = (u * q_d < p_d).astype(jnp.int32)
                a_s = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
                a = jnp.where(sampling, a_s, a_g)  # [slots]

                # Tail token at position a: correction/bonus.  Sampled
                # slots draw from the residual max(0, P_a - Q_a) (full
                # accept: Q_gamma := 0 so the residual is P_gamma itself).
                p_a = jnp.take_along_axis(p, a[:, None, None], axis=1)[:, 0]
                qs_pad = jnp.concatenate(
                    [qs, jnp.zeros((slots, 1, vocab), qs.dtype)], axis=1
                )
                q_a = jnp.take_along_axis(qs_pad, a[:, None, None], axis=1)[
                    :, 0
                ]
                resid = jnp.where(
                    (a < gamma)[:, None], jnp.clip(p_a - q_a, min=0.0), p_a
                )
                norm = jnp.sum(resid, axis=-1, keepdims=True)
                tail_p = jnp.where(norm > 0, resid / norm, p_a)
                tail_samp = jax.random.categorical(
                    kt, jnp.log(tail_p)
                ).astype(jnp.int32)
                tail_greedy = jnp.take_along_axis(t_greedy, a[:, None], 1)[
                    :, 0
                ]
                tail = jnp.where(sampling, tail_samp, tail_greedy)
                idxs = jnp.arange(gamma + 1)[None, :]
                props_pad = jnp.concatenate(
                    [props, jnp.zeros((slots, 1), jnp.int32)], axis=1
                )
                emitted = jnp.where(idxs < a[:, None], props_pad, tail[:, None])
                return emitted, a, mut["cache"]

            # Plain greedy round — no filter sorts, no softmaxes, no
            # stacked Q distributions.  Same step_plain rationale: a spec
            # engine serving only greedy requests (the CLI default) must
            # not pay the sampler machinery every round; _spec_step
            # dispatches host-side on whether any active slot samples.
            @functools.partial(jax.jit, donate_argnums=(2,))
            def spec_round_plain(params, dparams, cache, tokens, positions):
                def d_step(carry, i):
                    c, tok = carry
                    logits, mut = draft_model.apply(
                        {"params": dparams, "cache": c},
                        tok,
                        positions + i,
                        mutable=["cache"],
                    )
                    nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(
                        jnp.int32
                    )[:, None]
                    return (mut["cache"], nxt), nxt[:, 0]

                (cache, _), props_t = jax.lax.scan(
                    d_step, (cache, tokens), jnp.arange(gamma)
                )
                props = props_t.T
                L = positions[:, 0]
                cache = {
                    name: {
                        **cache[name],
                        "attn": {**cache[name]["attn"], "seq_lens": L},
                    }
                    for name in layer_names
                }
                block = jnp.concatenate([tokens, props], axis=1)
                block_pos = positions + jnp.arange(gamma + 1)[None, :]
                v_logits, mut = model.apply(
                    {"params": params, "cache": cache},
                    block,
                    block_pos,
                    mutable=["cache"],
                )
                slots = v_logits.shape[0]
                t_greedy = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
                match = (props == t_greedy[:, :gamma]).astype(jnp.int32)
                a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                tail = jnp.take_along_axis(t_greedy, a[:, None], 1)[:, 0]
                props_pad = jnp.concatenate(
                    [props, jnp.zeros((slots, 1), jnp.int32)], axis=1
                )
                emitted = jnp.where(
                    jnp.arange(gamma + 1)[None, :] < a[:, None],
                    props_pad,
                    tail[:, None],
                )
                return emitted, a, mut["cache"]

            self._spec_round = spec_round
            self._spec_round_plain = spec_round_plain
        # Host-visible speculation counters (also exported via metrics):
        # acceptance rate = accepted / proposed, the gamma-tuning signal.
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Optimistic admission: allocate prompt pages only at admission and
        # grow generation pages on demand; a pool shortage preempts the
        # NEWEST ready slot (recompute-resume via the effective prompt).
        self._optimistic = admission == "optimistic"
        self.preemptions = 0
        self._seq_counter = 0

        # Page 0 is the idle-slot scratch target — never allocated.
        self.free_pages: deque[int] = deque(range(1, paged.num_pages))
        self.slots: list[Optional[Request]] = [None] * max_slots
        self._slot_pages: list[list[int]] = [[] for _ in range(max_slots)]
        self._slot_last: list[int] = [0] * max_slots  # last emitted token
        self._slot_len: list[int] = [0] * max_slots  # consumed positions
        self._slot_temp: list[float] = [0.0] * max_slots  # 0 = greedy
        # Per-slot adapter id (-1 = base model); traced into the step so
        # slots switch adapters with no recompile (multi-LoRA serving).
        self._slot_aid: list[int] = [-1] * max_slots
        # Per-slot sampler restrictions; vocab / 1.0 mean "off" so idle
        # slots are no-ops in the shared filter.
        self._slot_topk: list[int] = [cfg.vocab_size] * max_slots
        self._slot_topp: list[float] = [1.0] * max_slots
        # Per-slot sparse logit bias: up to MAX_BIAS (id, value) pairs,
        # padded with (0, 0.0) — a zero bias is a no-op whatever the id.
        self._slot_bias_ids: list[list[int]] = [
            [0] * self.MAX_BIAS for _ in range(max_slots)
        ]
        self._slot_bias_vals: list[list[float]] = [
            [0.0] * self.MAX_BIAS for _ in range(max_slots)
        ]
        # Logical index of _slot_pages[s][0] in the device table row (> 0
        # once leading pages were reclaimed by a sliding window).
        self._slot_page_base: list[int] = [0] * max_slots
        # Logical page count PUBLISHED to the device table per slot.  The
        # full allocated chain includes not-yet-written generation pages;
        # publishing those at admission would make the kernel's pipeline
        # fetch them every step (pl.when gates compute, not the block
        # copies), so table entries stay at scratch page 0 until the write
        # frontier reaches them — per-row traffic is O(len), not
        # O(allocated).
        self._slot_visible: list[int] = [0] * max_slots
        self._slot_seq: list[int] = [0] * max_slots
        # A reserved slot decodes only after its prefill job grafted it
        # (chunked prefill spans several step() calls; until ready the
        # slot behaves exactly like an idle one in the jitted step).
        self._slot_ready: list[bool] = [False] * max_slots
        self._pending: list[dict] = []  # in-flight prefill jobs
        # Private pages of not-yet-grafted requests: the prefix-sharing
        # match refuses them (see _match_prefix) until _activate removes
        # them post-graft.
        self._pending_pages: set[int] = set()
        self.queue: deque[Request] = deque()
        # submit() is documented callable from other threads (the serving
        # topology: an RPC handler enqueues while the owner thread loops
        # step(), and MetricsServer scrapes concurrently) — the queue and
        # gauge updates are the shared state, so both sides take this lock.
        # Reentrant: submit() updates gauges while already holding it.
        self._lock = threading.RLock()
        self._next_rid = 0
        self._prefill_cache: dict[int, Any] = {}
        self._rng = jax.random.PRNGKey(0) if rng is None else rng
        self.metrics = metrics
        # Prefix sharing: K/V are a deterministic function of (params,
        # prompt tokens), so FULL pages covering a common prompt prefix are
        # byte-identical across requests and can be shared read-only —
        # decode only ever writes at the growing frontier, which lives in a
        # private page.  The registry is a per-page trie keyed
        # (parent_page, page_chunk) — O(prompt) to match/register, vs
        # O(prompt²/page_size) for whole-prefix keys — with -1 as the root
        # parent.  Pages are refcounted and registry links die with their
        # last user (this serves the concurrent shared-system-prompt case,
        # not a persistent prompt cache; freed-parent links cannot go
        # stale: any sequence holding a child page holds its whole prefix
        # chain, so a child always dies no later than its parent).
        self.prefix_sharing = prefix_sharing
        self._page_refs: dict[int, int] = {}
        self._prefix_pages: dict[tuple[int, tuple], int] = {}
        self._page_keys: dict[int, list[tuple[int, tuple]]] = {}
        # Keys in which a page is the PARENT: windowed reclamation can free
        # a parent before its children, and a freed id may be reallocated
        # and re-registered with different content — surviving child links
        # would then form a stale chain, so they die with the parent.
        self._child_keys: dict[int, list[tuple[int, tuple]]] = {}

    # ------------------------------------------------------------- admission

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        adapter: Optional[int] = None,
        logprobs: bool = False,
        stop: Optional[list] = None,
        logit_bias: Optional[dict] = None,
    ) -> Request:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if stop is not None:
            stop = [[int(t) for t in seq] for seq in stop]
            if not stop or any(not seq for seq in stop):
                raise ValueError(
                    "stop must be a non-empty list of non-empty "
                    "token-id sequences"
                )
            # _hit_stop is O(num_stops x stop_len) Python compares on the
            # owner thread per emitted token; an uncapped list from the
            # unauthenticated HTTP endpoint could stall the serving loop
            # for every tenant, so cap like logit_bias caps MAX_BIAS.
            if len(stop) > self.MAX_STOPS:
                raise ValueError(
                    f"at most {self.MAX_STOPS} stop sequences, got {len(stop)}"
                )
            too_long = [seq for seq in stop if len(seq) > self.MAX_STOP_LEN]
            if too_long:
                raise ValueError(
                    f"stop sequences are capped at {self.MAX_STOP_LEN} "
                    f"tokens, got one of length {max(len(s) for s in too_long)}"
                )
        if logit_bias is not None:
            logit_bias = {int(t): float(v) for t, v in logit_bias.items()}
            if not logit_bias or len(logit_bias) > self.MAX_BIAS:
                raise ValueError(
                    f"logit_bias must have 1..{self.MAX_BIAS} entries, "
                    f"got {len(logit_bias)}"
                )
            bad = [t for t in logit_bias if not 0 <= t < self.cfg.vocab_size]
            if bad:
                raise ValueError(f"logit_bias ids out of vocab range: {bad}")
            if self._spec_gamma:
                # The round's draft/verify acceptance math scores the
                # UNBIASED distributions; biasing only the emitted pick
                # would break the exactness guarantee.
                raise ValueError(
                    "logit_bias is not supported on a speculative engine"
                )
        if logprobs and self._spec_gamma:
            # The speculative round emits accepted draft tokens without
            # materializing their target log-softmax; scoring them would
            # need an extra pass per round.  Pick one per engine.
            raise ValueError(
                "logprobs is not supported on a speculative engine "
                "(spec_gamma > 0)"
            )
        if adapter is not None:
            if not self.cfg.lora_serve:
                raise ValueError(
                    "adapter requires an engine built with cfg.lora_serve"
                )
            if not 0 <= adapter < self.cfg.lora_serve:
                raise ValueError(
                    f"adapter must be in [0, {self.cfg.lora_serve}), "
                    f"got {adapter}"
                )
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and not 1 <= top_k <= self.cfg.vocab_size:
            raise ValueError(
                f"top_k must be in [1, vocab_size={self.cfg.vocab_size}], "
                f"got {top_k}"
            )
        if top_p is not None and not 0 < top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # Speculative rounds write up to gamma positions past the accepted
        # point before the host rewinds, so every capacity bound carries
        # that headroom (= models/speculative.py's max_seq check).
        need = len(prompt) + max_new_tokens + self._spec_gamma
        if need > self.paged.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens}"
                + (
                    f" + spec headroom {self._spec_gamma}"
                    if self._spec_gamma
                    else ""
                )
                + f" exceeds paged max_len {self.paged.max_len}"
            )
        # Admissibility, not just addressability: the request must fit the
        # ALLOCATABLE pool (page 0 is reserved), else it would block the
        # FIFO head forever.
        allocatable = (self.paged.num_pages - 1) * self.paged.page_size
        if need > allocatable:
            raise ValueError(
                f"request needs {need} cache slots but the pool only ever "
                f"has {allocatable} ({self.paged.num_pages - 1} allocatable "
                f"pages x {self.paged.page_size})"
            )
        with self._lock:
            req = Request(
                prompt, max_new_tokens, temperature, top_k, top_p,
                adapter=adapter, logprobs=logprobs, stop=stop,
                logit_bias=logit_bias,
                rid=self._next_rid, submitted_at=time.monotonic(),
            )
            self._next_rid += 1
            self.queue.append(req)
            # Scrapes happen on the MetricsServer thread: reflect queue
            # pressure immediately, not at the owner's next step().
            self._update_gauges()
        return req

    def cancel(self, req: Request) -> bool:
        """Stop generating for ``req`` (the client went away — the HTTP
        front-end calls this on disconnect/timeout so an abandoned
        request stops burning chip time).  Thread-safe like submit().

        A still-queued request finishes right here (it holds no pages);
        an in-flight one is marked and the owner thread tears it down at
        its next step boundary — slot, pages, and prefix refcounts all
        return through the ordinary _clear_slot path, so the pool stays
        exact.  Returns False if the request had already finished."""
        with self._lock:
            if req.done:
                return False
            req.cancelled = True
            try:
                self.queue.remove(req)
            except ValueError:
                pass  # admitted (slot or mid-prefill): next step cleans up
            else:
                req.done = True
            self._update_gauges()
            return True

    def _prefill_chunk_fn(self, chunk: int, batch: int):
        """Jitted CHUNK prefill: one multi-token cached append of ``chunk``
        tokens at traced offset pos0 into a carried dense cache.  One
        compiled program per (chunk, batch) pair serves every chunk index
        of every bucket (the unchunked path is simply chunk == bucket).
        Cached on THIS instance (a process-global lru_cache would pin the
        engine — params tree and page pools included — beyond its
        lifetime).  The carried cache is donated: the host rebinds
        job["cache"] from the output, so without donation every chunk
        would copy the whole [batch, max_len] dense cache."""
        key = (chunk, batch)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn

        def run(params, cache, tokens, pos0, last_idx, aids):
            pos = jnp.broadcast_to(
                pos0 + jnp.arange(chunk)[None, :], (batch, chunk)
            )
            logits, mut = self._dense_chunk.apply(
                {"params": params, "cache": cache}, tokens, pos,
                adapter_ids=aids,
                mutable=["cache"],
            )
            # Each row's true-last-position logits, valid only when
            # last_idx falls inside this chunk (the host keeps the row
            # from the covering chunk).
            sel = jnp.clip(last_idx - pos0, 0, chunk - 1)
            return logits[jnp.arange(batch), sel], mut["cache"]

        fn = jax.jit(run, donate_argnums=(1,))
        self._prefill_cache[key] = fn
        return fn

    def _start_prefill(self, items: list[tuple[int, "Request", list[int], int]]):
        """Create one prefill JOB for a same-length-bucket admission group.

        Length padding is sound because attention is causal — positions
        >= plen cannot influence logits[plen-1] — and _graft copies only
        rows [:plen] into pages, so the padded tail's garbage K/V never
        leaves the throwaway dense cache.  The batch dim is padded to a
        power of two (repeating the first prompt; its extra rows are
        discarded), so an admission burst of N prompts costs ONE dispatch
        per chunk instead of N serial prefills, and the number of
        compiled prefill programs stays O(log max_len * log max_slots).

        Without ``prefill_chunk`` the job is a single full-bucket chunk
        and completes on its first advance (same step() call it was
        admitted in); with chunking, step() advances ONE chunk per call,
        so active slots stall at most one chunk's compute per step while
        a long prompt streams in.
        """
        # Effective prompts: resumed (preempted) requests re-prefill
        # their original prompt PLUS what they had already generated.
        prompts = [it[1].prompt + it[1].tokens for it in items]
        longest = max(len(p) for p in prompts)
        bucket = min(1 << (longest - 1).bit_length(), self.paged.max_len)
        chunk = min(self._prefill_chunk or bucket, bucket)
        n = len(prompts)
        batch = 1 << (n - 1).bit_length()
        rows = [p + [0] * (bucket - len(p)) for p in prompts]
        rows += [rows[0]] * (batch - n)
        last_idx = [len(p) - 1 for p in prompts] + [0] * (batch - n)
        aids = [
            it[1].adapter if it[1].adapter is not None else -1 for it in items
        ]
        aids += [aids[0]] * (batch - n)  # pad rows are discarded anyway
        spec = decode_cache_spec(self._dense_chunk, batch)
        self._pending.append(
            {
                "items": items,
                "bucket": bucket,
                "chunk": chunk,
                "batch": batch,
                "rows": jnp.asarray(rows, jnp.int32),
                "last_idx_host": last_idx,
                "last_idx": jnp.asarray(last_idx, jnp.int32),
                "aids": jnp.asarray(aids, jnp.int32),
                "cache": jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), spec
                ),
                "pos": 0,
                "logits": [None] * n,
            }
        )

    def _advance_prefill(self, job: dict) -> bool:
        """Run ONE chunk of a pending prefill job; True when complete."""
        chunk, pos = job["chunk"], job["pos"]
        fn = self._prefill_chunk_fn(chunk, job["batch"])
        tokens = jax.lax.slice_in_dim(job["rows"], pos, pos + chunk, axis=1)
        logits_rows, job["cache"] = fn(
            self.params,
            job["cache"],
            tokens,
            jnp.asarray(pos, jnp.int32),
            job["last_idx"],
            job["aids"],
        )
        for i in range(len(job["items"])):
            if pos <= job["last_idx_host"][i] < pos + chunk:
                job["logits"][i] = logits_rows[i]
        job["pos"] = pos + chunk
        return job["pos"] >= job["bucket"]

    def _graft(
        self,
        slot: int,
        dense_cache: Any,
        pages: list[int],
        plen: int,
        n_shared: int,
        row_idx: int = 0,
    ):
        """Scatter a prefilled dense cache's rows into the PRIVATE prompt
        pages and point the slot's table/length at the full chain — ONE
        page-indexed scatter per pool per layer (not per page: eager `.at`
        updates are copy-on-write, so per-page updates would round-trip
        the whole pool once per page).

        Shared prefix pages (the first ``n_shared``) are never rewritten:
        a concurrent request is reading them, and K/V from a prefill
        compiled at a different prompt length are not guaranteed bitwise
        identical — rewriting could perturb an in-flight generation.
        Private pages are written whole; tail slots past plen carry zeros,
        which later appends overwrite before any masked read can see
        them."""
        ps = self.paged.page_size
        n_cover = math.ceil(plen / ps)
        # Publish only the pages the NEXT decode step can touch: those
        # covering positions [0, plen] (the first decode write lands at
        # position plen; a speculative round writes up to plen+gamma).
        # The rest of the chain stays at scratch page 0 until the
        # frontier reaches it (_extend_frontier) so the kernel's pipeline
        # never streams unwritten generation pages.
        n_publish = min((plen + self._spec_gamma) // ps + 1, len(pages))
        row = np.zeros((self.paged.max_pages_per_seq,), np.int32)
        row[:n_publish] = pages[:n_publish]
        self._slot_visible[slot] = n_publish
        lo_tok = n_shared * ps  # first private-covered token position
        n_priv_cover = n_cover - n_shared
        cover = jnp.asarray(pages[n_shared:n_cover], jnp.int32)
        pad = n_cover * ps - plen
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            src = dense_cache[name]["attn"]

            def paged_rows(slab):
                rows = slab[row_idx, lo_tok:plen]
                if pad:
                    rows = jnp.pad(
                        rows, ((0, pad),) + ((0, 0),) * (rows.ndim - 1)
                    )
                return rows.reshape(n_priv_cover, ps, *rows.shape[1:])

            new_att = {
                **att,
                "page_table": att["page_table"].at[slot].set(jnp.asarray(row)),
                "seq_lens": att["seq_lens"].at[slot].set(plen),
            }
            if n_priv_cover > 0:
                new_att["pool_key"] = (
                    att["pool_key"].at[cover].set(paged_rows(src["cached_key"]))
                )
                new_att["pool_value"] = (
                    att["pool_value"].at[cover].set(paged_rows(src["cached_value"]))
                )
                if "pool_key_scale" in att:  # int8 KV: scales ride along
                    new_att["pool_key_scale"] = (
                        att["pool_key_scale"]
                        .at[cover]
                        .set(paged_rows(src["cached_key_scale"]))
                    )
                    new_att["pool_value_scale"] = (
                        att["pool_value_scale"]
                        .at[cover]
                        .set(paged_rows(src["cached_value_scale"]))
                    )
            self.cache[name]["attn"] = new_att

    def _clear_slot(self, slot: int):
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "page_table": att["page_table"].at[slot].set(0),
                "seq_lens": att["seq_lens"].at[slot].set(0),
            }
        for page in self._slot_pages[slot]:
            self._release_page(page)
        self._slot_pages[slot] = []
        self.slots[slot] = None
        self._slot_last[slot] = 0
        self._slot_len[slot] = 0
        self._slot_temp[slot] = 0.0
        self._slot_topk[slot] = self.cfg.vocab_size
        self._slot_topp[slot] = 1.0
        self._slot_bias_ids[slot] = [0] * self.MAX_BIAS
        self._slot_bias_vals[slot] = [0.0] * self.MAX_BIAS
        self._slot_aid[slot] = -1
        self._slot_page_base[slot] = 0
        self._slot_visible[slot] = 0
        self._slot_ready[slot] = False

    def _release_page(self, page: int) -> None:
        """Drop one reference; at zero, tear down every trie link touching
        the page (keys registered FOR it and keys in which it is the
        PARENT — a freed id can be reallocated and re-registered with
        different content, so a surviving child link would let a later
        prompt walk into another request's K/V) and return it to the
        pool.  The ONE page-free path: _clear_slot and windowed
        reclamation both come through here.  Runs under the engine lock:
        _update_gauges iterates _page_refs from the scraping/submitting
        threads, and a resize here mid-iteration would crash them."""
        with self._lock:
            self._page_refs[page] -= 1
            if self._page_refs[page] > 0:
                return
            del self._page_refs[page]
            for key in self._page_keys.pop(page, []):
                self._prefix_pages.pop(key, None)
            for key in self._child_keys.pop(page, []):
                child = self._prefix_pages.pop(key, None)
                if child is not None:
                    keys = self._page_keys.get(child)
                    if keys and key in keys:
                        keys.remove(key)
            self.free_pages.append(page)

    @staticmethod
    def _trie_root(adapter: Optional[int]) -> int:
        """Root pseudo-parent for the prefix trie: K/V are a function of
        (params, adapter, tokens), so each adapter gets its own root (-1 =
        base model, -(2+i) = adapter i) and chains never cross adapters.
        Pseudo-roots are never real pages, so they are never freed and
        take no _child_keys bookkeeping (their links die with the child
        page, exactly like the old -1 root's)."""
        return -1 if adapter is None else -(2 + adapter)

    def _match_prefix(
        self,
        prompt: list[int],
        bucket: int,
        burst_pages: dict[int, int],
        adapter: Optional[int] = None,
    ) -> list[int]:
        """Longest chain of live registered pages whose token chunks equal
        this prompt's leading FULL pages (trie walk: O(prompt)).

        A page may only be shared once its content is guaranteed written
        before this request's first decode step: pages of ACTIVATED
        requests always qualify; pages of a still-pending prefill job do
        NOT (the owner's graft is deferred — sharing them would decode
        against zeros), EXCEPT pages admitted in this same burst with the
        same length bucket — those land in the same job, whose _activate
        grafts every item before any of them decodes.
        """
        ps = self.paged.page_size
        pages: list[int] = []
        parent = self._trie_root(adapter)
        for i in range(len(prompt) // ps):
            chunk = tuple(prompt[i * ps : (i + 1) * ps])
            page = self._prefix_pages.get((parent, chunk))
            if page is None:
                break
            if page in burst_pages:
                if burst_pages[page] != bucket:
                    break  # different bucket -> different job -> unsafe
            elif page in self._pending_pages:
                break  # owner's job from an earlier step not grafted yet
            pages.append(page)
            parent = page
        return pages

    def _admit(self) -> list[Request]:
        """Admit queued requests into free slots; returns any that finished
        at admission already (EOS or max_new_tokens == 1 on the prefill
        token) so step() can report them.

        Two phases so an admission BURST costs one prefill dispatch per
        length bucket, not one per request (serial per-request prefill was
        the churn-throughput hole, VERDICT r2 weak #5): phase 1 assigns
        slots/pages/trie links for everything that fits, phase 2 batches
        the dense prefills by length bucket and grafts each row.
        """
        admitted: list[tuple[int, Request, list[int], int]] = []
        burst_pages: dict[int, int] = {}  # page -> length bucket, this burst
        for slot in range(self.max_slots):
            # Queue peek/pop under the lock (submit() appends from other
            # threads); everything after the pop touches owner-only state.
            with self._lock:
                # A cancel() racing an eviction can leave a cancelled
                # request at the queue head (see _evict_slot); finish it
                # here instead of prefetching for a dead client.
                while self.queue and self.queue[0].cancelled:
                    dead = self.queue.popleft()
                    dead.done = True
                if self.slots[slot] is not None or not self.queue:
                    continue
                req = self.queue[0]
                # The EFFECTIVE prompt: original tokens plus anything a
                # previous occupancy already generated (recompute-resume
                # after preemption — empty for fresh requests, and always
                # empty under reserve admission).
                eff = req.prompt + req.tokens
                plen = len(eff)
                bucket = min(1 << (plen - 1).bit_length(), self.paged.max_len)
                if self._optimistic:
                    # Prompt pages + the first decode write (+ spec
                    # headroom); generation pages are allocated on demand
                    # by _ensure_frontier, preempting newer slots when
                    # the pool runs dry.
                    n_pages = math.ceil(
                        (plen + 1 + self._spec_gamma) / self.paged.page_size
                    )
                else:
                    # Reserve admission never preempts, so req.tokens is
                    # always empty here and plen == len(req.prompt): the
                    # worst-case chain, allocated up front.
                    n_pages = math.ceil(
                        (plen + req.max_new_tokens + self._spec_gamma)
                        / self.paged.page_size
                    )
                shared = (
                    self._match_prefix(
                        eff, bucket, burst_pages, req.adapter
                    )
                    if self.prefix_sharing
                    else []
                )
                n_private = n_pages - len(shared)
                if n_private > len(self.free_pages):
                    break  # FIFO: wait for pages rather than starving the head
                self.queue.popleft()
                # Refcounts and free-page moves stay under the lock too:
                # _update_gauges (called from submit() on another thread)
                # iterates _page_refs, and an unlocked resize here would
                # crash that iteration mid-scrape.
                private = [self.free_pages.popleft() for _ in range(n_private)]
                pages = shared + private
                for page in shared:
                    self._page_refs[page] += 1
                for page in private:
                    self._page_refs[page] = 1
                    # Ungrafted until _activate: shareable within this
                    # burst's same-bucket group only.
                    burst_pages[page] = bucket
                    self._pending_pages.add(page)
                if self.prefix_sharing:
                    # Register this prompt's full pages (shared or fresh) as
                    # trie links so later same-prefix requests can ride them
                    # — including requests admitted in this SAME burst: a
                    # same-burst match is sound because every shared page's
                    # content is written by its first owner's graft before
                    # any decode step reads it.
                    ps = self.paged.page_size
                    parent = self._trie_root(req.adapter)
                    for i in range(plen // ps):
                        key = (parent, tuple(eff[i * ps : (i + 1) * ps]))
                        if key not in self._prefix_pages:
                            self._prefix_pages[key] = pages[i]
                            self._page_keys.setdefault(pages[i], []).append(key)
                            if parent >= 0:
                                self._child_keys.setdefault(parent, []).append(key)
                        parent = pages[i]
                self.slots[slot] = req
                self._slot_pages[slot] = pages
                self._slot_seq[slot] = self._seq_counter
                self._seq_counter += 1
            admitted.append((slot, req, pages, len(shared)))

        if not admitted:
            return []
        # Group by length bucket; each group becomes ONE prefill job
        # (advanced chunk-by-chunk from step()).
        groups: dict[int, list[tuple[int, Request, list[int], int]]] = {}
        for item in admitted:
            plen = len(item[1].prompt) + len(item[1].tokens)
            bucket = min(1 << (plen - 1).bit_length(), self.paged.max_len)
            groups.setdefault(bucket, []).append(item)
        for items in groups.values():
            self._start_prefill(items)
        return []

    def _activate(self, job: dict) -> list[Request]:
        """Graft a completed prefill job's K/V into pages, sample each
        request's first token, and mark the slots ready to decode."""
        finished: list[Request] = []
        for row_idx, (slot, req, pages, n_shared) in enumerate(job["items"]):
            # Effective length: a resumed request's prefill covered its
            # original prompt plus the tokens generated before eviction
            # (req.tokens grows below AFTER this is read).
            resumed = bool(req.tokens)
            plen = len(req.prompt) + len(req.tokens)
            self._graft(
                slot, job["cache"], pages, plen, n_shared, row_idx=row_idx
            )
            # Grafted: the private pages are now real K/V and may be
            # prefix-shared by any later request.
            self._pending_pages.difference_update(pages[n_shared:])
            last_logits = job["logits"][row_idx]
            if req.logit_bias:
                # Same semantics as the jitted step: bias what gets
                # PICKED; reported logprobs (below) stay unbiased.
                ids = jnp.asarray(list(req.logit_bias), jnp.int32)
                vals = jnp.asarray(
                    list(req.logit_bias.values()), jnp.float32
                )
                picked_logits = last_logits.at[ids].add(
                    vals.astype(last_logits.dtype)
                )
            else:
                picked_logits = last_logits
            # A greedy slot's token is the argmax regardless of
            # top_k/top_p, so normalize them to "off" — otherwise one
            # greedy+top_k request would drag the whole batch onto the
            # filtered (sorting) step path for zero output change.
            if req.temperature > 0:
                topk = (
                    req.top_k
                    if req.top_k is not None
                    else self.cfg.vocab_size
                )
                topp = req.top_p if req.top_p is not None else 1.0
            else:
                topk, topp = self.cfg.vocab_size, 1.0
            if req.temperature > 0:
                # Same filter math as the jitted step — the admission
                # token must come from the same restricted distribution.
                self._rng, sub = jax.random.split(self._rng)
                filtered = filter_top_k_top_p(
                    (picked_logits / req.temperature)[None, :],
                    jnp.asarray([topk], jnp.int32),
                    jnp.asarray([topp], jnp.float32),
                )
                first = int(jax.random.categorical(sub, filtered[0]))
            else:
                first = int(jnp.argmax(picked_logits))
            if req.logprobs:
                # Same semantics as the jitted steps: the emitted token's
                # logprob under the unscaled model distribution.  Appended
                # BEFORE the token so a streaming snapshot never sees a
                # token without its logprob.
                req.token_logprobs.append(
                    float(
                        _token_logprob(
                            jnp.asarray(last_logits)[None, :],
                            jnp.asarray([first], jnp.int32),
                        )[0]
                    )
                )
            req.tokens.append(first)
            self._slot_last[slot] = first
            self._slot_len[slot] = plen
            self._slot_temp[slot] = req.temperature
            self._slot_topk[slot] = topk
            self._slot_topp[slot] = topp
            if req.logit_bias:
                ids_l = list(req.logit_bias)
                vals_l = list(req.logit_bias.values())
                pad = self.MAX_BIAS - len(ids_l)
                self._slot_bias_ids[slot] = ids_l + [0] * pad
                self._slot_bias_vals[slot] = vals_l + [0.0] * pad
            else:
                self._slot_bias_ids[slot] = [0] * self.MAX_BIAS
                self._slot_bias_vals[slot] = [0.0] * self.MAX_BIAS
            self._slot_aid[slot] = (
                req.adapter if req.adapter is not None else -1
            )
            self._slot_ready[slot] = True
            if self.metrics:
                # A preemption resume re-activates the SAME client
                # request: counting it again would skew requests_total
                # exactly in the overload regime it helps diagnose.
                if not resumed:
                    self.metrics.requests.inc()
                    self.metrics.wait_seconds.observe(
                        time.monotonic() - req.submitted_at
                    )
                self.metrics.tokens.inc()
            self._maybe_finish(slot)
            if req.done:
                finished.append(req)
        return finished

    @staticmethod
    def _hit_stop(req: Request) -> bool:
        """True when the output's tail equals one of the request's stop
        sequences (or already did): truncates the matched suffix (and its
        logprobs) and LATCHES ``req.stopped`` — the evidence is deleted,
        so the flag carries the verdict to _maybe_finish."""
        if req.stopped:
            return True
        if not req.stop:
            return False
        for seq in req.stop:
            n = len(seq)
            if n and len(req.tokens) >= n and req.tokens[-n:] == seq:
                del req.tokens[-n:]
                if req.logprobs:
                    del req.token_logprobs[len(req.tokens):]
                req.stopped = True
                return True
        return False

    def _maybe_finish(self, slot: int):
        req = self.slots[slot]
        if req is None:
            return
        if (
            req.cancelled
            or len(req.tokens) >= req.max_new_tokens
            or (
                self.eos_id is not None
                and req.tokens
                and req.tokens[-1] == self.eos_id
            )
            or self._hit_stop(req)
        ):
            req.done = True
            self._clear_slot(slot)

    # ----------------------------------------------------------------- steps

    @staticmethod
    def _variant_names(filtered: bool, biased: bool) -> list[str]:
        """Keyword names of the optional per-slot arrays a (filtered,
        biased) step/block variant takes, in signature order — the ONE
        place the ordering lives (builders zip *rest against it, call
        sites assemble arrays with _variant_arrays)."""
        names = []
        if filtered:
            names += ["topks", "topps"]
        if biased:
            names += ["bias_ids", "bias_vals"]
        return names

    def _variant_arrays(self, filtered: bool, biased: bool) -> list:
        """Device arrays matching _variant_names, built from slot state."""
        arrays = []
        if filtered:
            arrays += [
                jnp.asarray(self._slot_topk, jnp.int32),
                jnp.asarray(self._slot_topp, jnp.float32),
            ]
        if biased:
            arrays += [
                jnp.asarray(self._slot_bias_ids, jnp.int32),
                jnp.asarray(self._slot_bias_vals, jnp.float32),
            ]
        return arrays

    def _step_fn(self, filtered: bool, want_lp: bool, biased: bool = False):
        """Build (lazily, once per (filtered, want_lp, biased)) the jitted
        single-token decode step.  ``filtered`` compiles the top-k/top-p
        sort in; ``want_lp`` compiles the [slots, vocab] log-softmax +
        gather whose result logprobs requests read (without it the step
        returns a zeros placeholder so the host consumption code stays
        uniform); ``biased`` compiles the [slots, MAX_BIAS] scatter-add
        of per-slot logit biases onto the picking row (reported logprobs
        stay unbiased)."""
        key_ = (filtered, want_lp, biased)
        if key_ in self._step_fns:
            return self._step_fns[key_]
        model = self._decode_model

        # Variant signatures omit the arrays their feature compiled out:
        # an unused jit argument is still transferred every dispatch, and
        # the greedy/temperature-only path (the common case) shouldn't
        # pay host->device uploads for filters/biases it never applies.
        def _core(params, cache, tokens, positions, temps, aids, key,
                  topks=None, topps=None, bias_ids=None, bias_vals=None):
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                tokens,
                positions,
                adapter_ids=aids,
                mutable=["cache"],
            )
            row = logits[:, -1, :]
            pick = row
            if biased:
                rows = jnp.arange(row.shape[0])[:, None]
                pick = row.at[rows, bias_ids].add(
                    bias_vals.astype(row.dtype)
                )
            greedy = jnp.argmax(pick, axis=-1).astype(jnp.int32)
            # One categorical over the batch samples each row independently;
            # temp<=0 rows take the argmax (their scaled logits are unused).
            scaled = pick / jnp.where(temps > 0, temps, 1.0)[:, None]
            if filtered:
                scaled = filter_top_k_top_p(scaled, topks, topps)
            sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            lps = (
                _token_logprob(row, nxt)
                if want_lp
                else jnp.zeros(nxt.shape, jnp.float32)
            )
            return nxt, lps, mut["cache"]

        extra = self._variant_names(filtered, biased)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(params, cache, tokens, positions, temps, aids, key, *rest):
            return _core(
                params, cache, tokens, positions, temps, aids, key,
                **dict(zip(extra, rest)),
            )

        self._step_fns[key_] = step
        return step

    def _block_fn(self, T: int, filtered: bool, want_lp: bool, biased: bool = False):
        """Build (lazily, once per (T, filtered, want_lp, biased)) the jitted T-step decode
        block: a lax.scan of T exact single-token decode steps — same
        model apply, same per-slot sampling, a fresh subkey per step — so
        one dispatch advances every active slot T tokens.  Greedy slots
        emit exactly their step-at-a-time decode; sampled slots draw from
        the identical per-step distributions (different key schedule than
        T separate step() calls, same law)."""
        key_ = (T, filtered, want_lp, biased)
        if key_ in self._block_fns:
            return self._block_fns[key_]
        model = self._decode_model

        def _core(params, cache, tokens, positions, temps, aids, key,
                  topks=None, topps=None, bias_ids=None, bias_vals=None):
            def body(carry, k):
                cache, toks, pos = carry
                logits, mut = model.apply(
                    {"params": params, "cache": cache},
                    toks,
                    pos,
                    adapter_ids=aids,
                    mutable=["cache"],
                )
                row = logits[:, -1, :]
                pick = row
                if biased:
                    rows = jnp.arange(row.shape[0])[:, None]
                    pick = row.at[rows, bias_ids].add(
                        bias_vals.astype(row.dtype)
                    )
                greedy = jnp.argmax(pick, axis=-1).astype(jnp.int32)
                scaled = pick / jnp.where(temps > 0, temps, 1.0)[:, None]
                if filtered:
                    scaled = filter_top_k_top_p(scaled, topks, topps)
                sampled = jax.random.categorical(k, scaled).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                lp = (
                    _token_logprob(row, nxt)
                    if want_lp
                    else jnp.zeros(nxt.shape, jnp.float32)
                )
                return (mut["cache"], nxt[:, None], pos + 1), (nxt, lp)

            (cache, _, _), (toks, lps) = jax.lax.scan(
                body, (cache, tokens, positions), jax.random.split(key, T)
            )
            return toks.T, lps.T, cache  # [slots, T]

        # Same variant-signature split as _step_fn: the common path
        # shouldn't upload filter/bias arrays it compiled out.
        extra = self._variant_names(filtered, biased)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def block(params, cache, tokens, positions, temps, aids, key, *rest):
            return _core(
                params, cache, tokens, positions, temps, aids, key,
                **dict(zip(extra, rest)),
            )

        self._block_fns[key_] = block
        return block

    def _block_step(
        self, active: list[int], finished: list[Request], T: int
    ) -> list[Request]:
        """Advance every active slot up to T tokens in ONE dispatch (the
        pure-decode fast path of step()).  A slot that hits EOS/max_new
        mid-block wastes its tail iterations (their K/V writes land past
        the row's final length and are masked forever after the rewind —
        the speculative round's exact discipline); everything the host
        consumes is identical to T single steps."""
        active = self._ensure_frontier(active, T - 1)
        if not active:
            self._update_gauges()
            return finished
        tokens = jnp.asarray(self._slot_last, jnp.int32)[:, None]
        positions = jnp.asarray(self._slot_len, jnp.int32)[:, None]
        temps = jnp.asarray(self._slot_temp, jnp.float32)
        aids = jnp.asarray(self._slot_aid, jnp.int32)
        filtered = any(
            self.slots[s] is not None
            and (
                self._slot_topk[s] < self.cfg.vocab_size
                or self._slot_topp[s] < 1.0
            )
            for s in range(self.max_slots)
        )
        want_lp = any(
            self.slots[s] is not None and self.slots[s].logprobs
            for s in range(self.max_slots)
        )
        biased = any(
            self.slots[s] is not None and self.slots[s].logit_bias
            for s in range(self.max_slots)
        )
        self._rng, sub = jax.random.split(self._rng)
        out, lps, self.cache = self._block_fn(T, filtered, want_lp, biased)(
            self.params, self.cache, tokens, positions, temps, aids, sub,
            *self._variant_arrays(filtered, biased),
        )
        out = np.asarray(out)
        lps = np.asarray(lps)
        emitted_total = 0
        for s in active:
            req = self.slots[s]
            consumed = 0
            for j in range(T):
                tok = int(out[s, j])
                # Logprob BEFORE token: a streaming handler thread that
                # snapshots between the two appends must never see a
                # token whose logprob is missing.
                if req.logprobs:
                    req.token_logprobs.append(float(lps[s, j]))
                req.tokens.append(tok)
                self._slot_last[s] = tok
                consumed += 1
                emitted_total += 1
                if (
                    len(req.tokens) >= req.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or self._hit_stop(req)
                ):
                    break
            self._slot_len[s] += consumed
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        # The block left every row's device length at L+T; re-align to the
        # host truth in one vector write per layer (fresh array per layer
        # — see the identical note in _spec_step re double donation).
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "seq_lens": jnp.array(self._slot_len, jnp.int32),
            }
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(emitted_total)
        self._update_gauges()
        return finished

    def step(self) -> list[Request]:
        """Admit what fits, advance every active slot one token; returns
        every request that finished this step (including ones done at
        admission — EOS/max_new on the prefill token)."""
        if self.metrics:
            with self.metrics.step_seconds.time():
                return self._step_inner()
        return self._step_inner()

    def _step_inner(self) -> list[Request]:
        finished = self._admit()
        # Cancelled slots tear down BEFORE the dispatch (no farewell
        # token).  Only ready slots: a cancelled request mid-prefill
        # keeps its job's slot/pages intact until activation, whose own
        # _maybe_finish call then finishes it (this sweep catches
        # requests cancelled after they were already live).
        for s in range(self.max_slots):
            req = self.slots[s]
            if req is not None and req.cancelled and self._slot_ready[s]:
                self._maybe_finish(s)
                finished.append(req)
        # Advance every in-flight prefill job by ONE chunk (an unchunked
        # job completes right here, in the same step() it was admitted):
        # chunking bounds how long active slots stall per step while a
        # long prompt streams in.
        for job in list(self._pending):
            if self._advance_prefill(job):
                self._pending.remove(job)
                finished.extend(self._activate(job))
        active = [
            s
            for s in range(self.max_slots)
            if self.slots[s] is not None and self._slot_ready[s]
        ]
        if not active:
            self._update_gauges()
            return finished
        if self._spec_gamma:
            return self._spec_step(active, finished)
        if (
            self._decode_block > 1
            and not self._pending  # no prompt mid-stream: keep chunking
            and not self.queue  # admission possible next step: stay fine-grained
        ):
            # Largest power-of-two block that no active slot's remaining
            # budget truncates (so no slot can overrun max_new mid-block).
            room = min(
                self.slots[s].max_new_tokens - len(self.slots[s].tokens)
                for s in active
            )
            T = min(self._decode_block, 1 << max(0, room.bit_length() - 1))
            if T > 1:
                return self._block_step(active, finished, T)
        if self._optimistic:
            # The single-step path's next write (position len) must be
            # addressable; _block_step/_spec_step run their own ensure
            # with their larger lookaheads.
            active = self._ensure_frontier(active, 0)
            if not active:
                self._update_gauges()
                return finished
        tokens = jnp.asarray(self._slot_last, jnp.int32)[:, None]
        positions = jnp.asarray(self._slot_len, jnp.int32)[:, None]
        temps = jnp.asarray(self._slot_temp, jnp.float32)
        aids = jnp.asarray(self._slot_aid, jnp.int32)
        filtered = any(
            self.slots[s] is not None
            and (
                self._slot_topk[s] < self.cfg.vocab_size
                or self._slot_topp[s] < 1.0
            )
            for s in range(self.max_slots)
        )
        want_lp = any(
            self.slots[s] is not None and self.slots[s].logprobs
            for s in range(self.max_slots)
        )
        biased = any(
            self.slots[s] is not None and self.slots[s].logit_bias
            for s in range(self.max_slots)
        )
        self._rng, sub = jax.random.split(self._rng)
        nxt, lps, self.cache = self._step_fn(filtered, want_lp, biased)(
            self.params, self.cache, tokens, positions, temps, aids, sub,
            *self._variant_arrays(filtered, biased),
        )
        nxt = np.asarray(nxt)
        lps = np.asarray(lps)
        for s in active:
            req = self.slots[s]
            tok = int(nxt[s])
            # Logprob BEFORE token (see _block_step note).
            if req.logprobs:
                req.token_logprobs.append(float(lps[s]))
            req.tokens.append(tok)
            self._slot_last[s] = tok
            self._slot_len[s] += 1
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(len(active))
        self._update_gauges()
        return finished

    def _spec_step(self, active: list[int], finished: list[Request]) -> list[Request]:
        """One speculative round: gamma draft steps + one verify pass
        advance every active slot by 1..gamma+1 tokens.  Greedy slots
        emit EXACTLY their non-speculative greedy decode; sampled slots
        emit marginally exact filtered target samples (both pinned in
        tests/test_engine.py); speculation changes only the schedule."""
        active = self._ensure_frontier(active, self._spec_gamma)
        if not active:
            self._update_gauges()
            return finished
        tokens = jnp.asarray(self._slot_last, jnp.int32)[:, None]
        positions = jnp.asarray(self._slot_len, jnp.int32)[:, None]
        if any(
            self.slots[s] is not None and self._slot_temp[s] > 0
            for s in range(self.max_slots)
        ):
            temps = jnp.asarray(self._slot_temp, jnp.float32)
            topks = jnp.asarray(self._slot_topk, jnp.int32)
            topps = jnp.asarray(self._slot_topp, jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            emitted, a_vec, self.cache = self._spec_round(
                self.params, self.draft_params, self.cache, tokens,
                positions, temps, topks, topps, sub,
            )
        else:
            emitted, a_vec, self.cache = self._spec_round_plain(
                self.params, self.draft_params, self.cache, tokens, positions
            )
        emitted = np.asarray(emitted)
        a_vec = np.asarray(a_vec)
        gamma = self._spec_gamma
        emitted_total = 0
        for s in active:
            req = self.slots[s]
            a = int(a_vec[s])
            # Emit d_1..d_a then the target's own token at position a
            # (correction on rejection, bonus on full accept).  All a+1
            # tokens are consumed unless a finish condition truncates —
            # and truncation only ever coincides with req.done, so live
            # slots always consume exactly a+1.
            self.spec_proposed += gamma
            self.spec_accepted += a
            if self.metrics:
                self.metrics.spec_proposed.inc(gamma)
                self.metrics.spec_accepted.inc(a)
            round_toks = [int(emitted[s, j]) for j in range(a + 1)]
            consumed = 0
            for tok in round_toks:
                req.tokens.append(tok)
                self._slot_last[s] = tok
                consumed += 1
                emitted_total += 1
                if (
                    len(req.tokens) >= req.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or self._hit_stop(req)
                ):
                    break
            self._slot_len[s] += consumed
            self._maybe_finish(s)
            if req.done:
                finished.append(req)
            else:
                self._extend_frontier(s)
                if self.cfg.attention_window is not None:
                    self._reclaim_windowed(s)
        # The round left every row's device length at L+gamma+1; re-align
        # all rows to the host truth in one vector write per layer (idle
        # and just-cleared rows are 0 in _slot_len, matching _clear_slot).
        # A FRESH array per layer: sharing one across layers would hand
        # the next round's donation the same buffer twice, which XLA
        # rejects (donate(a), donate(a)).
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "seq_lens": jnp.array(self._slot_len, jnp.int32),
            }
        if self.metrics:
            self.metrics.steps.inc()
            self.metrics.tokens.inc(emitted_total)
        self._update_gauges()
        return finished

    def _ensure_frontier(self, active: list[int], lookahead: int) -> list[int]:
        """Make every coming write in [len, len+lookahead] addressable for
        each active slot, then publish the covering pages.

        Reserve admission: pages were all allocated at admission, so this
        is pure publication.  Optimistic admission: generation pages are
        allocated HERE, on demand — processed oldest-admission-first, a
        pool shortage preempts the newest ready slot (recompute-resume:
        the victim requeues at the head and re-prefills prompt+generated),
        and if the shortage persists the starved slot itself is evicted.
        Oldest-first + newest-evicted means the oldest request can never
        be robbed, which is the liveness argument (it eventually owns
        every page its submit-time bound guarantees fit).  Returns the
        active list minus anything evicted."""
        if not self._optimistic:
            for s in active:
                self._extend_frontier(s, lookahead=lookahead)
            return active
        ps = self.paged.page_size
        for s in sorted(active, key=lambda x: self._slot_seq[x]):
            req = self.slots[s]
            if req is None or not self._slot_ready[s]:
                continue  # evicted as a victim earlier in this pass
            need = (self._slot_len[s] + lookahead) // ps + 1
            while need > self._slot_page_base[s] + len(self._slot_pages[s]):
                with self._lock:
                    page = (
                        self.free_pages.popleft() if self.free_pages else None
                    )
                    if page is not None:
                        self._page_refs[page] = 1
                        self._slot_pages[s].append(page)
                        continue
                if not self._preempt_newest(newer_than=self._slot_seq[s]):
                    break
            if need > self._slot_page_base[s] + len(self._slot_pages[s]):
                self._evict_slot(s)  # starved even after preempting: resume later
                continue
            self._extend_frontier(s, lookahead=lookahead)
        return [
            s
            for s in active
            if self.slots[s] is not None and self._slot_ready[s]
        ]

    def _preempt_newest(self, newer_than: int) -> bool:
        """Evict the most recently admitted ready slot STRICTLY newer
        than ``newer_than`` to free its pages; False when none is.  A
        growing slot may only rob younger slots — never an older one —
        so the oldest request's page claim is monotone (liveness)."""
        cands = [
            s
            for s in range(self.max_slots)
            if self.slots[s] is not None
            and self._slot_ready[s]
            and self._slot_seq[s] > newer_than
        ]
        if not cands:
            return False
        self._evict_slot(max(cands, key=lambda s: self._slot_seq[s]))
        return True

    def _evict_slot(self, slot: int) -> None:
        """Preempt: tear the slot down exactly like a finish (pages,
        table row, prefix refcounts all through _clear_slot) but requeue
        the request at the queue HEAD for recompute-resume — unless the
        client already cancelled it, in which case eviction doubles as
        the teardown."""
        req = self.slots[slot]
        self._clear_slot(slot)
        with self._lock:
            # Atomic with cancel(): a disconnect racing this eviction
            # either finds the request still in a slot (cancel marks it;
            # we see cancelled here) or finds it back in the queue
            # (cancel removes it there) — never a cancelled request
            # silently re-admitted.
            if req.cancelled:
                req.done = True
                self._update_gauges()
                return
            # Only a real recompute-resume counts as a preemption: a
            # cancelled victim's eviction is ordinary teardown, and
            # operators size the pool from this counter.
            self.preemptions += 1
            if self.metrics:
                self.metrics.preemptions.inc()
            self.queue.appendleft(req)
            self._update_gauges()

    def _extend_frontier(self, slot: int, lookahead: Optional[int] = None) -> None:
        """Publish every page the next step can write — up to the one
        covering position len+lookahead — into the device table the
        moment the frontier approaches it: tiny .at[slot, idx].set
        updates per layer, amortized O(1/page_size) dispatches per token.
        ``lookahead`` defaults to the speculative gamma (0 for plain
        decode: only the next position's page); decode blocks pass T-1,
        their furthest write."""
        if lookahead is None:
            lookahead = self._spec_gamma
        need = (
            self._slot_len[slot] + lookahead
        ) // self.paged.page_size + 1
        need = min(
            need, self._slot_page_base[slot] + len(self._slot_pages[slot])
        )
        while self._slot_visible[slot] < need:
            idx = self._slot_visible[slot]  # logical page index to publish
            page = self._slot_pages[slot][idx - self._slot_page_base[slot]]
            for name in self._layer_names:
                att = self.cache[name]["attn"]
                self.cache[name]["attn"] = {
                    **att,
                    "page_table": att["page_table"].at[slot, idx].set(page),
                }
            self._slot_visible[slot] = idx + 1

    def _reclaim_windowed(self, slot: int) -> None:
        """Free pages that scrolled fully out of a sliding attention
        window.  A query at position p sees keys in (p - window, p]; once
        every position in a page is below ``len - window`` no future query
        can see it — visibility only moves forward — so the page returns
        to the pool mid-flight (bounded cache memory for long windowed
        decodes).  Its table entry points at the scratch page: gathers of
        masked positions read garbage that the window mask discards, and
        the append frontier is always ahead of the reclaimed region."""
        window = self.cfg.attention_window
        ps = self.paged.page_size
        horizon = self._slot_len[slot] - window
        # horizon // ps = TOTAL pages ever dead for this slot; subtract the
        # already-reclaimed count (the page list is trimmed in place, so
        # reusing the total as an increment would double-free live pages —
        # caught by the windowed-oracle test).
        n_dead = max(
            0,
            min(
                horizon // ps - self._slot_page_base[slot],
                len(self._slot_pages[slot]),
            ),
        )
        if n_dead <= 0:
            return
        dead, self._slot_pages[slot] = (
            self._slot_pages[slot][:n_dead],
            self._slot_pages[slot][n_dead:],
        )
        # The logical page indices shift only in OUR bookkeeping; the
        # device table keeps absolute logical positions, so dead entries
        # are re-pointed at scratch (a sliced device update — no host
        # round-trip) rather than compacted.
        lo = self._slot_page_base[slot]
        for name in self._layer_names:
            att = self.cache[name]["attn"]
            self.cache[name]["attn"] = {
                **att,
                "page_table": att["page_table"].at[slot, lo : lo + n_dead].set(0),
            }
        self._slot_page_base[slot] += n_dead
        for page in dead:
            self._release_page(page)

    def _update_gauges(self) -> None:
        if not self.metrics:
            return
        with self._lock:
            self.metrics.active_slots.set(
                sum(1 for s in self.slots if s is not None)
            )
            self.metrics.queued.set(len(self.queue))
            self.metrics.free_pages.set(len(self.free_pages))
            self.metrics.shared_pages.set(
                sum(1 for c in self._page_refs.values() if c > 1)
            )

    def run(self, requests: list[tuple[list[int], int]], **submit_kw) -> list[Request]:
        """Submit all (``submit_kw`` — temperature/top_k/top_p — applies to
        every request), step until drained, return in submission order."""
        subs = [self.submit(p, n, **submit_kw) for p, n in requests]
        guard = 0
        while not all(r.done for r in subs):
            self.step()
            guard += 1
            if guard > 100_000:
                raise RuntimeError("engine failed to drain")
        return subs


def main(argv: Optional[list[str]] = None) -> None:
    """In-pod serving demo/benchmark (≙ the per-family benchmark pods in
    deploy/): synthetic weights + synthetic request stream through the
    continuous-batching engine; prints one JSON summary line.

    ``k8s-pod-serve-gpt.yaml`` runs this against allocated chips; the same
    command works on any backend (tiny CPU smoke by default).
    """
    import argparse
    import json
    import sys
    import time

    from ..utils.platform import honor_jax_platforms_env
    from .benchmark import _positive_int

    # Empty JAX_PLATFORMS in a pod spec is a no-op, not a platform reset.
    honor_jax_platforms_env(
        empty_is_auto=False, log=lambda m: print(m, file=sys.stderr)
    )

    p = argparse.ArgumentParser(prog="tpu-serving-engine")
    p.add_argument("--hidden", type=_positive_int, default=512)
    p.add_argument("--layers", type=_positive_int, default=4)
    p.add_argument("--heads", type=_positive_int, default=8)
    p.add_argument("--kv-heads", type=_positive_int, default=4)
    p.add_argument("--vocab", type=_positive_int, default=32000)
    p.add_argument("--quant", choices=["w8", "w8a8"], default=None)
    p.add_argument(
        "--quant-kv",
        action="store_true",
        help="int8 paged KV pools (halved cache bandwidth; gather path)",
    )
    p.add_argument("--page-size", type=_positive_int, default=16)
    p.add_argument("--num-pages", type=_positive_int, default=128)
    p.add_argument("--max-pages-per-seq", type=_positive_int, default=16)
    p.add_argument("--slots", type=_positive_int, default=4)
    p.add_argument("--requests", type=_positive_int, default=8)
    p.add_argument("--prompt-len", type=_positive_int, default=32)
    p.add_argument("--max-new", type=_positive_int, default=32)
    p.add_argument(
        "--use-kernel",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="decode through the Pallas paged-attention kernel instead of "
        "the gather path (ops/paged_attention.py); default auto — kernel "
        "on TPU, gather on CPU and (until its Mosaic lowering is "
        "hardware-proven) for --quant-kv pools",
    )
    p.add_argument(
        "--temperature",
        type=float,
        default=0.0,
        help="sample every request at this temperature (0 = greedy)",
    )
    p.add_argument(
        "--top-k", type=_positive_int, default=None,
        help="restrict sampling to the k highest logits per step",
    )
    p.add_argument(
        "--top-p", type=float, default=None,
        help="restrict sampling to the smallest nucleus with mass >= p",
    )
    p.add_argument(
        "--spec-gamma",
        type=int,
        default=0,
        help="speculative decoding: gamma int8 self-draft proposals per "
        "verify pass (shared-pool; greedy slots emit exactly the greedy "
        "decode, sampled slots marginally exact filtered samples). "
        "Incompatible with --quant.",
    )
    p.add_argument(
        "--prefill-chunk",
        type=_pow2_int,
        default=None,
        help="stream prompts into the prefill in chunks of this many "
        "tokens (power of two), bounding how long active slots stall "
        "per step during a long admission",
    )
    p.add_argument(
        "--decode-block",
        type=_pow2_int,
        default=1,
        help="in pure decode (no admission work), advance every slot up "
        "to this many tokens per dispatch via one scanned program "
        "(power of two) — amortizes the per-step host round-trip; "
        "incompatible with --spec-gamma",
    )
    p.add_argument(
        "--admission",
        choices=["reserve", "optimistic"],
        default="reserve",
        help="reserve: allocate each request's worst-case page chain at "
        "admission (no preemption ever); optimistic: allocate prompt "
        "pages only and grow on demand, preempting the newest slot for "
        "recompute-resume when the pool runs dry — higher concurrency "
        "when generations finish early",
    )
    args = p.parse_args(argv)
    if args.spec_gamma and args.quant:
        raise SystemExit(
            "--spec-gamma uses the int8 SELF-draft against the bf16 "
            "target; an already-quantized target (--quant) leaves nothing "
            "to verify against — drop one of the flags"
        )

    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_heads=args.heads,
        intermediate_size=args.hidden * 3,
        max_seq=args.page_size * args.max_pages_per_seq,
        num_kv_heads=args.kv_heads,
    )
    rng = jax.random.PRNGKey(0)
    params = TransformerLM(cfg).init(rng, jnp.zeros((1, 2), jnp.int32))["params"]
    if args.quant:
        from ..ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
        cfg = dataclasses.replace(cfg, quant=args.quant)
    if args.quant_kv:
        cfg = dataclasses.replace(cfg, quant_kv=True)
    paged = PagedConfig(
        args.page_size,
        args.num_pages,
        args.max_pages_per_seq,
        use_kernel=args.use_kernel,
    )
    spec_kw = {}
    if args.spec_gamma:
        from ..ops.quant import quantize_lm_params

        spec_kw = dict(
            spec_gamma=args.spec_gamma,
            draft_params=quantize_lm_params(params),
        )
    eng = ServingEngine(
        cfg, params, paged, max_slots=args.slots,
        prefill_chunk=args.prefill_chunk, decode_block=args.decode_block,
        admission=args.admission, **spec_kw,
    )
    sample_kw = dict(
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p
    )

    # Half the stream shares a system-prompt prefix (exercises page sharing).
    common = list(range(1, args.prompt_len // 2 + 1))
    jobs = []
    for i in range(args.requests):
        tail = [(37 * i + j) % args.vocab for j in range(args.prompt_len // 2)]
        prompt = (common + tail) if i % 2 == 0 else [(11 * i + j) % args.vocab for j in range(args.prompt_len)]
        jobs.append((prompt, args.max_new))

    # Warmup: compile the fixed-slot step and EVERY distinct prompt-length
    # prefill OUTSIDE the timed region (max_new=2 forces one decode step),
    # so the JSON line reports steady-state serving throughput, not XLA
    # compilation — the same honesty rule every bench in this repo follows
    # (BASELINE.md "Measurement methodology").
    warm_lens: dict[int, list[int]] = {}
    for prompt, _ in jobs:
        warm_lens.setdefault(len(prompt), prompt)
    eng.run([(prompt, 2) for prompt in warm_lens.values()], **sample_kw)
    # Warmup rounds ran real speculative traffic; the reported acceptance
    # must cover the timed region only (same warmup-exclusion rule as the
    # throughput number).
    eng.spec_proposed = eng.spec_accepted = 0

    t0 = time.time()
    done = eng.run(jobs, **sample_kw)
    dt = time.time() - t0
    tokens = sum(len(r.tokens) for r in done)
    print(
        json.dumps(
            {
                "metric": "engine_decode_tokens_per_sec",
                "value": round(tokens / dt, 2),
                "unit": "tokens/sec",
                "requests": len(done),
                "slots": args.slots,
                "quant": args.quant,
                "kernel": paged.kernel_enabled(cfg.quant_kv),
                "sampler": "greedy"
                if args.temperature <= 0
                else f"temperature={args.temperature},top_k={args.top_k},"
                f"top_p={args.top_p}",
                "spec_gamma": args.spec_gamma,
                "spec_acceptance": round(
                    eng.spec_accepted / max(eng.spec_proposed, 1), 3
                )
                if args.spec_gamma
                else None,
                "tokens": tokens,
                "wall_s": round(dt, 2),
            }
        ),
        file=sys.stdout,
        flush=True,
    )


if __name__ == "__main__":
    main()
