"""Vision Transformer (ViT) image classifier in Flax.

The reference delegates all model code to its workload images (SURVEY.md
§2.4: the plugin ships convnet benchmark pods only); this framework's
workload layer is first-party, and ViT completes the image-model family
next to the convnets (alexnet.py, resnet.py): patchify -> encoder stack ->
classification head, the architecture modern TPU image benchmarks use.

TPU-first choices:
- Patch embedding is a single strided conv = one big MXU matmul per image;
  patch 16 on 224-inputs yields 196 tokens, padded with the [CLS] token to
  197 — attention therefore runs the plain-XLA path unless the token count
  is 128-aligned, so the default benchmark config uses image 256 / patch 16
  = 256 tokens + pad-free [CLS]-less mean pooling, which IS 128-aligned and
  takes the fused flash kernel (ops/flash_attention.py) end to end.
- bfloat16 activations, float32 layernorm/softmax, learned position
  embeddings (static shapes; no interpolation inside jit).
- Mean pooling instead of a [CLS] token keeps the sequence length a
  multiple of 128 for the kernel and drops a serial gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops.flash_attention import flash_attention


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 256
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    dtype: Any = jnp.bfloat16

    @property
    def num_tokens(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @staticmethod
    def base() -> "ViTConfig":
        """ViT-B/16 on 256px inputs: 256 tokens — flash-kernel aligned."""
        return ViTConfig()

    @staticmethod
    def tiny() -> "ViTConfig":
        """Structural stand-in for CPU tests."""
        return ViTConfig(
            image_size=32,
            patch_size=8,
            num_classes=10,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            intermediate_size=128,
        )


class ViTEncoderLayer(nn.Module):
    """Pre-LN encoder block (ViT uses pre-norm, unlike BERT's post-norm)."""

    config: ViTConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        x = nn.LayerNorm(dtype=jnp.float32)(hidden).astype(cfg.dtype)
        proj = {
            name: nn.DenseGeneral(
                features=(cfg.num_heads, head_dim), dtype=cfg.dtype, name=name
            )(x)
            for name in ("query", "key", "value")
        }
        seq_len = hidden.shape[1]
        if seq_len % 128 == 0:
            q, k, v = (
                proj[n].transpose(0, 2, 1, 3) for n in ("query", "key", "value")
            )
            attn = flash_attention(q, k, v).transpose(0, 2, 1, 3)
        else:
            attn = nn.dot_product_attention(
                proj["query"], proj["key"], proj["value"]
            )
        attn = nn.DenseGeneral(
            features=cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(attn)
        hidden = hidden + attn

        x = nn.LayerNorm(dtype=jnp.float32)(hidden).astype(cfg.dtype)
        x = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(x)
        return hidden + x


class ViT(nn.Module):
    """Patchify -> pre-LN encoder stack -> mean-pool -> class logits."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.config
        b, h, w, c = images.shape
        if h != cfg.image_size or w != cfg.image_size:
            raise ValueError(
                f"expected {cfg.image_size}x{cfg.image_size} images, got {h}x{w}"
            )
        # One strided conv patchifies and embeds in a single MXU pass:
        # [b, H/P, W/P, hidden].
        x = nn.Conv(
            cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=cfg.dtype,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(b, cfg.num_tokens, cfg.hidden_size)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, cfg.num_tokens, cfg.hidden_size),
        )
        x = x + pos.astype(cfg.dtype)

        for i in range(cfg.num_layers):
            x = ViTEncoderLayer(cfg, name=f"layer_{i}")(x)

        x = nn.LayerNorm(dtype=jnp.float32)(x)
        pooled = jnp.mean(x, axis=1)  # token-mean pooling, 128-friendly
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(pooled)
