"""Synthetic benchmark batches.

The reference's benchmark (convnet-benchmarks `benchmark_alexnet.py`, run by
k8s-pod-example-gpu.yaml) times training on random data — no input pipeline.
Same here: batches are generated on device, so the numbers measure the chip,
not the loader.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def synthetic_image_batch(
    key: jax.Array, batch_size: int, image_size: int = 224, num_classes: int = 1000
) -> dict:
    k_img, k_lbl = jax.random.split(key)
    return {
        "images": jax.random.normal(
            k_img, (batch_size, image_size, image_size, 3), jnp.float32
        ),
        "labels": jax.random.randint(k_lbl, (batch_size,), 0, num_classes),
    }


def synthetic_token_batch(
    key: jax.Array, batch_size: int, seq_len: int = 128, vocab_size: int = 30522
) -> dict:
    k_tok, k_lbl = jax.random.split(key)
    return {
        "input_ids": jax.random.randint(k_tok, (batch_size, seq_len), 0, vocab_size),
        "labels": jax.random.randint(k_lbl, (batch_size, seq_len), 0, vocab_size),
    }


def synthetic_lm_batch(
    key: jax.Array, batch_size: int, seq_len: int, vocab_size: int
) -> dict:
    """Causal-LM batch: labels are the inputs shifted by one position."""
    ids = jax.random.randint(key, (batch_size, seq_len + 1), 0, vocab_size)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
