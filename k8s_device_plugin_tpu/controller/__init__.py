"""Closed-loop fleet autoscaler (ISSUE 19): a jax-free reconciler that
polls the router's ``/debug/fleet``, computes a desired fleet spec
(size x role mix) from the host-side pressure signals, and converges
the live fleet through a pluggable actuator — warm scale-up, drain-down,
and role rebalancing, with hysteresis/cooldown flap guards.

Run it: ``python -m k8s_device_plugin_tpu.controller --url http://router:8100``.
"""

from .actuators import (
    Actuator,
    ActuatorError,
    FleetSimActuator,
    KubernetesActuator,
    NullActuator,
)
from .reconciler import (
    ACTIONS,
    OUTCOMES,
    ControllerConfig,
    ControllerMetrics,
    Reconciler,
    fetch_fleet,
)
from .server import ControllerServer

__all__ = [
    "ACTIONS",
    "OUTCOMES",
    "Actuator",
    "ActuatorError",
    "ControllerConfig",
    "ControllerMetrics",
    "ControllerServer",
    "FleetSimActuator",
    "KubernetesActuator",
    "NullActuator",
    "Reconciler",
    "fetch_fleet",
]
