"""Actuators — how a :class:`~.reconciler.Reconciler` decision lands.

The reconciler decides; an actuator executes.  The interface is three
verbs matching the three non-hold actions:

- ``scale_up(role=..., peers=[...])`` — bring up a warm replica for a
  role, donor-selected from ``peers`` (the eligible decode-capable
  fleet) via the snapshot plane's ``donor_for`` ketama walk, and join
  it to the router.
- ``scale_down(replica, role=...)`` — drain, wait for in-flight
  streams to finish, then reap.  Zero client-visible drops is the
  actuator's contract, not the reconciler's hope.
- ``set_role(replica, role)`` — flip a live replica's role via its
  admin ``POST /debug/role``; the router reconciles the change off its
  next summary poll (on/off the /generate ring).

Failures raise :class:`ActuatorError`; the reconciler degrades the
tick to hold, records ``controller.actuator_error``, and retries at
cooldown pace.

Three shapes ship:

- :class:`NullActuator` — the CLI default: every action refuses, so a
  misconfigured controller can never touch a fleet (observe via
  ``--dry-run`` instead).
- :class:`FleetSimActuator` — callable-injected lifecycle for the
  fleet-sim tier (chaos scenario, bench): spawn/warm/join/drain/reap
  as plain functions over in-process replicas.
- :class:`KubernetesActuator` — the k8s shape: desired counts are the
  actuation surface (the controller's ``tpu_controller_desired_replicas``
  gauge, scraped by an external-metrics adapter that scales the serving
  Deployment — deploy/k8s-deploy-controller.yaml); role flips still
  dial the pod's admin endpoint directly.

All jax-free (stdlib + the numpy-only snapshot helpers).
"""

from __future__ import annotations

import collections
import json
import urllib.request
from typing import Callable, Optional


class ActuatorError(RuntimeError):
    """An actuation failed; the reconciler holds and retries later."""


def post_role(replica: str, role: str, timeout_s: float = 5.0) -> dict:
    """``POST /debug/role`` against a replica's admin surface (the
    engine gates it behind ``--admin-endpoints``)."""
    url = f"http://{replica}/debug/role"
    body = json.dumps({"role": role}).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read() or b"{}")
    except OSError as e:
        raise ActuatorError(f"role flip on {replica} failed: {e}") from e


class Actuator:
    """The verb interface.  Subclasses implement all three or raise
    :class:`ActuatorError` for the ones their substrate cannot do."""

    name = "actuator"

    def scale_up(self, *, role: str, peers: list) -> dict:
        """Bring up + warm + join one replica; returns
        ``{"replica": name, "donor": name | None}``."""
        raise NotImplementedError

    def scale_down(self, replica: str, *, role: Optional[str] = None) -> None:
        """Drain then reap ``replica`` (blocking until reaped)."""
        raise NotImplementedError

    def set_role(self, replica: str, role: str) -> None:
        """Flip a live replica's role."""
        raise NotImplementedError


class NullActuator(Actuator):
    """Refuses every action — the safe CLI default when no actuator is
    configured and --dry-run was explicitly disarmed anyway."""

    name = "none"

    def _refuse(self) -> None:
        raise ActuatorError(
            "no actuator configured (--actuator none) — run with "
            "--dry-run 1 to observe, or pick an actuator"
        )

    def scale_up(self, *, role: str, peers: list) -> dict:
        self._refuse()
        return {}

    def scale_down(self, replica: str, *, role: Optional[str] = None) -> None:
        self._refuse()

    def set_role(self, replica: str, role: str) -> None:
        self._refuse()


class FleetSimActuator(Actuator):
    """Lifecycle-by-callables for in-process fleets (the chaos scenario
    and the AUTOSCALE bench phase inject these over FakeReplica /
    sim-fleet objects):

    - ``spawn_fn(role) -> name`` starts a replica process/object and
      returns its ``host:port`` name (not yet joined).
    - ``warm_fn(name, donor)`` streams the donor's snapshot into it
      (optional — skipped when absent or no donor exists).
    - ``join_fn(name, role)`` registers it with the router.
    - ``drain_fn(name)`` begins drain and blocks until in-flight work
      finishes (the zero-drops contract lives here).
    - ``reap_fn(name)`` removes it from the router and stops it.
    - ``set_role_fn(name, role)`` flips a role; defaults to the real
      admin ``POST /debug/role`` dial.

    Donor selection is the real ``donor_for`` ketama walk over
    ``peers`` — the same placement the warm-join CLI path uses, so the
    sim exercises production donor choice."""

    name = "fleet-sim"

    def __init__(
        self,
        *,
        spawn_fn: Callable[[str], str],
        join_fn: Callable[[str, str], None],
        drain_fn: Callable[[str], None],
        reap_fn: Callable[[str], None],
        warm_fn: Optional[Callable[[str, str], None]] = None,
        set_role_fn: Optional[Callable[[str, str], None]] = None,
    ):
        self._spawn = spawn_fn
        self._join = join_fn
        self._drain = drain_fn
        self._reap = reap_fn
        self._warm = warm_fn
        self._set_role = set_role_fn

    def scale_up(self, *, role: str, peers: list) -> dict:
        from ..models.engine_snapshot import donor_for

        try:
            name = self._spawn(role)
            donor = donor_for(name, list(peers)) if peers else None
            if donor and self._warm is not None:
                self._warm(name, donor)
            self._join(name, role)
        except (OSError, RuntimeError, ValueError) as e:
            raise ActuatorError(f"scale_up failed: {e}") from e
        return {"replica": name, "donor": donor}

    def scale_down(self, replica: str, *, role: Optional[str] = None) -> None:
        try:
            self._drain(replica)
            self._reap(replica)
        except (OSError, RuntimeError, ValueError) as e:
            raise ActuatorError(f"scale_down of {replica} failed: {e}") from e

    def set_role(self, replica: str, role: str) -> None:
        if self._set_role is not None:
            try:
                self._set_role(replica, role)
            except (OSError, RuntimeError, ValueError) as e:
                raise ActuatorError(
                    f"role flip on {replica} failed: {e}"
                ) from e
        else:
            post_role(replica, role)


class KubernetesActuator(Actuator):
    """The Kubernetes shape: replica *counts* are actuated by the
    platform, not by this process.  ``scale_up``/``scale_down`` record
    an intent and bump the desired count the controller already exposes
    as ``tpu_controller_desired_replicas{role=...}`` — an
    external-metrics adapter (or a thin sidecar watching
    ``/debug/controller``) scales the serving Deployment to match
    (deploy/k8s-deploy-controller.yaml carries the manifest pair).
    New pods warm themselves via their own ``--warm-from-fleet`` flag,
    so no donor plumbing is needed here; scale_down relies on the pod
    preStop drain hook the serving Deployment already ships.

    Role flips are immediate either way: the pod's admin
    ``POST /debug/role`` is dialed directly.

    ``apply_fn(intent)`` is the seam for a real client-go/kubectl
    binding (and for tests); absent, intents only accumulate for the
    adapter to scrape."""

    name = "k8s"

    def __init__(self, apply_fn: Optional[Callable[[dict], None]] = None):
        self._apply = apply_fn
        self.desired: dict[str, int] = {}
        self.intents: collections.deque = collections.deque(maxlen=64)

    def _intend(self, intent: dict) -> None:
        self.intents.append(intent)
        if self._apply is not None:
            try:
                self._apply(intent)
            except (OSError, RuntimeError, ValueError) as e:
                raise ActuatorError(f"apply failed: {e}") from e

    def scale_up(self, *, role: str, peers: list) -> dict:
        self.desired[role] = self.desired.get(role, 0) + 1
        self._intend(
            {"verb": "scale_up", "role": role, "desired": self.desired[role]}
        )
        # The Deployment brings the pod; its name is the platform's.
        return {"replica": None, "donor": None}

    def scale_down(self, replica: str, *, role: Optional[str] = None) -> None:
        key = role or "unified"
        self.desired[key] = max(0, self.desired.get(key, 1) - 1)
        self._intend(
            {
                "verb": "scale_down",
                "role": key,
                "replica": replica,
                "desired": self.desired[key],
            }
        )

    def set_role(self, replica: str, role: str) -> None:
        self._intend({"verb": "set_role", "replica": replica, "role": role})
        post_role(replica, role)
