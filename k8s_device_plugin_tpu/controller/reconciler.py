"""Closed-loop fleet autoscaler — the reconciler (ISSUE 19).

``scale_recommendation`` (router/migration.py) turns host-side pressure
signals into a scale_up/scale_down/hold verdict, and until this module
the verdict dead-ended at ``tools/fleet_plan.py`` exit codes "so a cron
can act" (ROADMAP item 5).  The :class:`Reconciler` closes the loop: it
polls the router's ``GET /debug/fleet`` snapshot, computes a desired
fleet spec (size x role mix), and converges the live fleet toward it
through a pluggable :class:`~..controller.actuators.Actuator` — warm
scale-up (donor-selected peer warm-join), drain-then-reap scale-down,
and role rebalancing.

Decision discipline — one hot poll must never flap the fleet:

- **Hysteresis**: a non-hold verdict must repeat for ``sustain_ticks``
  consecutive ticks before anything executes (a verdict change resets
  the streak, so an oscillating fleet holds forever — the flap guard).
- **Cooldown**: after any executed (or dry-run, or failed) action the
  controller holds for ``cooldown_s`` — let the last action land and
  the EWMAs react before judging again.
- **Bounded actions**: at most ``max_actions_per_tick`` per tick, and
  scale_up refuses past ``max_replicas``.
- **Role flips before hardware**: when the disagg prefill pool
  saturates while a decode replica idles (or vice versa), the
  controller flips the idle replica's role — the router already
  reconciles role changes off its summary poll — because a flip is
  cheaper than a scale-up.
- **Never the last of a role**: scale_down and role flips refuse to
  empty a role's pool.
- **Degrade to hold**: a failed fleet poll or a raising actuator is a
  held tick plus a flight event, never a crash and never a guess.

Acts on host-side signals only (queue-wait EWMA, drain-rate forecast —
the Host-Side Telemetry pattern, PAPERS.md), jax-free and
fake-clock-injectable: the unit suite drives :meth:`Reconciler.tick`
with a fake clock, a canned-snapshot fetch, and a recording actuator;
production wires :class:`~.server.ControllerServer`'s daemon thread
(``python -m k8s_device_plugin_tpu.controller``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
import urllib.request
from typing import Callable, Optional

from .actuators import Actuator, ActuatorError

ROLE_PREFILL = "prefill"

# Closed decision enums (metrics label sets; tools/metrics_lint.py
# FAMILY_BUDGETS pins their product as the cardinality budget).
ACTIONS = ("hold", "role_flip", "scale_up", "scale_down")
OUTCOMES = (
    "idle",  # hold verdict: nothing to converge
    "executed",  # the actuator applied the action
    "dry_run",  # --dry-run: logged + metered, actuator never called
    "held_hysteresis",  # verdict not yet sustained sustain_ticks
    "held_cooldown",  # a recent action is still settling
    "capped",  # scale_up refused at max_replicas
    "refused_last_replica",  # would empty a role's pool
    "actuator_error",  # actuator raised: degraded to hold
    "poll_error",  # fleet snapshot fetch failed: degraded to hold
)


@dataclasses.dataclass
class ControllerConfig:
    """Tunables for :class:`Reconciler` (CLI: the ``--controller`` knob
    set of ``python -m k8s_device_plugin_tpu.controller``)."""

    # Seconds between reconcile ticks (the daemon loop's cadence).
    interval_s: float = 5.0
    # Consecutive ticks a non-hold verdict must repeat before acting —
    # the hysteresis/flap guard (a verdict change resets the streak).
    sustain_ticks: int = 3
    # Seconds after any action (executed, dry-run, or failed) before
    # the next one: let the fleet settle and the EWMAs react.
    cooldown_s: float = 30.0
    # Actions per tick ceiling (1 = one careful step at a time).
    max_actions_per_tick: int = 1
    # Fleet size bounds for the decode-capable pool.  max_replicas 0 =
    # uncapped (the actuator's own capacity is the cap).
    min_replicas: int = 1
    max_replicas: int = 0
    # Pressure classification for role rebalancing (the prefill pool is
    # outside the router's recommendation, which only judges the
    # decode-capable pool).  Overridden by the thresholds the snapshot's
    # recommendation carries when present, so controller and router
    # always judge with the same knobs.
    hot_wait_s: float = 2.0
    cold_wait_s: float = 0.5
    # Observe-only mode: decisions are computed, logged, metered, and
    # served at /debug/controller — the actuator is never called.
    dry_run: bool = False
    # Decision-log ring capacity (served at /debug/controller and
    # rendered by tools/fleet_plan.py --controller-url).
    decision_log: int = 256

    def __post_init__(self):
        if self.sustain_ticks < 1:
            raise ValueError("sustain_ticks must be >= 1")
        if self.max_actions_per_tick < 1:
            raise ValueError("max_actions_per_tick must be >= 1")
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas and self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be 0 or >= min_replicas")
        if self.hot_wait_s <= self.cold_wait_s:
            raise ValueError(
                "hot_wait_s must exceed cold_wait_s "
                f"({self.hot_wait_s} <= {self.cold_wait_s})"
            )


class ControllerMetrics:
    """The controller's Prometheus families (served on its own
    /metrics — the k8s actuator's external-metrics surface; linted
    live in tier-1 like the router's)."""

    def __init__(self, registry):
        self.ticks = registry.counter(
            "tpu_controller_ticks_total",
            "Reconcile ticks by outcome (ok: fleet snapshot fetched and "
            "judged; error: the /debug/fleet poll failed — the tick "
            "degraded to hold)",
            ("outcome",),
        )
        self.decisions = registry.counter(
            "tpu_controller_decisions_total",
            "Reconciler decisions by action (hold/role_flip/scale_up/"
            "scale_down) and outcome (idle/executed/dry_run/"
            "held_hysteresis/held_cooldown/capped/refused_last_replica/"
            "actuator_error/poll_error) — both closed enums; every tick "
            "lands exactly one decision here",
            ("action", "outcome"),
        )
        self.desired_replicas = registry.gauge(
            "tpu_controller_desired_replicas",
            "Desired replica count per role (unified/prefill/decode) — "
            "the external-metrics surface a Kubernetes adapter scrapes "
            "to scale the serving Deployment "
            "(deploy/k8s-deploy-controller.yaml)",
            ("role",),
        )
        self.observed_replicas = registry.gauge(
            "tpu_controller_observed_replicas",
            "Observed replica count per role from the last fleet "
            "snapshot (desired vs observed divergence = convergence "
            "in progress or an actuator wedged)",
            ("role",),
        )
        self.replica_minutes = registry.counter(
            "tpu_controller_replica_minutes_total",
            "Accumulated replica-minutes per role (fleet size "
            "integrated over wall time between ticks) — the hardware "
            "bill the autoscaler exists to shrink; the AUTOSCALE bench "
            "row compares it against a static peak-sized fleet",
            ("role",),
        )
        self.tick_seconds = registry.histogram(
            "tpu_controller_tick_seconds",
            "Reconcile tick latency (fleet poll + decision + actuation)",
        )


def fetch_fleet(url: str, timeout_s: float = 10.0) -> dict:
    """One ``GET /debug/fleet`` dial against a router base URL — the
    production fetch the CLI wires into :class:`Reconciler` (tests
    inject canned-snapshot callables instead)."""
    base = url.rstrip("/")
    if not base.startswith("http"):
        base = f"http://{base}"
    with urllib.request.urlopen(base + "/debug/fleet", timeout=timeout_s) as r:
        return json.loads(r.read() or b"{}")


class Reconciler:
    """Poll -> desired spec -> guarded actuation, one :meth:`tick` at a
    time.  Single-threaded by contract: the controller's daemon loop
    (or the driving test) owns it; :meth:`snapshot` reads are plain
    dict/deque reads of already-published values (GIL-atomic, one-tick
    stale at worst — the same discipline as the router's poll state).

    ``fetch`` returns the router's ``/debug/fleet`` dict (raises
    ``OSError``/``ValueError`` on failure); ``actuator`` executes
    decisions (:mod:`.actuators`).  Injectables: ``metrics``
    (:class:`ControllerMetrics`), ``flight`` (FlightRecorder), ``now``
    (fake clock)."""

    def __init__(
        self,
        fetch: Callable[[], dict],
        actuator: Actuator,
        *,
        config: Optional[ControllerConfig] = None,
        metrics: Optional[ControllerMetrics] = None,
        flight=None,
        anomaly=None,
        now=time.monotonic,
    ):
        self.cfg = config or ControllerConfig()
        self._fetch = fetch
        self.actuator = actuator
        self.metrics = metrics
        self.flight = flight
        # Optional AnomalyMonitor (utils/anomaly.py): actuator failures
        # are DISCRETE incidents (wrong on first observation) — the
        # report fans out to the incident ring, the JSON log, and any
        # postmortem-capture listener.
        self.anomaly = anomaly
        self._now = now
        self.ticks = 0
        self.actions_executed = 0
        self.role_flips = 0
        self.scale_ups = 0
        self.scale_downs = 0
        # Replica-minutes ledger: fleet size integrated over the wall
        # time between consecutive ticks, per role and total.
        self.replica_minutes = 0.0
        self.replica_minutes_by_role: dict[str, float] = {}
        self._last_tick_t: Optional[float] = None
        # Hysteresis streak: consecutive ticks proposing the same
        # action kind.  A change (including back to hold) resets it.
        self._streak_action = "hold"
        self._streak = 0
        self._last_action_t: Optional[float] = None
        self._last_error: Optional[str] = None
        self._desired: dict[str, int] = {}
        self._observed: dict[str, int] = {}
        self.decisions: collections.deque = collections.deque(
            maxlen=self.cfg.decision_log
        )
        self._last_recorded: Optional[tuple] = None

    # ------------------------------------------------------------ wiring

    def _record(self, kind: str, **fields) -> None:
        if self.flight is not None:
            self.flight.record(kind, **fields)

    def _meter_decision(self, action: str, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.decisions.inc(action=action, outcome=outcome)

    # ------------------------------------------------------- observation

    @staticmethod
    def _pressure(row: dict) -> float:
        return float(row.get("pressure_s") or 0.0)

    @staticmethod
    def _healthy(row: dict) -> bool:
        return (
            bool(row.get("reachable", True))
            and not row.get("draining")
            and not row.get("fenced")
        )

    @staticmethod
    def _pool_role(rows: dict) -> str:
        """The decode-capable pool's role label: "decode" in a split
        fleet, else "unified"."""
        for row in rows.values():
            if row.get("role") == "decode":
                return "decode"
        return "unified"

    def _accrue_minutes(self, counts: dict, t: float) -> None:
        if self._last_tick_t is not None:
            dt_min = max(0.0, t - self._last_tick_t) / 60.0
            for role, n in counts.items():
                self.replica_minutes += n * dt_min
                self.replica_minutes_by_role[role] = (
                    self.replica_minutes_by_role.get(role, 0.0) + n * dt_min
                )
                if self.metrics is not None:
                    self.metrics.replica_minutes.inc(n * dt_min, role=role)
        self._last_tick_t = t

    # --------------------------------------------------------- decisions

    def _candidate(self, rows: dict, rec: dict) -> dict:
        """The unguarded verdict for this snapshot: what the controller
        WOULD do, before hysteresis/cooldown/caps.  Role rebalancing
        outranks hardware in both directions — a flip is cheaper than a
        scale-up and faster than a drain."""
        hot_wait = float(rec.get("hot_wait_s") or self.cfg.hot_wait_s)
        cold_wait = float(rec.get("cold_wait_s") or self.cfg.cold_wait_s)
        prefill = {
            n: r for n, r in rows.items() if r.get("role") == ROLE_PREFILL
        }
        pool = {
            n: r for n, r in rows.items() if r.get("role") != ROLE_PREFILL
        }
        pool_role = self._pool_role(pool)

        # Prefill pool saturated + an idle decode-capable replica ->
        # flip it to prefill (the router reconciles the role change off
        # its next summary poll and lifts it out of the /generate ring).
        hot_prefill = sorted(
            n
            for n, r in prefill.items()
            if self._healthy(r) and self._pressure(r) >= hot_wait
        )
        if hot_prefill:
            idle = sorted(
                (
                    (r.get("active_slots", 0), self._pressure(r), n)
                    for n, r in pool.items()
                    if self._healthy(r)
                    and self._pressure(r) <= cold_wait
                    and not r.get("queue_depth", 0)
                ),
            )
            if idle and len(pool) > 1:
                _, _, name = idle[0]
                return {
                    "action": "role_flip",
                    "replica": name,
                    "from": rows[name].get("role", "unified"),
                    "to": ROLE_PREFILL,
                    "reason": (
                        f"prefill pool saturated ({', '.join(hot_prefill)} "
                        f">= {hot_wait}s) while {name} idles — a flip is "
                        "cheaper than a scale-up"
                    ),
                }
            return {
                "action": "hold",
                "reason": (
                    f"prefill pool saturated ({', '.join(hot_prefill)}) "
                    "but no idle decode-capable replica to flip"
                ),
            }

        action = str(rec.get("action") or "hold")
        if action == "scale_up":
            # Flip-before-buy: an idle prefill replica covers decode
            # pressure without new hardware (never the last prefill).
            idle_prefill = sorted(
                (self._pressure(r), n)
                for n, r in prefill.items()
                if self._healthy(r) and self._pressure(r) <= cold_wait
            )
            if idle_prefill and len(prefill) > 1:
                _, name = idle_prefill[0]
                return {
                    "action": "role_flip",
                    "replica": name,
                    "from": ROLE_PREFILL,
                    "to": pool_role,
                    "reason": (
                        "decode pool hot while prefill replica "
                        f"{name} idles — a flip is cheaper than a "
                        "scale-up"
                    ),
                }
            return {
                "action": "scale_up",
                "role": pool_role,
                "reason": str(rec.get("reason") or "fleet hot"),
            }
        if action == "scale_down":
            victims = sorted(
                (self._pressure(r), n)
                for n, r in pool.items()
                if r.get("eligible")
            )
            if not victims:
                return {"action": "hold", "reason": "no eligible victim"}
            _, victim = victims[0]
            victim_role = pool[victim].get("role", "unified")
            same_role = sum(
                1 for r in pool.values() if r.get("role") == victim_role
            )
            if (
                len(pool) <= self.cfg.min_replicas
                or same_role <= 1
            ):
                return {
                    "action": "scale_down",
                    "replica": victim,
                    "role": victim_role,
                    "refused": True,
                    "reason": (
                        f"{victim} is the last {victim_role} replica "
                        f"(pool {len(pool)}, min {self.cfg.min_replicas}) "
                        "— refusing to reap it"
                    ),
                }
            return {
                "action": "scale_down",
                "replica": victim,
                "role": victim_role,
                "reason": str(rec.get("reason") or "fleet cold"),
            }
        return {
            "action": "hold",
            "reason": str(rec.get("reason") or "fleet within bounds"),
        }

    def _desired_spec(
        self, counts: dict, rec: dict, candidate: dict
    ) -> dict:
        """Desired role mix: observed counts adjusted by the current
        verdict (the recommendation's suggested size for the decode
        pool; +-1 role shifts for a pending flip)."""
        desired = dict(counts)
        pool_role = (
            "decode" if counts.get("decode") else "unified"
        )
        action = candidate.get("action")
        if action == "scale_up":
            n = int(rec.get("replicas") or 0)
            suggested = int(rec.get("suggested_replicas") or (n + 1))
            grow = max(1, suggested - n)
            if self.cfg.max_replicas:
                room = self.cfg.max_replicas - sum(counts.values())
                grow = max(0, min(grow, room))
            desired[pool_role] = counts.get(pool_role, 0) + grow
        elif action == "scale_down" and not candidate.get("refused"):
            role = candidate.get("role", pool_role)
            desired[role] = max(
                self.cfg.min_replicas, counts.get(role, 1) - 1
            )
        elif action == "role_flip":
            src = candidate.get("from", pool_role)
            dst = candidate.get("to", ROLE_PREFILL)
            desired[src] = max(0, counts.get(src, 0) - 1)
            desired[dst] = counts.get(dst, 0) + 1
        return {role: n for role, n in sorted(desired.items())}

    # -------------------------------------------------------------- tick

    def tick(self) -> dict:
        """One reconcile pass: fetch -> judge -> (maybe) act.  Returns
        the decision record appended to the log — the unit-test driving
        seam; production calls this from the daemon loop."""
        t0 = self._now()
        self.ticks += 1
        try:
            fleet = self._fetch()
        except (OSError, ValueError) as e:
            self._last_error = str(e)
            if self.metrics is not None:
                self.metrics.ticks.inc(outcome="error")
                self.metrics.tick_seconds.observe(self._now() - t0)
            self._record("controller.tick_error", error=str(e))
            return self._decide(
                t0, {"action": "hold", "reason": f"fleet poll failed: {e}"},
                outcome="poll_error",
            )
        self._last_error = None
        rows = dict(fleet.get("replicas") or {})
        rec = dict(fleet.get("recommendation") or {})
        counts: dict[str, int] = {}
        for row in rows.values():
            role = str(row.get("role") or "unified")
            counts[role] = counts.get(role, 0) + 1
        self._observed = {r: n for r, n in sorted(counts.items())}
        self._accrue_minutes(counts, t0)
        if self.metrics is not None:
            self.metrics.ticks.inc(outcome="ok")
            for role, n in counts.items():
                self.metrics.observed_replicas.set(n, role=role)

        candidate = self._candidate(rows, rec)
        self._desired = self._desired_spec(counts, rec, candidate)
        if self.metrics is not None:
            for role, n in self._desired.items():
                self.metrics.desired_replicas.set(n, role=role)

        # Hysteresis streak over the *verdict kind* — any change
        # (including back to hold) re-arms it, so an oscillating fleet
        # never acts (the flap guard).
        action = candidate["action"]
        if action == self._streak_action:
            self._streak += 1
        else:
            self._streak_action = action
            self._streak = 1

        if action == "hold":
            decision = self._decide(t0, candidate, outcome="idle")
        elif candidate.get("refused"):
            decision = self._decide(
                t0, candidate, outcome="refused_last_replica"
            )
        elif self._streak < self.cfg.sustain_ticks:
            decision = self._decide(
                t0,
                candidate,
                outcome="held_hysteresis",
                streak=self._streak,
            )
        elif (
            self._last_action_t is not None
            and t0 - self._last_action_t < self.cfg.cooldown_s
        ):
            decision = self._decide(t0, candidate, outcome="held_cooldown")
        elif (
            action == "scale_up"
            and self.cfg.max_replicas
            and sum(counts.values()) >= self.cfg.max_replicas
        ):
            decision = self._decide(t0, candidate, outcome="capped")
        else:
            decision = self._act(t0, rows, candidate)
        if self.metrics is not None:
            self.metrics.tick_seconds.observe(self._now() - t0)
        return decision

    def _act(self, t0: float, rows: dict, candidate: dict) -> dict:
        """Execute one sustained, un-gated verdict (dry-run: log only).
        Cooldown arms on every attempt — executed, dry-run, or failed —
        so even a raising actuator is retried at the settle pace, not
        hammered every tick."""
        action = candidate["action"]
        self._last_action_t = t0
        self._streak = 0
        self._streak_action = "hold"
        if self.cfg.dry_run:
            return self._decide(t0, candidate, outcome="dry_run")
        donors = sorted(
            n
            for n, r in rows.items()
            if r.get("eligible") and r.get("role") != ROLE_PREFILL
        )
        try:
            if action == "role_flip":
                self.actuator.set_role(
                    candidate["replica"], candidate["to"]
                )
                self.role_flips += 1
                self._record(
                    "controller.role_flip",
                    replica=candidate["replica"],
                    previous=candidate["from"],
                    role=candidate["to"],
                )
            elif action == "scale_up":
                result = self.actuator.scale_up(
                    role=candidate.get("role", "unified"), peers=donors
                ) or {}
                candidate = dict(
                    candidate,
                    replica=result.get("replica"),
                    donor=result.get("donor"),
                )
                self.scale_ups += 1
                self._record(
                    "controller.scale_up",
                    replica=candidate.get("replica"),
                    donor=candidate.get("donor"),
                    role=candidate.get("role"),
                )
            elif action == "scale_down":
                self.actuator.scale_down(
                    candidate["replica"], role=candidate.get("role")
                )
                self.scale_downs += 1
                self._record(
                    "controller.scale_down",
                    replica=candidate["replica"],
                    role=candidate.get("role"),
                )
        except (ActuatorError, OSError, ValueError) as e:
            self._record(
                "controller.actuator_error", action=action, error=str(e)
            )
            if self.anomaly is not None:
                self.anomaly.report(
                    "controller.actuator_error",
                    action=action,
                    error=str(e),
                )
            return self._decide(
                t0, candidate, outcome="actuator_error", error=str(e)
            )
        self.actions_executed += 1
        return self._decide(t0, candidate, outcome="executed")

    def _decide(
        self, t0: float, candidate: dict, *, outcome: str, **extra
    ) -> dict:
        decision = {
            "tick": self.ticks,
            "t": round(t0, 3),
            "action": candidate["action"],
            "outcome": outcome,
            "reason": candidate.get("reason", ""),
        }
        for key in ("replica", "from", "to", "role", "donor"):
            if candidate.get(key) is not None:
                decision[key] = candidate[key]
        decision.update(extra)
        self.decisions.append(decision)
        self._meter_decision(candidate["action"], outcome)
        # Every decision is observable; the flight ring gets the
        # *transitions* (a 5s-cadence hold would drown everything else
        # — the full log rides /debug/controller).
        signature = (candidate["action"], outcome)
        if signature != self._last_recorded or outcome in (
            "executed",
            "dry_run",
            "actuator_error",
        ):
            self._record(
                "controller.decision",
                action=candidate["action"],
                outcome=outcome,
                reason=decision["reason"],
            )
        self._last_recorded = signature
        return decision

    # ---------------------------------------------------------- snapshot

    def snapshot(self, last: int = 32) -> dict:
        """The ``GET /debug/controller`` body (any thread; plain reads
        of published values — one tick stale at worst)."""
        return {
            "ticks": self.ticks,
            "dry_run": self.cfg.dry_run,
            "actuator": getattr(self.actuator, "name", "none"),
            "actions": {
                "executed": self.actions_executed,
                "role_flips": self.role_flips,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
            },
            "replica_minutes": round(self.replica_minutes, 3),
            "replica_minutes_by_role": {
                role: round(v, 3)
                for role, v in sorted(self.replica_minutes_by_role.items())
            },
            "desired": self._desired,
            "observed": self._observed,
            "last_error": self._last_error,
            "decisions": list(self.decisions)[-last:],
            "config": {
                "interval_s": self.cfg.interval_s,
                "sustain_ticks": self.cfg.sustain_ticks,
                "cooldown_s": self.cfg.cooldown_s,
                "max_actions_per_tick": self.cfg.max_actions_per_tick,
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "hot_wait_s": self.cfg.hot_wait_s,
                "cold_wait_s": self.cfg.cold_wait_s,
                "dry_run": self.cfg.dry_run,
            },
        }
