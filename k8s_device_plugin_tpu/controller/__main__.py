"""CLI for the fleet autoscaler: ``python -m k8s_device_plugin_tpu.controller``.

Safe by default: ``--dry-run 1`` and ``--actuator none`` — point it at a
router and it observes, logging every decision it WOULD make to its
flight ring and ``GET /debug/controller`` without touching the fleet.
Arming it is two explicit choices: ``--dry-run 0 --actuator k8s``.

The knobs mirror :class:`~.reconciler.ControllerConfig`; the full
decision table and triage runbook live in docs/operations.md ("Fleet
autoscaling").
"""

from __future__ import annotations

import argparse
import sys
import time

from ..utils import flight as flight_mod
from ..utils.anomaly import AnomalyMonitor
from ..utils.flight import FlightRecorder, install_dump_handlers
from ..utils.metrics import MetricsRegistry
from ..utils.spans import SpanRecorder
from .actuators import KubernetesActuator, NullActuator
from .reconciler import (
    ControllerConfig,
    ControllerMetrics,
    Reconciler,
    fetch_fleet,
)
from .server import ControllerServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m k8s_device_plugin_tpu.controller",
        description=(
            "closed-loop fleet autoscaler: polls a router's /debug/fleet, "
            "computes a desired fleet spec from the host-side pressure "
            "signals, and converges the fleet through an actuator — role "
            "flips before hardware, warm scale-up, drain-down"
        ),
    )
    p.add_argument(
        "--url",
        required=True,
        help="router base URL to reconcile (e.g. http://router:8100)",
    )
    p.add_argument(
        "--host",
        default="0.0.0.0",
        help="bind host for the controller's own HTTP surface",
    )
    p.add_argument(
        "--port",
        type=int,
        default=8200,
        help="controller HTTP port (/metrics, /healthz, /debug/controller)",
    )
    p.add_argument(
        "--interval",
        type=float,
        default=5.0,
        help="seconds between reconcile ticks",
    )
    p.add_argument(
        "--dry-run",
        type=int,
        choices=[0, 1],
        default=1,
        help=(
            "1 (default): observe-only — decisions are computed, logged, "
            "and metered but the actuator is never called; 0 arms actuation"
        ),
    )
    p.add_argument(
        "--actuator",
        choices=["none", "k8s"],
        default="none",
        help=(
            "actuation backend: none refuses every action (pair with "
            "--dry-run 1); k8s exposes desired counts for an "
            "external-metrics adapter and dials replica admin endpoints "
            "for role flips (deploy/k8s-deploy-controller.yaml)"
        ),
    )
    p.add_argument(
        "--sustain-ticks",
        type=int,
        default=3,
        help=(
            "consecutive ticks a verdict must repeat before acting — the "
            "hysteresis/flap guard"
        ),
    )
    p.add_argument(
        "--cooldown-s",
        type=float,
        default=30.0,
        help="seconds after any action before the next one",
    )
    p.add_argument(
        "--max-actions-per-tick",
        type=int,
        default=1,
        help="ceiling on actions per reconcile tick",
    )
    p.add_argument(
        "--min-replicas",
        type=int,
        default=1,
        help="never drain the decode-capable pool below this",
    )
    p.add_argument(
        "--max-replicas",
        type=int,
        default=0,
        help="never scale the fleet above this (0 = uncapped)",
    )
    p.add_argument(
        "--hot-wait",
        type=float,
        default=2.0,
        help=(
            "queue-wait seconds above which a prefill replica counts as "
            "saturated (fallback when the router snapshot carries no "
            "thresholds)"
        ),
    )
    p.add_argument(
        "--cold-wait",
        type=float,
        default=0.5,
        help=(
            "queue-wait seconds below which a replica counts as idle / "
            "flip-eligible (fallback, as --hot-wait)"
        ),
    )
    p.add_argument(
        "--decision-log",
        type=int,
        default=256,
        help="decision-log ring capacity served at /debug/controller",
    )
    p.add_argument(
        "--dump-dir",
        default=flight_mod.default_dump_dir() or "",
        help="directory for flight dumps and postmortem bundles "
        "(default: $TPU_PLUGIN_DUMP_DIR): SIGUSR2/exit dumps the "
        "flight ring, and every actuator-failure incident snapshots "
        "the controller's forensic state (utils/postmortem.py)",
    )
    p.add_argument(
        "--dump-budget-mb",
        type=int,
        default=0,
        help="retention budget (MiB) for --dump-dir, shared by flight "
        "dumps and postmortem bundles: after every write the oldest "
        "entries are pruned until the directory fits (0 = unbounded)",
    )
    args = p.parse_args(argv)

    try:
        cfg = ControllerConfig(
            interval_s=args.interval,
            sustain_ticks=args.sustain_ticks,
            cooldown_s=args.cooldown_s,
            max_actions_per_tick=args.max_actions_per_tick,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            hot_wait_s=args.hot_wait,
            cold_wait_s=args.cold_wait,
            dry_run=bool(args.dry_run),
            decision_log=args.decision_log,
        )
    except ValueError as e:
        p.error(str(e))

    registry = MetricsRegistry()
    # Registered so SIGUSR2/atexit dumps include the controller's ring;
    # the span ring rides the same dumps (trace-assembler input).
    flight = flight_mod.register(
        FlightRecorder(capacity=2048, name="controller")
    )
    spans = flight_mod.register_spans(
        SpanRecorder(capacity=512, name="controller")
    )
    install_dump_handlers(args.dump_dir or None)
    if args.dump_budget_mb:
        flight_mod.set_dump_budget(args.dump_budget_mb * 1024 * 1024)
    anomaly = AnomalyMonitor(flight=flight)
    actuator = (
        KubernetesActuator() if args.actuator == "k8s" else NullActuator()
    )
    reconciler = Reconciler(
        lambda: fetch_fleet(args.url),
        actuator,
        config=cfg,
        metrics=ControllerMetrics(registry),
        flight=flight,
        anomaly=anomaly,
    )
    server = ControllerServer(
        reconciler, registry, host=args.host, port=args.port, spans=spans
    )
    if args.dump_dir:
        # Incident-triggered local postmortem capture: an actuator
        # failure snapshots the decision log + flight ring before the
        # rings roll (utils/postmortem.py).
        from ..utils.postmortem import PostmortemCapture

        capture = PostmortemCapture(
            "controller",
            args.dump_dir,
            flight=flight,
            spans=spans,
            registry=registry,
            state_fn=server._debug_state,
            budget_bytes=(
                args.dump_budget_mb * 1024 * 1024
                if args.dump_budget_mb
                else None
            ),
        )
        anomaly.add_listener(capture.on_incident)
    server.start()
    print(
        f"controller: reconciling {args.url} every {cfg.interval_s}s "
        f"(dry_run={cfg.dry_run}, actuator={actuator.name}) — "
        f"http on {args.host}:{server.port}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
