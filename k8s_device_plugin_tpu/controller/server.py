"""ControllerServer — the reconciler's daemon shell.

Pairs a tick loop (``stop.wait(interval_s)`` cadence, same shape as the
router's CanaryProber) with an HTTP surface reusing the shared
:class:`~..utils.metrics.MetricsServer`:

- ``GET /debug/controller`` — the reconciler's decision log, desired vs
  observed spec, replica-minutes ledger, and config (what
  ``tools/fleet_plan.py --controller-url`` renders).
- ``GET /metrics`` — the ``tpu_controller_*`` families; in the k8s
  shape this exposition IS the actuation surface
  (``tpu_controller_desired_replicas`` scraped by an external-metrics
  adapter — deploy/k8s-deploy-controller.yaml).
- ``GET /healthz`` — 200 while the tick loop is alive, 503 once it
  dies, so a liveness probe restarts a wedged controller.

A tick that raises is recorded (``controller.tick_error``) and the loop
continues — the controller must outlive any single bad snapshot.
"""

from __future__ import annotations

import threading

from ..utils.metrics import MetricsServer
from .reconciler import Reconciler


class ControllerServer:
    """Own the reconciler's tick thread + HTTP surface.  ``port=0``
    picks a free port (tests); ``.port`` reports it."""

    def __init__(
        self,
        reconciler: Reconciler,
        registry,
        *,
        host: str = "0.0.0.0",
        port: int = 8200,
        spans=None,
    ):
        self.reconciler = reconciler
        self.spans = spans
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Forensics parity with the router and plugin daemon: the
        # controller's flight ring and span ring are pullable surfaces,
        # so the fleet postmortem collector (router/postmortem.py) can
        # join controller decisions into an incident timeline.
        debug = {
            "/debug/controller": self._debug_controller,
            "/debug/state": self._debug_state,
        }
        if reconciler.flight is not None:
            debug["/debug/flight"] = reconciler.flight.snapshot
        if spans is not None:
            debug["/debug/spans"] = lambda query: spans.dump(
                trace_id=(query.get("rid") or [None])[0]
            )
        if reconciler.anomaly is not None:
            debug["/debug/incidents"] = reconciler.anomaly.snapshot
        self._http = MetricsServer(
            registry,
            host=host,
            port=port,
            health=self._loop_alive,
            debug=debug,
        )

    def _loop_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _debug_controller(self, query) -> dict:
        last = 32
        try:
            last = int(query.get("last", ["32"])[0])
        except (TypeError, ValueError):
            pass
        return self.reconciler.snapshot(last=last)

    def _debug_state(self) -> dict:
        """The controller's ``/debug/state``-equivalent — what the fleet
        postmortem collector pulls alongside flight/spans/metrics."""
        return {
            "component": "controller",
            "loop_alive": self._loop_alive(),
            "controller": self.reconciler.snapshot(last=32),
        }

    @property
    def port(self) -> int:
        return self._http.port

    def _run(self) -> None:
        while not self._stop.wait(self.reconciler.cfg.interval_s):
            try:
                self.reconciler.tick()
            except Exception as e:  # the loop must outlive a bad tick
                self.reconciler._record("controller.tick_error", error=str(e))

    def start(self) -> None:
        assert self._thread is None
        self._thread = threading.Thread(
            target=self._run, name="tpu-controller", daemon=True
        )
        self._thread.start()
        self._http.start()
        self.reconciler._record(
            "controller.started",
            interval_s=self.reconciler.cfg.interval_s,
            dry_run=self.reconciler.cfg.dry_run,
            actuator=getattr(self.reconciler.actuator, "name", "none"),
        )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._http.stop()
        self.reconciler._record(
            "controller.stopped", ticks=self.reconciler.ticks
        )
