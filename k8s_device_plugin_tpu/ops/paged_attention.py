"""Pallas paged-attention decode kernel: attention over page-table-
indirected KV pools.

The gather path (models/transformer.py paged decode) materializes every
slot's logical [max_len] K/V view in HBM before the attention einsum —
correct, but it writes (and re-reads) max_len bytes per slot per step even
when a sequence occupies two pages.  This kernel reads pages DIRECTLY from
the pool: the page table rides Pallas's scalar-prefetch lane, so each grid
step's BlockSpec index map picks its physical page (`table[b, p]`) and the
DMA engine streams the pages a slot points at — no intermediate view.
`pl.when` gates only the kernel body, NOT the pipeline's block copies, so
O(len)-not-O(max_len) traffic additionally requires that a row's dead
TAIL entries alias one page (the serving engine guarantees this: idle,
window-reclaimed, and not-yet-written entries all point at scratch page
0, whose repeated index skips re-fetch — the table frontier is published
lazily as each sequence grows).

Design (same language as ops/flash_attention.py):

- grid (batch, pages): batch parallel, the page axis sequential.  Each
  step's K/V block is a FULL page — all kv heads, ``[page_size,
  kv_heads, head_dim]`` — so every live page is fetched exactly once per
  row (the round-2 design blocked one kv head per step, which Mosaic
  rejects — a block's second-to-last dim must be 8-divisible or span the
  array — and would have re-fetched each page once per kv head);
- inside the kernel a STATIC unrolled loop over kv heads scores each
  head's q-group tile ([group_pad, head_dim]) against its slice of the
  resident page, carrying per-head lane-replicated [group_pad, 128]
  online-softmax state (running max / denominator) and an f32 output
  accumulator, all stacked ``[kv_heads, ...]`` in VMEM scratch;
- GQA-native: one page fetch serves every q head;
- pages past a slot's length skip all matmuls via `pl.when` (the grid
  is rectangular; dead pages cost one predicate);
- per-position masking inside the frontier page via iota < len;
- f32 pools matmul at ``Precision.HIGHEST`` (the MXU's default bf16
  passes cost ~2e-3 relative error, measured on v5e; bf16 pools use the
  native path);
- int8 pools (``GPTConfig.quant_kv``) stream as int8 — HALF the decode
  HBM traffic — with per-(slot, head) scale pools riding as extra
  blocks; the scale factors out of the head_dim dot, so pages matmul on
  the exact int8→bf16 cast and scales multiply the small score matrix.

Status: Mosaic-compiled and parity-checked against an f32 host oracle on
real v5e hardware (round 3 session 2; MHA/GQA/MQA, windowed, bf16+f32,
page sizes 8/16 — see BASELINE.md).  Reference analogue: none — the
reference delegates all compute to the workload image (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")

# TPU vector registers are 8 sublanes x 128 lanes; a q tile shorter than 8
# rows would be sub-sublane, so the head group is padded up to this.
_MIN_GROUP_TILE = 8


def _paged_kernel(
    table_ref,  # scalar-prefetch: [batch, pages] int32
    lens_ref,  # scalar-prefetch: [batch] int32
    q_ref,  # [1, kv_heads, group_pad, head_dim]
    k_ref,  # [1, page_size, kv_heads, head_dim] — one full page
    v_ref,
    *rest,  # int8 pools: sk_ref, sv_ref [1, kv_heads, page_size] f32; then
    # o_ref [1, kv_heads, group_pad, head_dim],
    # m_ref VMEM [kv_heads, group_pad, 128] f32 lane-replicated running max,
    # l_ref VMEM [kv_heads, group_pad, 128] f32 running denominator,
    # acc_ref VMEM [kv_heads, group_pad, head_dim] f32
    page_size: int,
    num_pages: int,
    kv_heads: int,
    sm_scale: float,
    window: int | None,
    quant: bool,
):
    if quant:
        sk_ref, sv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b, p = pl.program_id(0), pl.program_id(1)
    length = lens_ref[b]  # valid cache slots: positions [0, length)
    # Sliding window: the (single) query sits at position length-1 and sees
    # keys in (length-1-window, length-1] — i.e. col >= length - window —
    # matching the gather path's `q_pos - key_pos < window` mask
    # (models/transformer.py cached_group_attention).
    lo = length - window if window is not None else 0

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    def _page():
        # f32 operands need HIGHEST or the MXU's bf16 passes cost ~2e-3.
        prec = (
            jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32 else None
        )
        # Mask positions at/past the frontier (the partial last page) and,
        # under a sliding window, positions that scrolled out — the mask
        # is head-independent, so it is built once outside the unroll.
        group_pad = q_ref.shape[2]
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (group_pad, page_size), 1
        )
        valid = col < length
        if window is not None:
            valid = jnp.logical_and(valid, col >= lo)
        for h in range(kv_heads):  # static unroll: one page, every kv head
            q = q_ref[0, h]  # [group_pad, head_dim]
            k = k_ref[0, :, h, :]  # [page_size, head_dim]
            v = v_ref[0, :, h, :]
            if quant:
                # int8 pages: the per-(position, head) scale factors OUT
                # of the dot over head_dim, so the page matmuls on the
                # EXACT int8→compute-dtype cast (|x| ≤ 127 is exact in
                # bf16) and the scale multiplies the small [group_pad,
                # page_size] score matrix in f32 — no dequantized page
                # materializes, and no bf16 rounding of scaled K (the
                # gather path rounds; this path is strictly closer to the
                # f32 math).
                k = k.astype(q.dtype)
            s = (
                jax.lax.dot_general(
                    q,
                    k,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=prec,
                )
                * sm_scale
            )  # [group_pad, page_size]
            if quant:
                s = s * sk_ref[0, h][None, :]
            s = jnp.where(valid, s, NEG_INF)

            m_prev = m_ref[h, :, :1]
            l_prev = l_ref[h, :, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            seen = m_new > NEG_INF
            prob = jnp.where(seen, jnp.exp(s - jnp.where(seen, m_new, 0.0)), 0.0)
            alpha = jnp.where(
                seen, jnp.exp(jnp.where(seen, m_prev - m_new, 0.0)), 0.0
            )
            l_ref[h] = jnp.broadcast_to(
                alpha * l_prev + jnp.sum(prob, axis=-1, keepdims=True),
                l_ref.shape[1:],
            )
            if quant:
                # V's scale rides the probabilities (same factoring as K).
                prob = prob * sv_ref[0, h][None, :]
                v = v.astype(q.dtype)
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                prob.astype(v.dtype),
                v,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec,
            )
            m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])

    # Pages wholly past the frontier — or wholly scrolled out of the
    # window — skip all matmuls.
    live = p * page_size < length
    if window is not None:
        live = jnp.logical_and(live, (p + 1) * page_size > lo)
    pl.when(live)(_page)

    @pl.when(p == num_pages - 1)
    def _finish():
        for h in range(kv_heads):
            l = l_ref[h, :, :1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h] = (acc_ref[h] / l_safe).astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    page_table: jax.Array,
    lens: jax.Array,
    *,
    scale_k: jax.Array | None = None,
    scale_v: jax.Array | None = None,
    sm_scale: float | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged KV pool.

    q: [batch, num_heads, head_dim] — the current token's queries.
    pool_k/pool_v: [num_pool_pages, page_size, kv_heads, head_dim].
    page_table: [batch, pages_per_seq] int32 physical page ids.
    lens: [batch] int32 — valid cache slots per row (the current token's
    K/V must already be written: ``lens = position + 1``).

    Returns [batch, num_heads, head_dim].  GQA-native: ``kv_heads`` must
    divide ``num_heads``; each group shares its kv head's resident page.

    ``window``: sliding attention window — the query sees only the last
    ``window`` positions (same semantics as the gather path / flash
    kernel's window mask); pages wholly outside it skip compute, and the
    serving engine additionally re-points their table entries at scratch
    so they skip fetch too (windowed page reclamation).

    ``scale_k``/``scale_v``: int8 KV pools — when the pools are int8
    (``GPTConfig.quant_kv``), pass the per-(page-slot, kv-head) f32 scale
    pools ``[num_pool_pages, page_size, kv_heads]`` and the kernel
    streams int8 pages (HALF the decode HBM traffic) and applies scales
    on the score matrix, where they factor out of the head_dim dot.

    Traffic note: table entries past a row's live pages are read by the
    pipeline regardless of the dead-page predicate (see module docstring)
    — point them all at one scratch page to keep per-row traffic O(len).
    models/engine.py does exactly this: idle rows, window-reclaimed
    entries, AND not-yet-written generation pages all alias scratch page
    0 (the table frontier extends lazily as the sequence grows).
    """
    batch, num_heads, head_dim = q.shape
    kv_heads, page_size = pool_k.shape[2], pool_k.shape[1]
    pages_per_seq = page_table.shape[1]
    if num_heads % kv_heads:
        raise ValueError(f"num_heads {num_heads} not a multiple of kv_heads {kv_heads}")
    quant = pool_k.dtype == jnp.int8
    if pool_v.dtype != pool_k.dtype:
        raise ValueError(
            f"pool dtypes must match, got k={pool_k.dtype} v={pool_v.dtype}"
        )
    if quant and (scale_k is None or scale_v is None):
        raise ValueError("int8 pools require scale_k and scale_v scale pools")
    if not quant and (scale_k is not None or scale_v is not None):
        raise ValueError(f"scale pools passed with {pool_k.dtype} (non-int8) pools")
    group = num_heads // kv_heads
    if sm_scale is None:
        sm_scale = head_dim ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    group_pad = max(group, _MIN_GROUP_TILE)
    q4 = q.reshape(batch, kv_heads, group, head_dim)
    if group_pad != group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))

    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    kernel = functools.partial(
        _paged_kernel,
        page_size=page_size,
        num_pages=pages_per_seq,
        kv_heads=kv_heads,
        sm_scale=sm_scale,
        window=window,
        quant=quant,
    )
    qo_spec = pl.BlockSpec(
        (1, kv_heads, group_pad, head_dim),
        lambda b, p, table, lens: (b, 0, 0, 0),
    )
    page_spec = pl.BlockSpec(
        (1, page_size, kv_heads, head_dim),
        lambda b, p, table, lens: (table[b, p], 0, 0, 0),
    )
    in_specs = [qo_spec, page_spec, page_spec]
    operands = [q4, pool_k, pool_v]
    if quant:
        # Scales ride as [pool, kv_heads, page_size] so the in-kernel
        # slice [0, h] lands on the LANE axis, matching the score
        # matrix's page_size lanes (the engine stores [pool, page_size,
        # kv_heads]; this transpose moves KB, the pools move MB).
        scale_spec = pl.BlockSpec(
            (1, kv_heads, page_size),
            lambda b, p, table, lens: (table[b, p], 0, 0),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [
            jnp.swapaxes(scale_k, 1, 2),
            jnp.swapaxes(scale_v, 1, 2),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, pages_per_seq),
        in_specs=in_specs,
        out_specs=qo_spec,
        scratch_shapes=[
            pltpu.VMEM((kv_heads, group_pad, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group_pad, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group_pad, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (batch, kv_heads, group_pad, head_dim), q.dtype
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(page_table, lens, *operands)
    return out[:, :, :group, :].reshape(batch, num_heads, head_dim)
