"""Split-K flash-decode paged attention: page-table-indirected KV pools
streamed once with online softmax, partitioned across a split axis.

The gather path (models/transformer.py paged decode) materializes every
slot's logical [max_len] K/V view in HBM before the attention einsum —
correct, but it writes (and re-reads) max_len bytes per slot per step even
when a sequence occupies two pages.  This kernel reads pages DIRECTLY from
the pool: the page table rides Pallas's scalar-prefetch lane, so each grid
step's BlockSpec index map picks its physical page (`table[b, page]`) and
the DMA engine streams the pages a slot points at — no intermediate view.

Split-K (the flash-decode shape, new in this round): decode attention has
ONE query per slot, so the page axis is the only parallelism available —
and the previous kernel walked it sequentially, serializing a long
context behind one program.  Now each sequence's page list is partitioned
across a ``num_splits`` grid axis: every program computes a partial
``(running max m, denominator l, unnormalized accumulator acc)`` over its
page span with online softmax, and a cheap second-stage combine reduces
the partials exactly:

    m* = max_s m_s;   alpha_s = exp(m_s - m*)
    out = (sum_s alpha_s * acc_s) / (sum_s alpha_s * l_s)

Short contexts pick the degenerate 1-split (ops/tuning.py), which skips
the combine entirely and emits the normalized output straight from the
kernel — the previous single-pass behavior.

Quantized pools dequantize INSIDE the kernel, never in HBM:

- int8 pools stream as int8 with per-(slot, head) scale pools riding as
  extra blocks; the scale factors out of the head_dim dot, so pages
  matmul on the exact int8→compute-dtype cast and scales multiply the
  small score matrix (the gather path materializes a full dequantized
  [max_len] view first — the traffic this fusion deletes);
- int4-packed pools (two signed nibbles per byte along head_dim,
  ops/quant.py ``quantize_kv4``) unpack in VMEM with sign-extending
  shifts — a QUARTER of the bf16 page bytes; same score-side scales.

Backend routing: on TPU the Pallas kernel compiles under Mosaic.  On CPU
(the engine's parity/smoke environment) the SAME split-K math runs as a
vectorized XLA program (``_decode_xla``) — algebraically identical
(same split partition, same online-softmax/combine associativity), which
is what took the CPU smoke rows from the old Pallas-interpreter's
0.06–0.12x of the gather path to >=1x (the KERNELS ledger,
`benchmark.py --kernel`).  Passing ``interpret=True`` still forces the
real kernel through the Pallas interpreter — that is the parity lane for
the kernel itself (tests/test_paged_attention.py), not a serving path.

Status: the PREVIOUS single-pass kernel was Mosaic-compiled and
parity-checked on real v5e (rounds 3/5, BASELINE.md).  The split-K
rewrite keeps its page/block geometry (full-page blocks, scalar-prefetch
table, lane-replicated f32 state) but adds the split grid axis and
partial outputs — interpreter parity is pinned; a hardware round must
re-prove Mosaic and fill the tuning rows before `use_kernel` defaults on
(docs/kernels.md "Fallback & parity contract").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tuning

NEG_INF = float("-inf")

# TPU vector registers are 8 sublanes x 128 lanes; a q tile shorter than 8
# rows would be sub-sublane, so the head group is padded up to this.
_MIN_GROUP_TILE = 8

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# repo meets (the hardware image vs the CPU driver image); resolve once so
# the kernel builds on both.
_COMPILER_PARAMS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _unpack_int4(packed: jax.Array, dtype) -> jax.Array:
    """Sign-extend an int4-packed array (two nibbles per int8 byte along
    the last axis; element 2i in the LOW nibble) to ``dtype`` with twice
    the last-dim width.  Plain shifts + one interleave reshape — works
    identically in the Pallas kernel, the interpreter, and the XLA
    route, so every backend computes the same bytes."""
    x = packed.astype(jnp.int32)
    lo = jnp.right_shift(jnp.left_shift(x, 28), 28)
    hi = jnp.right_shift(jnp.left_shift(x, 24), 28)
    both = jnp.stack([lo, hi], axis=-1)  # [..., d/2, 2]
    return both.reshape(*packed.shape[:-1], packed.shape[-1] * 2).astype(dtype)


def _combine_splits(o_part, m_part, l_part, out_dtype):
    """Second-stage reduction over the split axis (axis 1).

    ``o_part``: [batch, splits, kv_heads, group, head_dim] f32 unnormalized
    accumulators; ``m_part``/``l_part``: [batch, splits, kv_heads, group]
    f32 running max / denominator.  Empty splits carry (m=-inf, l=0,
    acc=0) and contribute exactly nothing; a row with NO live split (a
    fully-masked query — the engine never produces one, lens >= 1)
    returns zeros rather than NaN.
    """
    m_star = jnp.max(m_part, axis=1, keepdims=True)  # [b, 1, hk, g]
    seen = m_part > NEG_INF
    alpha = jnp.where(
        seen, jnp.exp(jnp.where(seen, m_part - m_star, 0.0)), 0.0
    )
    denom = jnp.sum(alpha * l_part, axis=1)  # [b, hk, g]
    out = jnp.sum(alpha[..., None] * o_part, axis=1)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    return (out / denom[..., None]).astype(out_dtype)


def _page_update(
    q_ref, k_ref, v_ref, sk_ref, sv_ref, m_ref, l_ref, acc_ref,
    *, p_abs, length, lo, page_size: int, kv_heads: int, sm_scale: float,
    window, quant: bool, int4: bool,
):
    """Online-softmax update of the VMEM state triple with one resident
    page (all kv heads), shared by the 1-split and split-K kernels.
    ``p_abs`` is the page's ABSOLUTE index in the row's logical order —
    masking is positional, so splits never change the math."""
    # f32 operands need HIGHEST or the MXU's bf16 passes cost ~2e-3.
    prec = jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32 else None
    # Mask positions at/past the frontier (the partial last page) and,
    # under a sliding window, positions that scrolled out — the mask is
    # head-independent, so it is built once outside the unroll.
    group_pad = q_ref.shape[-2]
    col = p_abs * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (group_pad, page_size), 1
    )
    valid = col < length
    if window is not None:
        valid = jnp.logical_and(valid, col >= lo)
    for h in range(kv_heads):  # static unroll: one page, every kv head
        q = q_ref[0, h]  # [group_pad, head_dim]
        k = k_ref[0, :, h, :]  # [page_size, head_dim(/2 packed)]
        v = v_ref[0, :, h, :]
        if int4:
            # int4 pages: two sign-extended nibbles per byte unpack in
            # VMEM — a quarter of the bf16 page traffic; scales factor
            # onto the score matrix exactly like int8's.
            k = _unpack_int4(k, q.dtype)
        elif quant:
            # int8 pages: the per-(position, head) scale factors OUT of
            # the dot over head_dim, so the page matmuls on the EXACT
            # int8→compute-dtype cast (|x| <= 127 is exact in bf16) and
            # the scale multiplies the small [group_pad, page_size]
            # score matrix in f32 — no dequantized page materializes.
            k = k.astype(q.dtype)
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec,
            )
            * sm_scale
        )  # [group_pad, page_size]
        if quant:
            s = s * sk_ref[0, h][None, :]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[h, :, :1]
        l_prev = l_ref[h, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        seen = m_new > NEG_INF
        prob = jnp.where(seen, jnp.exp(s - jnp.where(seen, m_new, 0.0)), 0.0)
        alpha = jnp.where(
            seen, jnp.exp(jnp.where(seen, m_prev - m_new, 0.0)), 0.0
        )
        l_ref[h] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(prob, axis=-1, keepdims=True),
            l_ref.shape[1:],
        )
        if int4:
            prob = prob * sv_ref[0, h][None, :]
            v = _unpack_int4(v, q.dtype)
        elif quant:
            # V's scale rides the probabilities (same factoring as K).
            prob = prob * sv_ref[0, h][None, :]
            v = v.astype(q.dtype)
        acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
            prob.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )
        m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])


def _paged_kernel(
    table_ref,  # scalar-prefetch: [batch, splits * pages_per_split] int32
    lens_ref,  # scalar-prefetch: [batch] int32
    q_ref,  # [1, kv_heads, group_pad, head_dim]
    k_ref,  # [1, page_size, kv_heads, head_dim(/2)] — one full page
    v_ref,
    *rest,  # quant: sk_ref, sv_ref [1, kv_heads, page_size] f32; then the
    # outputs (1-split: o_ref [1, kv_heads, group_pad, head_dim]; split-K:
    # o_ref [1, 1, kv_heads, group_pad, head_dim] f32 partial +
    # m/l partial refs [1, 1, kv_heads, group_pad, 128] f32), then VMEM
    # scratch m/l [kv_heads, group_pad, 128] + acc [kv_heads, group_pad,
    # head_dim] f32
    page_size: int,
    pages_per_split: int,
    num_splits: int,
    kv_heads: int,
    sm_scale: float,
    window,
    quant: bool,
    int4: bool,
):
    if quant:
        sk_ref, sv_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        sk_ref = sv_ref = None
    if num_splits == 1:
        o_ref, m_ref, l_ref, acc_ref = rest
        mo_ref = lo_ref = None
    else:
        o_ref, mo_ref, lo_ref, m_ref, l_ref, acc_ref = rest
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    p_abs = s * pages_per_split + p
    length = lens_ref[b]  # valid cache slots: positions [0, length)
    # Sliding window: the (single) query sits at position length-1 and sees
    # keys in (length-1-window, length-1] — i.e. col >= length - window —
    # matching the gather path's `q_pos - key_pos < window` mask
    # (models/transformer.py cached_group_attention).
    lo = length - window if window is not None else 0

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    # Pages wholly past the frontier — or wholly scrolled out of the
    # window — skip all matmuls (the grid is rectangular; dead pages cost
    # one predicate).  Split padding pages land here too: their absolute
    # position starts at/past max_len >= length.
    live = p_abs * page_size < length
    if window is not None:
        live = jnp.logical_and(live, (p_abs + 1) * page_size > lo)
    pl.when(live)(
        lambda: _page_update(
            q_ref, k_ref, v_ref, sk_ref, sv_ref, m_ref, l_ref, acc_ref,
            p_abs=p_abs, length=length, lo=lo, page_size=page_size,
            kv_heads=kv_heads, sm_scale=sm_scale, window=window,
            quant=quant, int4=int4,
        )
    )

    @pl.when(p == pages_per_split - 1)
    def _finish():
        if num_splits == 1:
            # Degenerate split: normalize in-kernel, no combine stage.
            for h in range(kv_heads):
                l = l_ref[h, :, :1]
                l_safe = jnp.where(l == 0.0, 1.0, l)
                o_ref[0, h] = (acc_ref[h] / l_safe).astype(o_ref.dtype)
        else:
            # Emit this split's partial triple; _combine_splits reduces.
            o_ref[0, 0] = acc_ref[...]
            mo_ref[0, 0] = m_ref[...]
            lo_ref[0, 0] = l_ref[...]


def _paged_pallas(
    q4, pool_k, pool_v, table, lens, scale_k, scale_v,
    *, sm_scale, window, num_splits, quant, int4, interpret,
):
    """The Pallas lane: compiled under Mosaic on TPU, interpreter when
    ``interpret`` (the kernel-parity tests).  ``q4`` is [batch, kv_heads,
    group_pad, head_dim] with the group padded to the sublane tile."""
    batch, kv_heads, group_pad, head_dim = q4.shape
    page_size = pool_k.shape[1]
    mpp = table.shape[1]
    pages_per_split = -(-mpp // num_splits)
    if pages_per_split * num_splits != mpp:
        # Pad the table so every split spans the same page count; padding
        # entries alias page 0 (the engine's scratch page — repeated
        # indices skip re-fetch) and their absolute positions start at
        # >= max_len, so the dead-page predicate skips their compute.
        table = jnp.pad(
            table, ((0, 0), (0, pages_per_split * num_splits - mpp))
        )
    kernel = functools.partial(
        _paged_kernel,
        page_size=page_size,
        pages_per_split=pages_per_split,
        num_splits=num_splits,
        kv_heads=kv_heads,
        sm_scale=sm_scale,
        window=window,
        quant=quant,
        int4=int4,
    )
    q_spec = pl.BlockSpec(
        (1, kv_heads, group_pad, head_dim),
        lambda b, s, p, table, lens: (b, 0, 0, 0),
    )
    page_spec = pl.BlockSpec(
        (1, page_size, kv_heads, pool_k.shape[3]),
        lambda b, s, p, table, lens: (
            table[b, s * pages_per_split + p], 0, 0, 0,
        ),
    )
    in_specs = [q_spec, page_spec, page_spec]
    operands = [q4, pool_k, pool_v]
    if quant:
        # Scales ride as [pool, kv_heads, page_size] so the in-kernel
        # slice [0, h] lands on the LANE axis, matching the score
        # matrix's page_size lanes (the engine stores [pool, page_size,
        # kv_heads]; this transpose moves KB, the pools move MB).
        scale_spec = pl.BlockSpec(
            (1, kv_heads, page_size),
            lambda b, s, p, table, lens: (
                table[b, s * pages_per_split + p], 0, 0,
            ),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [
            jnp.swapaxes(scale_k, 1, 2),
            jnp.swapaxes(scale_v, 1, 2),
        ]
    if num_splits == 1:
        out_specs = pl.BlockSpec(
            (1, kv_heads, group_pad, head_dim),
            lambda b, s, p, table, lens: (b, 0, 0, 0),
        )
        out_shape = jax.ShapeDtypeStruct(
            (batch, kv_heads, group_pad, head_dim), q4.dtype
        )
    else:
        part_spec = pl.BlockSpec(
            (1, 1, kv_heads, group_pad, head_dim),
            lambda b, s, p, table, lens: (b, s, 0, 0, 0),
        )
        ml_spec = pl.BlockSpec(
            (1, 1, kv_heads, group_pad, 128),
            lambda b, s, p, table, lens: (b, s, 0, 0, 0),
        )
        out_specs = [part_spec, ml_spec, ml_spec]
        out_shape = [
            jax.ShapeDtypeStruct(
                (batch, num_splits, kv_heads, group_pad, head_dim),
                jnp.float32,
            ),
            jax.ShapeDtypeStruct(
                (batch, num_splits, kv_heads, group_pad, 128), jnp.float32
            ),
            jax.ShapeDtypeStruct(
                (batch, num_splits, kv_heads, group_pad, 128), jnp.float32
            ),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, num_splits, pages_per_split),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((kv_heads, group_pad, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group_pad, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group_pad, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # batch and split axes are independent; the page axis carries the
        # online-softmax scratch between iterations (sequential).
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(table, lens, *operands)
    if num_splits == 1:
        return out
    o_part, m_part, l_part = out
    return _combine_splits(
        o_part, m_part[..., 0], l_part[..., 0], q4.dtype
    )


def _decode_xla(
    q4, pool_k, pool_v, table, lens, scale_k, scale_v,
    *, sm_scale, window, num_splits, quant, int4,
):
    """The XLA lane: the SAME split-K online-softmax math as the kernel,
    vectorized over the split axis — the CPU serving/parity route (and
    the reference the interpreter parity suite checks the kernel
    against).  ``q4`` is [batch, kv_heads, group, head_dim] UNPADDED
    (no tile constraints off-chip)."""
    batch, kv_heads, group, head_dim = q4.shape
    page_size = pool_k.shape[1]
    mpp = table.shape[1]
    prec = jax.lax.Precision.HIGHEST if q4.dtype == jnp.float32 else None
    splits = num_splits
    pps = -(-mpp // splits)
    if pps * splits != mpp:
        table = jnp.pad(table, ((0, 0), (0, pps * splits - mpp)))
    span = pps * page_size  # positions per split
    # One page-indexed gather per pool — the same bytes the gather path
    # reads, but nothing dequantized is ever materialized at [max_len]
    # width: integer codes cast inside the fused attention computation
    # and scales multiply the score matrix, not the operands.
    k = pool_k[table].reshape(batch, splits, span, kv_heads, -1)
    v = pool_v[table].reshape(batch, splits, span, kv_heads, -1)
    if int4:
        k = _unpack_int4(k, q4.dtype)
        v = _unpack_int4(v, q4.dtype)
    elif k.dtype != q4.dtype:
        k = k.astype(q4.dtype)
        v = v.astype(q4.dtype)
    s = jnp.einsum(
        "bhgd,bslhd->bshgl", q4, k,
        preferred_element_type=jnp.float32, precision=prec,
    ) * sm_scale  # [b, S, hk, g, span]
    if quant:
        sk = scale_k[table].reshape(batch, splits, span, kv_heads)
        s = s * sk.transpose(0, 1, 3, 2)[:, :, :, None, :]
    col = jnp.arange(splits * span, dtype=jnp.int32).reshape(splits, span)
    col = col[None, :, None, None, :]
    ln = lens[:, None, None, None, None]
    valid = col < ln
    if window is not None:
        valid = jnp.logical_and(valid, col >= ln - window)
    s = jnp.where(valid, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # per-split running max
    seen = m > NEG_INF
    p = jnp.where(seen, jnp.exp(s - jnp.where(seen, m, 0.0)), 0.0)
    l = jnp.sum(p, axis=-1)  # [b, S, hk, g]
    if quant:
        sv = scale_v[table].reshape(batch, splits, span, kv_heads)
        p = p * sv.transpose(0, 1, 3, 2)[:, :, :, None, :]
    acc = jnp.einsum(
        "bshgl,bslhd->bshgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32, precision=prec,
    )
    return _combine_splits(acc, m[..., 0], l, q4.dtype)


def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    page_table: jax.Array,
    lens: jax.Array,
    *,
    scale_k: jax.Array | None = None,
    scale_v: jax.Array | None = None,
    sm_scale: float | None = None,
    window: int | None = None,
    num_splits: int | None = None,
    kv_format: str | None = None,
    interpret: bool | None = None,
    use_pallas: bool | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged KV pool (split-K).

    q: [batch, num_heads, head_dim] — the current token's queries.
    pool_k/pool_v: [num_pool_pages, page_size, kv_heads, head_dim] —
    float pools, int8 pools, or int4-packed pools ([..., head_dim//2]
    int8, two signed nibbles per byte; ops/quant.py ``quantize_kv4``).
    page_table: [batch, pages_per_seq] int32 physical page ids.
    lens: [batch] int32 — valid cache slots per row (the current token's
    K/V must already be written: ``lens = position + 1``).

    Returns [batch, num_heads, head_dim].  GQA-native: ``kv_heads`` must
    divide ``num_heads``; each group shares its kv head's resident page.

    ``window``: sliding attention window — the query sees only the last
    ``window`` positions (same semantics as the gather path); pages
    wholly outside it skip compute, and the serving engine additionally
    re-points their table entries at scratch so they skip fetch too.

    ``num_splits``: how many grid programs partition each row's page
    list (None = the per-generation tuning table, ops/tuning.py — 1 on
    CPU and for short contexts, where the combine stage is skipped
    entirely).  The split changes float association only through the
    documented combine; every split count computes the same attention.

    ``kv_format``: None infers "f" (float pools) or "int8" from the pool
    dtype; pass "int4" for packed pools (also auto-inferred when the
    pool's trailing dim is head_dim//2).  Quantized formats require
    ``scale_k``/``scale_v`` pools [num_pool_pages, page_size, kv_heads].

    ``use_pallas``/``interpret``: None routes TPU to the compiled Mosaic
    kernel and everything else to the vectorized XLA implementation of
    the same math; ``interpret=True`` forces the real kernel through the
    Pallas interpreter (the kernel-parity lane).

    Traffic note: table entries past a row's live pages are read by the
    pipeline regardless of the dead-page predicate — point them all at
    one scratch page to keep per-row traffic O(len).  models/engine.py
    does exactly this (idle rows, window-reclaimed entries, and
    not-yet-written generation pages all alias scratch page 0).
    """
    batch, num_heads, head_dim = q.shape
    kv_heads, page_size = pool_k.shape[2], pool_k.shape[1]
    pages_per_seq = page_table.shape[1]
    if num_heads % kv_heads:
        raise ValueError(
            f"num_heads {num_heads} not a multiple of kv_heads {kv_heads}"
        )
    if pool_v.dtype != pool_k.dtype or pool_v.shape != pool_k.shape:
        raise ValueError(
            f"pools must match, got k={pool_k.dtype}{pool_k.shape} "
            f"v={pool_v.dtype}{pool_v.shape}"
        )
    if kv_format is None:
        if pool_k.dtype == jnp.int8:
            kv_format = (
                "int4" if pool_k.shape[3] * 2 == head_dim else "int8"
            )
        else:
            kv_format = "f"
    if kv_format not in ("f", "int8", "int4"):
        raise ValueError(f"kv_format must be f|int8|int4, got {kv_format!r}")
    int4 = kv_format == "int4"
    quant = kv_format in ("int8", "int4")
    if quant and pool_k.dtype != jnp.int8:
        raise ValueError(
            f"{kv_format} pools must be int8 storage, got {pool_k.dtype}"
        )
    want_last = head_dim // 2 if int4 else head_dim
    if int4 and head_dim % 2:
        raise ValueError(f"int4 packing needs even head_dim, got {head_dim}")
    if pool_k.shape[3] != want_last:
        raise ValueError(
            f"pool head_dim {pool_k.shape[3]} != expected {want_last} for "
            f"kv_format={kv_format!r} (int4 pools pack two values per byte)"
        )
    if quant and (scale_k is None or scale_v is None):
        raise ValueError(
            f"{kv_format} pools require scale_k and scale_v scale pools"
        )
    if not quant and (scale_k is not None or scale_v is not None):
        raise ValueError(
            f"scale pools passed with {pool_k.dtype} (non-int8) pools"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    group = num_heads // kv_heads
    if sm_scale is None:
        sm_scale = head_dim ** -0.5
    on_tpu = jax.default_backend() == "tpu"
    if use_pallas is None:
        use_pallas = on_tpu or bool(interpret)
    if interpret is None:
        interpret = not on_tpu
    if num_splits is None:
        num_splits = tuning.pick_num_splits(pages_per_seq)
    num_splits = max(1, min(int(num_splits), pages_per_seq))

    q4 = q.reshape(batch, kv_heads, group, head_dim)
    if not use_pallas:
        out = _decode_xla(
            q4, pool_k, pool_v, page_table, lens, scale_k, scale_v,
            sm_scale=sm_scale, window=window, num_splits=num_splits,
            quant=quant, int4=int4,
        )
        return out.reshape(batch, num_heads, head_dim)

    group_pad = max(group, _MIN_GROUP_TILE)
    if group_pad != group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))
    out = _paged_pallas(
        q4, pool_k, pool_v, page_table, lens, scale_k, scale_v,
        sm_scale=sm_scale, window=window, num_splits=num_splits,
        quant=quant, int4=int4, interpret=interpret,
    )
    return out[:, :, :group, :].reshape(batch, num_heads, head_dim)
