"""Per-chip-generation kernel tuning tables for the decode kernels.

The split-K paged-attention kernel (ops/paged_attention.py) has one
load-bearing free parameter — how many grid programs share one
sequence's page list — and the right answer is a property of the CHIP
(how many sequential page fetches amortize one program's setup, how
much VMEM a partial-state triple costs), not of the model.  This module
owns that knowledge the same way ops/flash_attention.py owns its block
tables: small reviewed rows keyed by TPU generation, matched against
what the plugin actually discovered.

Grounding (the MT4G pattern, PAPERS.md): the serving container never
guesses its chip.  The plugin daemon discovers the accelerator type at
registration (plugin/discovery.py) and Allocate injects it as
``TPU_ACCELERATOR_TYPE`` alongside ``TPU_CHIPS_PER_HOST_BOUNDS``
(plugin/envs.py), so the engine's tuning lookup keys off the SAME
topology source the mesh derivation uses (parallel/mesh.py) — with
``jax.devices()[0].device_kind`` as the on-chip tie-breaker and an
interpret-mode-safe default row for CPU smoke.

Row schema (see docs/kernels.md "Tile-table schema" for how a hardware
round records a new row):

- ``generation``  — device_kind prefix the row matches (or "cpu");
- ``min_pages_per_split`` — never split below this many pages per
  program: each split re-pays the online-softmax state init and one
  combine term, so thin splits trade HBM streaming for overhead;
- ``max_splits`` — cap on the split axis (bounds the partial buffers
  and the combine's reduction width);
- ``source`` — provenance: which bench round measured it, or why the
  row is provisional.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Mapping, Optional


@dataclass(frozen=True)
class DecodeRow:
    """One generation's split-K decode tuning row."""

    generation: str
    min_pages_per_split: int
    max_splits: int
    source: str


# Keyed by device_kind prefix (the flash-attention table's convention).
# The TPU rows are PROVISIONAL: they inherit the grid-overhead shape of
# the round-2/3 flash block sweeps (v5e amortizes setup over large
# sequential spans; v4 prefers smaller working sets) and exist so a
# hardware round has a schema to fill in — `use_kernel` stays opt-in
# until one does (models/transformer.py PagedConfig).
DECODE_ROWS: tuple[DecodeRow, ...] = (
    DecodeRow("TPU v5 lite", 4, 8, "provisional: awaiting hw round"),
    DecodeRow("TPU v5e", 4, 8, "provisional: awaiting hw round"),
    DecodeRow("TPU v5p", 4, 8, "provisional: awaiting hw round"),
    DecodeRow("TPU v4", 4, 4, "provisional: smaller VMEM, fewer splits"),
    DecodeRow("TPU v6", 4, 8, "provisional: inherits v5e until swept"),
)

# CPU smoke / Pallas interpreter: splitting buys nothing (no DMA
# pipeline to parallelize) and every extra split is pure combine
# overhead, so the safe row is the degenerate 1-split — which is also
# what keeps the KERNELS ledger's CPU rows honest about the kernel's
# structure rather than its split bookkeeping.
CPU_ROW = DecodeRow("cpu", 1 << 30, 1, "interpret-mode-safe default")

# Unknown TPU generation: conservative splits so the kernel stays
# usable while the missing row is the visible gap (the engine meters it
# as a kernel.fallback, reason=untuned_generation).
FALLBACK_ROW = DecodeRow("unknown-tpu", 8, 2, "no row for this generation")

# TPU_ACCELERATOR_TYPE prefixes (plugin/discovery.py values like
# "v5litepod-8") -> the device_kind prefix the rows key on.
_ACCEL_TYPE_PREFIXES: tuple[tuple[str, str], ...] = (
    ("v5litepod", "TPU v5 lite"),
    ("v5e", "TPU v5e"),
    ("v5p", "TPU v5p"),
    ("v4", "TPU v4"),
    ("v6", "TPU v6"),
)


def device_generation(environ: Optional[Mapping[str, str]] = None) -> str:
    """The generation key tuning rows match against.

    Preference order: the live backend's device_kind (authoritative when
    jax actually sits on a TPU), then the plugin-injected
    ``TPU_ACCELERATOR_TYPE`` (the discovered-topology source — present
    in every Allocate-launched serving container even before jax
    initializes the chip), else "cpu".
    """
    env = os.environ if environ is None else environ
    try:
        import jax

        if jax.default_backend() == "tpu":
            return jax.devices()[0].device_kind
    except Exception:  # codelint: ignore[naked-except] best-effort probe: jax may be absent (plugin-only install) or refuse to initialize a backend here; the env/cpu fallback below is the answer either way
        pass
    accel = env.get("TPU_ACCELERATOR_TYPE", "")
    for prefix, kind in _ACCEL_TYPE_PREFIXES:
        if accel.startswith(prefix):
            return kind
    return "cpu"


def decode_row(generation: Optional[str] = None) -> tuple[DecodeRow, bool]:
    """The tuning row for ``generation`` (default: discovered) and
    whether it was an exact match (False = the conservative fallback —
    the engine's untuned-generation fallback signal)."""
    kind = device_generation() if generation is None else generation
    if kind == "cpu":
        return CPU_ROW, True
    for row in DECODE_ROWS:
        if kind.startswith(row.generation):
            return row, True
    return FALLBACK_ROW, False


def has_row(generation: Optional[str] = None) -> bool:
    """Whether a reviewed tuning row exists for this generation."""
    return decode_row(generation)[1]


def pick_num_splits(
    pages_per_seq: int, generation: Optional[str] = None
) -> int:
    """Split-K degree for a sequence of ``pages_per_seq`` table entries.

    Largest power-of-two split count that (a) stays within the row's
    ``max_splits`` and (b) leaves every split at least
    ``min_pages_per_split`` pages of real streaming work.  Degenerates
    to 1 for short contexts (the combine stage is skipped entirely
    there — ops/paged_attention.py) and on the CPU row.
    """
    if pages_per_seq < 1:
        raise ValueError(f"pages_per_seq must be >= 1, got {pages_per_seq}")
    row, _ = decode_row(generation)
    splits = 1
    while (
        splits * 2 <= row.max_splits
        and pages_per_seq // (splits * 2) >= row.min_pages_per_split
    ):
        splits *= 2
    return min(splits, pages_per_seq)
