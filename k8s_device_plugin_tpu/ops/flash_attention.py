"""Fused multi-head attention (flash attention) as a Pallas TPU kernel.

The reference delegates all compute to its workload image (SURVEY.md §2.4:
"GPU compute kernels — absent from the plugin; delegated to the workload");
our workload layer is first-party, so its hot op gets a first-party TPU
kernel.  Design follows the TPU flash-attention pattern (online softmax with
running max/denominator, one [block_q, block_kv] tile resident in VMEM at a
time), NOT a port of any CUDA kernel:

- grid = (batch*heads, q_blocks, kv_blocks); the kv axis is innermost, which
  TPU executes sequentially per (batch, q_block), so the running softmax
  state lives in VMEM scratch across kv iterations.
- tiles are MXU-shaped ([128, 128] blocks by default); both matmuls
  (q·kᵀ and p·v) accumulate in float32 via preferred_element_type while
  inputs stay bfloat16.
- with ``causal=True`` tiles entirely above the diagonal skip both matmuls
  (`pl.when` guard) — ~2x fewer MXU FLOPs at long sequence length.
- O(seq) memory: the [seq, seq] score matrix never exists in HBM, which is
  what lets long-context models fit (HBM capacity/bandwidth is the TPU
  bottleneck, not FLOPs).

Differentiation: the forward also emits per-row log-sum-exp, and the custom
VJP recomputes attention **one kv block at a time** (`lax.scan`) from the
saved q/k/v/out/lse — flash-style rematerialization, O(seq·block) peak
memory in backward too, no [seq, seq] residual ever stored.

On non-TPU backends the same kernel runs under the Pallas interpreter
(tests), or callers use :func:`mha_reference` directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")

# Per-generation (block_q, block_kv) defaults, matched by device_kind
# prefix, separately for forward and backward.  The forward kernel is
# grid-overhead-bound at small tiles on v5e — the round-2 idle-machine
# sweep (median-of-5, 50-iter chains, separate k/v buffers) measured
# q128/kv512 at 2.53 ms vs q512/kv1024 at 1.23 ms for b4 h16 s2048 d64 —
# so the fwd default rides the large end; VMEM stays modest (f32 scores
# tile 512x1024 = 2 MB + double-buffered kv tiles).  The backward kernels
# keep more operands live per tile (q, k, v, dO, O, lse + two f32
# accumulators), so their swept optimum is squarer: the round-3 bwd sweep
# (two-point, 10-iter chains) measured grad(flash) at q512/kv512 in
# 1.67 ms vs 2.78 ms at q256/kv512 for b4 h16 s2048 d64, and 1.49 ms vs
# 7.03 ms (single-point) at the old q128/kv512 for d=128 — q512/kv1024
# regressed (8.15 ms, VMEM pressure), so bwd stays at 512x512.
_BLOCK_DEFAULTS = (
    ("TPU v5 lite", (512, 1024)),
    ("TPU v5e", (512, 1024)),
    ("TPU v5p", (512, 1024)),
    ("TPU v4", (128, 256)),
    ("TPU v6", (512, 1024)),  # unswept: inherit v5e until a v6 sweep exists
)
_BWD_BLOCK_DEFAULTS = (
    ("TPU v5 lite", (512, 512)),
    ("TPU v5e", (512, 512)),
    ("TPU v5p", (512, 512)),
    ("TPU v4", (128, 256)),
    ("TPU v6", (512, 512)),  # unswept: inherit v5e until a v6 sweep exists
)
_FALLBACK_BLOCKS = (128, 256)  # unknown TPU generation
_INTERPRET_BLOCKS = (128, 128)  # CPU interpreter: smallest legal tiles


def _default_blocks(interpret: bool, table=_BLOCK_DEFAULTS) -> tuple[int, int]:
    if interpret or jax.default_backend() != "tpu":
        return _INTERPRET_BLOCKS
    kind = jax.devices()[0].device_kind
    for prefix, blocks in table:
        if kind.startswith(prefix):
            return blocks
    return _FALLBACK_BLOCKS


def _fit_block(block: int, seq: int) -> int:
    """Largest size <= block that divides ``seq`` (halving from block)."""
    b = min(block, seq)
    while b > 1 and seq % b:
        b //= 2
    return b


# Short sequences: the large per-generation forward defaults exist to
# amortize grid setup over LONG kv walks, but at seq <= _SHORT_SEQ the
# naive fit swallows the whole sequence into one or two tiles and
# starves the grid of parallel work — the r03–r05 smoke rows measured
# the (1, 2, 256, 64) forward at 1.38 ms vs XLA's 1.05 ms (0.76x)
# because q512 fitted to a single 256-row tile.  Capping the defaulted
# q block at 128 under the threshold restores >= 2 q-programs per
# (batch, head) and the MXU-native 128-row tile; the kv block keeps its
# fitted size (kv iterations are the sequential axis either way).
# Explicitly-passed blocks are never capped.
_SHORT_SEQ = 512
_SHORT_BLOCK_Q = 128


def _auto_block(default: int, seq: int, q_axis: bool = False) -> int:
    fitted = _fit_block(default, seq)
    if q_axis and seq <= _SHORT_SEQ and fitted > _SHORT_BLOCK_Q:
        # Re-fit from the cap, not min(): the capped block must still
        # divide the sequence (192 fits to 64, not an invalid 128).
        fitted = _fit_block(_SHORT_BLOCK_Q, seq)
    return fitted


def resolve_blocks(
    seq_q: int,
    seq_kv: int,
    block_q: int | None = None,
    block_kv: int | None = None,
    bwd_block_q: int | None = None,
    bwd_block_kv: int | None = None,
    interpret: bool = False,
    defaults: tuple[tuple[int, int], tuple[int, int]] | None = None,
) -> tuple[int, int, int, int]:
    """The one block-resolution rule :func:`flash_attention` applies:
    per-generation defaults fitted to the sequence (with the short-seq q
    cap above), explicit blocks clamped but never re-fitted.  Split out
    (and parameterized on ``defaults`` = ((fwd_q, fwd_kv), (bwd_q,
    bwd_kv))) so the chosen tiles are unit-testable off-TPU —
    tests/test_ops.py pins the short-sequence fix."""
    if defaults is None:
        defaults = (
            _default_blocks(interpret),
            _default_blocks(interpret, _BWD_BLOCK_DEFAULTS),
        )
    (default_q, default_kv), (bwd_default_q, bwd_default_kv) = defaults

    def resolve(explicit, default, seq, q_axis=False):
        if explicit is not None:
            return min(explicit, seq)
        return _auto_block(default, seq, q_axis=q_axis)

    return (
        resolve(block_q, default_q, seq_q, q_axis=True),
        resolve(block_kv, default_kv, seq_kv),
        resolve(bwd_block_q, bwd_default_q, seq_q),
        resolve(bwd_block_kv, bwd_default_kv, seq_kv),
    )


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Plain-XLA attention with identical semantics to the kernel.

    [batch, heads, seq, head_dim] in, same out; float32 softmax accumulation.
    The numerical oracle for tests and the non-fused fallback path.
    ``window`` (requires causal): each query attends to the ``window`` most
    recent positions, itself included — Mistral-style local attention.

    Grouped-query attention: k/v may carry ``kv_heads`` dividing q's heads;
    being the oracle (not the fast path), this simply expands kv heads.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if k.shape[1] != q.shape[1]:
        if q.shape[1] % k.shape[1]:
            raise ValueError(
                f"q heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}"
            )
        group = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            # window=0 would mask every score; softmax over all -inf is NaN.
            raise ValueError(f"window must be >= 1, got {window}")
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        mask = row >= col
        if window is not None:
            mask = jnp.logical_and(mask, row - col < window)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


# --------------------------------------------------------------------- kernel


def _tile_live(qi, ki, block_q: int, block_kv: int, window):
    """Whether a [block_q, block_kv] tile intersects the causal(+window)
    band: its smallest column must not exceed its largest row, and with a
    window its largest column must not fall entirely behind the smallest
    row's window.  Shared by the forward and both backward kernels."""
    live = (qi * block_q + block_q - 1) >= (ki * block_kv)
    if window is not None:
        live = jnp.logical_and(
            live,
            (ki * block_kv + block_kv - 1) >= (qi * block_q - (window - 1)),
        )
    return live


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    sm_scale: float,
    causal: bool,
    window,
    block_q: int,
    block_kv: int,
    num_kv_blocks: int,
):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    qi = pl.program_id(1)

    def _tile():
        # Inputs stay in their storage dtype (bfloat16 in production):
        # the MXU multiplies bf16 natively with float32 accumulation via
        # preferred_element_type — upcasting q/k/v first would demote both
        # matmuls to the much slower f32 MXU path.
        q = q_ref[0]  # [block_q, head_dim]
        k = k_ref[0]  # [block_kv, head_dim]
        v = v_ref[0]

        # Scores tile on the MXU, float32 accumulation.
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * sm_scale
        )  # [block_q, block_kv]

        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = row >= col
            if window is not None:
                mask = jnp.logical_and(mask, row - col < window)
            s = jnp.where(mask, s, NEG_INF)

        # Online softmax update.  m/l scratch is [block_q, 128]
        # (lane-replicated: TPU vector registers are 128 lanes wide, a
        # [block_q, 1] store would be sub-lane); only column 0 is read back.
        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows keep m_new == -inf; exp(-inf - -inf) would
        # be NaN, so substitute 0 under the mask (they contribute nothing).
        seen = m_new > NEG_INF
        p = jnp.where(seen, jnp.exp(s - jnp.where(seen, m_new, 0.0)), 0.0)
        alpha = jnp.where(seen, jnp.exp(jnp.where(seen, m_prev - m_new, 0.0)), 0.0)

        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        # p·v on the MXU in the inputs' dtype (bf16 weights path); the
        # f32 statistics (m/l/acc) keep the online softmax exact.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Dead tiles skip both matmuls (the grid still visits them —
        # Pallas grids are rectangular — but they cost only this check).
        pl.when(_tile_live(qi, ki, block_q, block_kv, window))(_tile)
    else:
        _tile()

    @pl.when(ki == num_kv_blocks - 1)
    def _finish():
        m = m_ref[...]  # [block_q, 128], lane-replicated
        l = l_ref[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked row -> zero output
        o_ref[0] = (acc_ref[...] / l_safe[:, :1]).astype(o_ref.dtype)
        # Per-row log-sum-exp, the backward pass's softmax residual.  Written
        # lane-replicated ([block_q, 128]) — a [block_q, 1] -> [1, block_q]
        # transpose would be a cross-lane shuffle; callers read lane 0.
        lse_ref[0] = jnp.where(l > 0.0, m + jnp.log(l_safe), NEG_INF)


def _flash_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    window,
    sm_scale: float,
    block_q: int,
    block_kv: int,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    """Returns (out [b,h,sq,d], lse_rep [b*h, sq, 128] float32).

    The returned log-sum-exp is the kernel's lane-replicated layout (every
    lane carries the row's value); the backward kernels read it directly
    as (1, block_q, 128) tiles, so no cross-lane reshape ever happens.

    GQA-native: k/v may have ``kv_heads`` dividing q's ``heads``.  The kv
    BlockSpec index map routes every q head to its group's kv head, so the
    kv tile is *shared* across the head group in VMEM — no repeated K/V is
    ever materialized in HBM and the kernel does kv_heads' worth of kv
    traffic, not heads' (the GQA bandwidth win the round-1 `jnp.repeat`
    path gave away, VERDICT r1 weak #4).
    """
    batch, heads, seq_q, head_dim = q.shape
    kv_heads, seq_kv = k.shape[1], k.shape[2]
    if heads % kv_heads:
        raise ValueError(f"q heads {heads} not a multiple of kv heads {kv_heads}")
    group = heads // kv_heads
    _check_blocks(seq_q, seq_kv, block_q, block_kv)
    bh = batch * heads
    q3 = q.reshape(bh, seq_q, head_dim)
    k3 = k.reshape(batch * kv_heads, seq_kv, head_dim)
    v3 = v.reshape(batch * kv_heads, seq_kv, head_dim)
    num_q_blocks = seq_q // block_q
    num_kv_blocks = seq_kv // block_kv

    def kv_index(b, qi, ki):
        # Flat q index b = batch_i * heads + head_i; its kv row is
        # batch_i * kv_heads + head_i // group.  Static ints, traced fine.
        return (b // heads) * kv_heads + (b % heads) // group, ki, 0

    kernel = functools.partial(
        _flash_kernel,
        sm_scale=sm_scale,
        causal=causal,
        window=window,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_kv, head_dim), kv_index),
            pl.BlockSpec((1, block_kv, head_dim), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
            # Lane-replicated lse (see kernel); lane 0 is sliced off below.
            jax.ShapeDtypeStruct((bh, seq_q, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denominator
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        # Mosaic grid semantics: bh and q blocks are independent (parallel);
        # the kv axis carries the online-softmax scratch between iterations
        # and must stay sequential (arbitrary).  Telling the compiler lets it
        # overlap/pipeline the parallel axes instead of serializing the grid.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(batch, heads, seq_q, head_dim), lse


# ------------------------------------------------------------------- backward


def _check_blocks(seq_q: int, seq_kv: int, block_q: int, block_kv: int) -> None:
    if seq_q % block_q or seq_kv % block_kv:
        raise ValueError(
            f"seq lengths ({seq_q}, {seq_kv}) must divide by blocks "
            f"({block_q}, {block_kv}); pad to MXU multiples first"
        )


def _bwd_p_tile(q, k, lse_col, rows, cols, sm_scale, causal, window):
    """Recompute the probability tile P = exp(S·scale − lse) with masking.

    Shared by both backward kernels.  ``lse_col`` is [block_q, 1] float32;
    rows/cols are absolute index iotas for the tile.  Returns p
    ([block_q, block_kv] float32).
    """
    s = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * sm_scale
    )
    # Rows that attended to nothing carry lse == -inf; exp(s - -inf) would
    # be +inf, so force their P to 0 via the finite mask.
    finite = lse_col > NEG_INF
    p = jnp.where(finite, jnp.exp(s - jnp.where(finite, lse_col, 0.0)), 0.0)
    if causal:
        mask = rows >= cols
        if window is not None:
            mask = jnp.logical_and(mask, rows - cols < window)
        p = jnp.where(mask, p, 0.0)
    return p


def _dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    o_ref,
    lse_ref,
    dq_ref,
    dq_acc,
    *,
    sm_scale: float,
    causal: bool,
    window,
    block_q: int,
    block_kv: int,
    num_kv_blocks: int,
):
    """dQ: grid (b*h, q_blocks, kv_blocks), kv innermost sequential.

    Flash-style recomputation: P is rebuilt one kv tile at a time from the
    saved lse (never [seq, seq]); dQ accumulates in a float32 VMEM scratch
    across the kv axis and is written once on the last kv block.
    """
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros(dq_acc.shape, dq_acc.dtype)

    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0
        )
        cols = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1
        )
        lse_col = lse_ref[0][:, :1]
        p = _bwd_p_tile(q, k, lse_col, rows, cols, sm_scale, causal, window)
        # delta_i = Σ_d dO·O per row — cheap enough to recompute per tile
        # (block_q·d mul-adds vs the block_q·block_kv·d matmuls around it).
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1,
            keepdims=True,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_tile_live(qi, ki, block_q, block_kv, window))(_tile)
    else:
        _tile()

    @pl.when(ki == num_kv_blocks - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    o_ref,
    lse_ref,
    dk_ref,
    dv_ref,
    dk_acc,
    dv_acc,
    *,
    sm_scale: float,
    causal: bool,
    window,
    block_q: int,
    block_kv: int,
    num_q_blocks: int,
    group: int,
):
    """dK/dV: grid (b*kv_heads, kv_blocks, group*q_blocks), innermost
    sequential over the whole (q-head-in-group × q-block) range.

    GQA-native like the forward: one kv tile stays resident while every q
    head of its group streams past, so the shared kv head's gradient sums
    the whole group without any repeated K/V in HBM.
    """
    ki, t = pl.program_id(1), pl.program_id(2)
    qi = t % num_q_blocks

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros(dk_acc.shape, dk_acc.dtype)
        dv_acc[...] = jnp.zeros(dv_acc.shape, dv_acc.dtype)

    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0
        )
        cols = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1
        )
        lse_col = lse_ref[0][:, :1]
        p = _bwd_p_tile(q, k, lse_col, rows, cols, sm_scale, causal, window)
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1,
            keepdims=True,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dK += dSᵀ·Q, dV += Pᵀ·dO — contract the q-row axis (dim 0 of both).
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_tile_live(qi, ki, block_q, block_kv, window))(_tile)
    else:
        _tile()

    @pl.when(t == group * num_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(
    q, k, v, out, lse_rep, dout, causal, window, sm_scale, block_q, block_kv, interpret
):
    """Fused flash backward: two Pallas kernels (dQ; dK/dV), both O(seq)
    memory, both GQA-native.  lse_rep is the forward's lane-replicated
    [b*h, seq_q, 128] residual — consumed tile-wise, no reshapes."""
    batch, heads, seq_q, head_dim = q.shape
    kv_heads, seq_kv = k.shape[1], k.shape[2]
    group = heads // kv_heads
    _check_blocks(seq_q, seq_kv, block_q, block_kv)
    bh = batch * heads
    q3 = q.reshape(bh, seq_q, head_dim)
    do3 = dout.reshape(bh, seq_q, head_dim)
    o3 = out.reshape(bh, seq_q, head_dim)
    k3 = k.reshape(batch * kv_heads, seq_kv, head_dim)
    v3 = v.reshape(batch * kv_heads, seq_kv, head_dim)
    num_q_blocks = seq_q // block_q
    num_kv_blocks = seq_kv // block_kv

    def kv_index(b, qi, ki):
        return (b // heads) * kv_heads + (b % heads) // group, ki, 0

    q_spec = pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0))
    kv_spec = pl.BlockSpec((1, block_kv, head_dim), kv_index)
    lse_spec = pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            sm_scale=sm_scale,
            causal=causal,
            window=window,
            block_q=block_q,
            block_kv=block_kv,
            num_kv_blocks=num_kv_blocks,
        ),
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, o3, lse_rep)

    # dK/dV grid walks (kv head, kv block, every group member × q block);
    # index maps route each t to its q row within the group.
    def q_row(b2, ki, t):
        g = t // num_q_blocks
        return (b2 // kv_heads) * heads + (b2 % kv_heads) * group + g

    def q_index(b2, ki, t):
        return q_row(b2, ki, t), t % num_q_blocks, 0

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            sm_scale=sm_scale,
            causal=causal,
            window=window,
            block_q=block_q,
            block_kv=block_kv,
            num_q_blocks=num_q_blocks,
            group=group,
        ),
        grid=(batch * kv_heads, num_kv_blocks, group * num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), q_index),
            pl.BlockSpec((1, block_kv, head_dim), lambda b2, ki, t: (b2, ki, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b2, ki, t: (b2, ki, 0)),
            pl.BlockSpec((1, block_q, head_dim), q_index),
            pl.BlockSpec((1, block_q, head_dim), q_index),
            pl.BlockSpec((1, block_q, 128), q_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, head_dim), lambda b2, ki, t: (b2, ki, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b2, ki, t: (b2, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * kv_heads, seq_kv, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch * kv_heads, seq_kv, head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, o3, lse_rep)

    return (
        dq.reshape(q.shape),
        dk.reshape(k.shape),
        dv.reshape(v.shape),
    )


def _mha_bwd_chunked(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    causal: bool,
    window,
    sm_scale: float,
    block_kv: int,
):
    """Flash-style backward: recompute P one kv block at a time from the
    saved lse, never materializing [seq, seq].

    Standard decomposition (same math every flash backward uses):
        Pᵢⱼ = exp(Sᵢⱼ·scale − lseᵢ)
        Dᵢ  = Σⱼ dOᵢⱼ·Oᵢⱼ            (row dot, O(seq·d))
        dPᵢⱼ = dO·Vᵀ ;  dSᵢⱼ = Pᵢⱼ·(dPᵢⱼ − Dᵢ)·scale
        dQ = ΣⱼdS·K ;  dK = dSᵀ·Q ;  dV = Pᵀ·dO
    Each kv block contributes independently, so a `lax.scan` over kv blocks
    accumulates dQ and emits the block's dK/dV — peak extra memory is one
    [seq_q, block_kv] tile per (batch, head), i.e. O(seq), matching forward.

    GQA: q (and out/dout/lse) carry ``heads = kv_heads * group``; all
    row-indexed tensors are reshaped to an explicit [b, kv_heads, group, …]
    layout so each einsum contracts q's group axis against the *shared* kv
    head — dK/dV sum a whole head group's contribution in one matmul and
    no repeated K/V exists.
    """
    f32 = jnp.float32
    batch, heads, seq_q, head_dim = q.shape
    kv_heads, seq_kv = k.shape[1], k.shape[2]
    group = heads // kv_heads
    g5 = (batch, kv_heads, group, seq_q, head_dim)
    g4 = (batch, kv_heads, group, seq_q)
    qf = q.astype(f32).reshape(g5)
    dof = dout.astype(f32).reshape(g5)
    of = out.astype(f32).reshape(g5)
    kf, vf = k.astype(f32), v.astype(f32)
    num_blocks = seq_kv // block_kv

    d_row = jnp.sum(dof * of, axis=-1)  # [b,hk,g,sq]
    # Rows that attend to nothing have lse == -inf; exp(s - -inf) would blow
    # up, so clamp (their P is forced to 0 below anyway via the finite mask).
    lse = lse.reshape(g4)
    finite = jnp.isfinite(lse)
    lse_safe = jnp.where(finite, lse, 0.0)

    # With a sliding window only rows [start, start + block_kv - 1 + window)
    # can touch kv block [start, start + block_kv) — slice just that query
    # band (static length) so backward FLOPs scale O(seq·window) like the
    # forward's tile skipping, instead of masking a dense [seq_q, block_kv].
    banded = (
        causal
        and window is not None
        and seq_q == seq_kv  # band geometry assumes aligned self-attention
        and block_kv + window - 1 < seq_q
    )
    q_rows = min(seq_q, block_kv + window - 1) if banded else seq_q

    row_ids = jax.lax.broadcasted_iota(jnp.int32, (q_rows, block_kv), 0)

    def one_block(dq_acc, block_idx):
        start = block_idx * block_kv
        k_blk = jax.lax.dynamic_slice_in_dim(kf, start, block_kv, axis=2)
        v_blk = jax.lax.dynamic_slice_in_dim(vf, start, block_kv, axis=2)
        if banded:
            # Clamped band start: rows [row0, row0 + q_rows) cover every
            # in-band row for this kv block.  Sequence is axis 3 in the
            # grouped [b, kv_heads, group, seq, ...] layout.
            row0 = jnp.minimum(start, seq_q - q_rows)
            q_b = jax.lax.dynamic_slice_in_dim(qf, row0, q_rows, axis=3)
            do_b = jax.lax.dynamic_slice_in_dim(dof, row0, q_rows, axis=3)
            dr_b = jax.lax.dynamic_slice_in_dim(d_row, row0, q_rows, axis=3)
            lse_b = jax.lax.dynamic_slice_in_dim(lse_safe, row0, q_rows, axis=3)
            fin_b = jax.lax.dynamic_slice_in_dim(finite, row0, q_rows, axis=3)
            rows_abs = row0 + row_ids
        else:
            row0 = 0
            q_b, do_b, dr_b, lse_b, fin_b = qf, dof, d_row, lse_safe, finite
            rows_abs = row_ids
        # h = kv head, g = q-head group member: kv tensors have no g axis,
        # so XLA broadcasts one kv tile across the group (GQA-native).
        s = jnp.einsum("bhgqd,bhkd->bhgqk", q_b, k_blk) * sm_scale
        p = jnp.exp(s - lse_b[..., None])
        p = jnp.where(fin_b[..., None], p, 0.0)
        if causal:
            col_ids = start + jax.lax.broadcasted_iota(
                jnp.int32, (q_rows, block_kv), 1
            )
            mask = rows_abs >= col_ids
            if window is not None:
                mask = jnp.logical_and(mask, rows_abs - col_ids < window)
            p = jnp.where(mask, p, 0.0)
        dp = jnp.einsum("bhgqd,bhkd->bhgqk", do_b, v_blk)
        ds = p * (dp - dr_b[..., None]) * sm_scale
        dq_contrib = jnp.einsum("bhgqk,bhkd->bhgqd", ds, k_blk)
        if banded:
            cur = jax.lax.dynamic_slice_in_dim(dq_acc, row0, q_rows, axis=3)
            dq_acc = jax.lax.dynamic_update_slice_in_dim(
                dq_acc, cur + dq_contrib, row0, axis=3
            )
        else:
            dq_acc = dq_acc + dq_contrib
        # dK/dV contract the group axis too: the shared kv head's gradient
        # sums every q head in its group in one matmul.
        dk_blk = jnp.einsum("bhgqk,bhgqd->bhkd", ds, q_b)
        dv_blk = jnp.einsum("bhgqk,bhgqd->bhkd", p, do_b)
        return dq_acc, (dk_blk, dv_blk)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        one_block, jnp.zeros_like(qf), jnp.arange(num_blocks)
    )
    # scan stacks along axis 0: [nblocks, b, hk, block_kv, d] -> [b, hk, skv, d]
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(v.shape)
    return dq.reshape(q.shape).astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash(
    q, k, v, causal, window, sm_scale, block_q, block_kv,
    bwd_block_q, bwd_block_kv, interpret, bwd_impl,
):
    out, _ = _flash_impl(
        q, k, v, causal, window, sm_scale, block_q, block_kv, interpret
    )
    return out


def _flash_fwd(
    q, k, v, causal, window, sm_scale, block_q, block_kv,
    bwd_block_q, bwd_block_kv, interpret, bwd_impl,
):
    out, lse_rep = _flash_impl(
        q, k, v, causal, window, sm_scale, block_q, block_kv, interpret
    )
    if bwd_impl != "pallas":
        # The XLA backward only reads one lane — slice the residual down to
        # [b, h, seq] here rather than holding the 128x lane-replicated
        # buffer live between forward and backward for every layer.
        batch, heads, seq_q = q.shape[0], q.shape[1], q.shape[2]
        return out, (q, k, v, out, lse_rep[:, :, 0].reshape(batch, heads, seq_q))
    return out, (q, k, v, out, lse_rep)


def _flash_bwd(
    causal, window, sm_scale, block_q, block_kv, bwd_block_q, bwd_block_kv,
    interpret, bwd_impl, residuals, dout,
):
    q, k, v, out, lse = residuals
    if bwd_impl == "pallas":
        # lse is the lane-replicated [b*h, seq, 128] layout (see _flash_fwd).
        return _flash_bwd_pallas(
            q, k, v, out, lse, dout,
            causal, window, sm_scale, bwd_block_q, bwd_block_kv, interpret,
        )
    return _mha_bwd_chunked(
        q, k, v, out, lse, dout, causal, window, sm_scale, bwd_block_kv
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    window: int | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    bwd_block_q: int | None = None,
    bwd_block_kv: int | None = None,
    interpret: bool | None = None,
    bwd_impl: str = "auto",
) -> jax.Array:
    """Fused attention over [batch, heads, seq, head_dim] inputs.

    Grouped-query attention is native: pass k/v with ``kv_heads`` dividing
    q's ``heads`` and each q-head group reads its shared kv tile directly —
    kv HBM traffic scales with kv_heads, not heads, in forward AND backward.

    ``interpret`` defaults to running the compiled kernel on TPU and the
    Pallas interpreter elsewhere (so the same code path is testable on the
    8-device CPU mesh).  ``block_q``/``block_kv`` tile the FORWARD kernel
    and ``bwd_block_q``/``bwd_block_kv`` the backward kernels; each
    defaults per TPU generation (``_BLOCK_DEFAULTS`` /
    ``_BWD_BLOCK_DEFAULTS``, keyed on device_kind; 128/128 under the
    interpreter) and clamps to the sequence length for short sequences.
    The passes tile independently because their VMEM working sets differ
    (backward keeps q, k, v, dO, O, lse and two f32 accumulators live per
    tile) — a forward-fast shape like 512x2048 is not automatically safe
    or fast for backward.

    ``window`` (requires ``causal``): sliding-window local attention — each
    query sees only its ``window`` most recent positions.  Forward tiles
    entirely outside the band skip both matmuls, and the chunked backward
    restricts each kv block to its query band, so both passes scale
    O(seq·window) instead of O(seq²) once seq >> window.

    ``bwd_impl``: "pallas" — fused flash backward kernels (dQ; dK/dV),
    "xla" — the chunked `lax.scan` backward, "auto" (default) — pallas on
    TPU, xla elsewhere (the interpreter is too slow for the bwd grids in
    routine test runs; dedicated parity tests exercise the pallas path).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if bwd_impl == "auto":
        bwd_impl = "xla" if interpret else "pallas"
    if bwd_impl not in ("pallas", "xla"):
        raise ValueError(f"bwd_impl must be auto|pallas|xla, got {bwd_impl!r}")
    # Defaulted blocks FIT the sequence (halve until they divide it) so a
    # generation default of 512 never rejects a seq that 128 accepted —
    # and short sequences additionally cap the forward q block so the
    # grid keeps parallel work (resolve_blocks; the r03–r05 short-seq
    # regression).  Explicitly-passed blocks keep the strict
    # divide-or-raise contract.
    fwd_q, fwd_kv, bwd_q, bwd_kv = resolve_blocks(
        q.shape[2], k.shape[2], block_q, block_kv,
        bwd_block_q, bwd_block_kv, interpret,
    )
    return _flash(
        q, k, v, causal, window, sm_scale, fwd_q, fwd_kv, bwd_q, bwd_kv,
        interpret, bwd_impl,
    )
