"""Fused LM-head + softmax cross-entropy without materializing the logits.

The standard decoder-LM training tail — ``logits = hidden @ W`` then
``softmax_xent(logits, labels)`` — materializes a float32
``[batch*seq, vocab]`` tensor.  At the benchmark config (b=8, s=1024,
V=32000) that is ~1 GiB of HBM for a single intermediate that the loss
immediately reduces away, and it is the peak-memory site of LM training
once activations are rematerialized.

This op computes the identical loss with an online log-sum-exp over vocab
CHUNKS (the flash-attention trick applied to the classifier axis): each
``[N, chunk]`` logits tile exists only transiently inside a ``lax.scan``
step, peak extra memory is ``N * chunk`` instead of ``N * V``, and the
matmuls still hit the MXU at full tile sizes.  The custom VJP recomputes
each chunk's softmax from the saved log-sum-exp — same recompute-vs-store
trade as the flash backward (ops/flash_attention.py) — and accumulates

    dH = (P - onehot) @ Wᵀ        chunk-by-chunk
    dW = Hᵀ @ (P - onehot)        chunk-by-chunk

so no full-vocab probability tensor exists in the backward either.

No reference analogue (the reference ships no model code, SURVEY.md §2.4);
this is the TPU-first expression of the LM training tail.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def naive_linear_xent(
    hidden: jax.Array, w: jax.Array, labels: jax.Array
) -> jax.Array:
    """The oracle: materialize logits, mean token cross-entropy."""
    logits = (hidden @ w).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    correct = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - correct)


def _col_valid(ci, chunk, vocab):
    """[1, chunk] bool: which columns of chunk ``ci`` are real vocab
    entries (the last chunk of a padded W carries dead columns)."""
    cols = ci * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
    return cols < vocab


def _forward_stats(hidden, w_pad, labels, chunk, vocab):
    """Online (max, sumexp, correct-logit) over vocab chunks.

    Returns (lse [N] f32, correct [N] f32): everything the loss and the
    backward need — the [N, V] logits never exist.  ``w_pad`` is padded to
    a chunk multiple; padded columns are masked to -inf.
    """
    n = hidden.shape[0]
    n_chunks = w_pad.shape[1] // chunk
    init = (
        jnp.full((n,), NEG_INF, jnp.float32),  # running max
        jnp.zeros((n,), jnp.float32),  # running sum of exp
        jnp.zeros((n,), jnp.float32),  # correct-class logit
    )

    def step(carry, ci):
        m, l, correct = carry
        w_c = jax.lax.dynamic_slice_in_dim(w_pad, ci * chunk, chunk, axis=1)
        logits = jnp.dot(
            hidden, w_c, preferred_element_type=jnp.float32
        )  # [N, chunk]
        logits = jnp.where(_col_valid(ci, chunk, vocab), logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # Collect the label's logit when it falls inside this chunk.
        local = labels - ci * chunk
        in_chunk = jnp.logical_and(local >= 0, local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1
        )[:, 0]
        correct = jnp.where(in_chunk, picked, correct)
        return (m_new, l, correct), None

    (m, l, correct), _ = jax.lax.scan(step, init, jnp.arange(n_chunks))
    return m + jnp.log(l), correct


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_core(hidden, w_pad, labels, chunk, vocab):
    lse, correct = _forward_stats(hidden, w_pad, labels, chunk, vocab)
    return jnp.mean(lse - correct)


def _fused_fwd(hidden, w_pad, labels, chunk, vocab):
    lse, correct = _forward_stats(hidden, w_pad, labels, chunk, vocab)
    return jnp.mean(lse - correct), (hidden, w_pad, labels, lse)


def _fused_bwd(chunk, vocab, residuals, g):
    hidden, w_pad, labels, lse = residuals
    n = hidden.shape[0]
    n_chunks = w_pad.shape[1] // chunk
    scale = g / n  # d(mean)/d(per-token) with the incoming cotangent

    def step(carry, ci):
        dh = carry
        w_c = jax.lax.dynamic_slice_in_dim(w_pad, ci * chunk, chunk, axis=1)
        logits = jnp.dot(hidden, w_c, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])  # softmax chunk, recomputed
        p = jnp.where(_col_valid(ci, chunk, vocab), p, 0.0)
        local = labels - ci * chunk
        in_chunk = jnp.logical_and(local >= 0, local < chunk)
        onehot = jnp.where(
            in_chunk[:, None],
            jax.nn.one_hot(jnp.clip(local, 0, chunk - 1), chunk, dtype=p.dtype),
            0.0,
        )
        delta = (p - onehot) * scale  # [N, chunk] f32
        dh = dh + jnp.dot(
            delta.astype(w_c.dtype), w_c.T, preferred_element_type=jnp.float32
        )
        dw_c = jnp.dot(
            hidden.T, delta.astype(hidden.dtype), preferred_element_type=jnp.float32
        )
        return dh, dw_c.astype(w_pad.dtype)

    dh, dw_chunks = jax.lax.scan(
        step, jnp.zeros(hidden.shape, jnp.float32), jnp.arange(n_chunks)
    )
    # scan stacks [n_chunks, d, chunk] -> [d, V_pad]
    dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(w_pad.shape)
    return dh.astype(hidden.dtype), dw, None


_fused_core.defvjp(_fused_fwd, _fused_bwd)


def fused_linear_xent(hidden, w, labels, chunk: int = 4096):
    """Mean token cross-entropy of ``hidden @ w`` against ``labels``.

    hidden: [N, d] (flatten batch×seq first), w: [d, V], labels: [N] int.
    ``chunk`` needs no relation to V: W is padded to a chunk multiple and
    the ragged tail is masked in both passes (gradients for pad columns
    are exactly zero and sliced away by autodiff through the pad), so an
    awkward vocab like 50257 still runs at full tile sizes.  Peak extra
    memory is N×chunk logits.  Differentiable in ``hidden`` and ``w``.
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    vocab = w.shape[1]
    chunk = min(chunk, vocab)
    pad = (-vocab) % chunk
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return _fused_core(hidden, w, labels, chunk, vocab)
