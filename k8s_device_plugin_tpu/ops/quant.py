"""Post-training int8 quantization for the decode/serving path.

The reference repo has no quantization story (it ships no model code at all
— reference main.go's job ends at handing device nodes to the workload,
SURVEY.md §2.4); this module exists because on TPU v5e the int8 MXU runs at
2x the bf16 rate and decode is HBM-bandwidth-bound, so int8 weights are the
canonical single-chip serving lever: half the weight bytes per step, and
optionally int8 x int8 -> int32 matmuls on the MXU.

TPU-first choices:

- Symmetric per-output-channel scales only (no zero points): the MXU
  consumes plain int8 operands and XLA fuses the per-channel rescale into
  the matmul epilogue; asymmetric zero-point correction terms would add a
  second reduction per tile for ~no accuracy gain at 8 bits.
- Two compute modes.  ``w8``: int8 weights dequantized on the fly
  (bf16 compute — XLA fuses convert-and-scale into the dot's operand read,
  so the bf16 weight tensor never lands in HBM); decode reads half the
  weight bytes.  ``w8a8``: activations are dynamically quantized per row
  (one amax per token) and the matmul runs int8 x int8 -> int32 on the
  MXU — the throughput mode for prefill/large-batch serving.
- Everything is plain XLA (`lax.dot_general` with
  ``preferred_element_type=int32``): int8 matmul is MXU-native, there is
  nothing for a hand kernel to add.

Flow: train/load bf16 params -> :func:`quantize_lm_params` (one-time tree
transform) -> run the SAME model code with ``GPTConfig(quant="w8")`` — the
transformer's dense sites (models/transformer.py) swap to
:class:`Int8DenseGeneral`, whose parameter names/shapes match what
``quantize_lm_params`` emits, so checkpoints stay portable.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

# int8 symmetric range: [-127, 127] (not -128: symmetric range keeps
# q = round(w/s) invertible without per-sign handling and costs 0.4% range).
_QMAX = 127.0
# int4 symmetric range: [-7, 7] — same invertibility argument one octave
# down; codes pack two per int8 byte (pack_int4) for the paged decode
# kernel's quarter-traffic KV variant (ops/paged_attention.py).
_QMAX4 = 7.0


def _sym_quantize(
    x: jax.Array, axes: tuple[int, ...], qmax: float = _QMAX
) -> tuple[jax.Array, jax.Array]:
    """The one symmetric core every quantized path shares: amax over
    ``axes`` per remaining coordinate, zero-amax guarded to scale 1,
    round-and-clip to [-qmax, qmax].  Returns (q int8 [x.shape], scale
    float32 [x.shape minus axes])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(
        jnp.round(xf / jnp.expand_dims(scale, axes)), -qmax, qmax
    ).astype(jnp.int8)
    return q, scale


def quantize_int8(w: jax.Array, contract_ndim: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a kernel.

    ``w``: [*contract_dims, *feature_dims] (flax DenseGeneral kernel
    layout); the first ``contract_ndim`` axes are reduced for the scale, so
    every output channel (remaining axes) gets its own scale.

    Returns ``(q int8 [w.shape], scale float32 [feature_dims])`` with
    ``q * scale ~= w``.
    """
    return _sym_quantize(w, tuple(range(contract_ndim)))


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_int8` (scale broadcasts over the leading
    contraction axes)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-token, per-head int8 quantization of a K or V slab.

    ``x``: [batch, tokens, kv_heads, head_dim].  Each (token, head) row gets
    its own scale over head_dim — the finest granularity that adds no
    matmul-side work (the scale rides the token axis, which is never
    contracted against weights).  Returns (int8 [x.shape], float32
    [batch, tokens, kv_heads]).
    """
    return _sym_quantize(x, (-1,))


def quantize_kv_pair(
    k: jax.Array, v: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Quantize a K/V pair in ONE fused pass: the pair stacks on a fresh
    leading axis so the amax/scale/round-clip machinery traces once
    instead of twice per append (per-element math — and therefore every
    code and scale byte — is bit-identical to two :func:`quantize_kv`
    calls, pinned in tests/test_quant.py).  Returns
    ``(k_q, v_q, k_scale, v_scale)``."""
    q, scale = _sym_quantize(jnp.stack([k, v]), (-1,))
    return q[0], q[1], scale[0], scale[1]


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_kv`; int8 stays the HBM format — the
    convert-and-scale fuses into the attention einsum's operand read, so
    decode reads half the cache bytes."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def pack_int4(codes: jax.Array) -> jax.Array:
    """Pack int4 codes (int8 storage, values in [-7, 7]) two-per-byte
    along the last axis: element 2i lands in the LOW nibble, 2i+1 in the
    high — the layout ops/paged_attention.py's in-kernel unpack
    (sign-extending shifts) inverts.  Last dim must be even."""
    if codes.shape[-1] % 2:
        raise ValueError(
            f"int4 packing needs an even last dim, got {codes.shape[-1]}"
        )
    pairs = codes.reshape(*codes.shape[:-1], codes.shape[-1] // 2, 2)
    lo = pairs[..., 0].astype(jnp.int32) & 0xF
    hi = pairs[..., 1].astype(jnp.int32) & 0xF
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_int4(packed: jax.Array, dtype: Any = jnp.int8) -> jax.Array:
    """Inverse of :func:`pack_int4` (host-side convenience; the kernels
    carry their own in-VMEM copy of the same shift math)."""
    x = packed.astype(jnp.int32)
    lo = jnp.right_shift(jnp.left_shift(x, 28), 28)
    hi = jnp.right_shift(jnp.left_shift(x, 24), 28)
    both = jnp.stack([lo, hi], axis=-1)
    return both.reshape(*packed.shape[:-1], packed.shape[-1] * 2).astype(dtype)


def quantize_kv4(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-token, per-head int4 quantization of a K or V slab, packed
    two-codes-per-byte along head_dim — a QUARTER of the bf16 KV bytes.

    ``x``: [..., head_dim] with head_dim even.  Same per-(token, head)
    scale granularity as :func:`quantize_kv` (the scale still factors
    out of the head_dim dot, so the paged kernel applies it on the score
    matrix).  Returns (packed int8 [..., head_dim//2], float32 scales
    [x.shape minus the last axis]).
    """
    codes, scale = _sym_quantize(x, (-1,), qmax=_QMAX4)
    return pack_int4(codes), scale


def dequantize_kv4(packed: jax.Array, scale: jax.Array, dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_kv4` — the gather-path analogue the
    int4 parity tests oracle against."""
    return (
        unpack_int4(packed, jnp.float32) * scale[..., None]
    ).astype(dtype)


def _normalize_axis(axis: Union[int, Sequence[int]], ndim: int) -> tuple[int, ...]:
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return tuple(a % ndim for a in axes)


def dense_geometry(
    x: jax.Array, axis: Union[int, Sequence[int]], features: Union[int, Sequence[int]]
) -> tuple[tuple[int, ...], tuple[int, ...], tuple[int, ...], Any]:
    """The one contraction convention every dense-site module shares
    (Int8DenseGeneral here, LoRADense in models/lora.py): returns
    ``(feats, axes, contract, dims)`` — feature dims as a tuple, normalized
    input contraction axes, their sizes, and the `dot_general` dimension
    numbers for a [*contract, *feats] kernel."""
    feats = (features,) if isinstance(features, int) else tuple(features)
    axes = _normalize_axis(axis, x.ndim)
    contract = tuple(x.shape[a] for a in axes)
    dims = ((axes, tuple(range(len(axes)))), ((), ()))
    return feats, axes, contract, dims


def int8_dot_general(
    x: jax.Array,
    w_q: jax.Array,
    w_scale: jax.Array,
    *,
    axis: Union[int, Sequence[int]] = -1,
    mode: str = "w8",
    dtype: Any = jnp.bfloat16,
) -> jax.Array:
    """Contract ``x``'s ``axis`` dims against the leading dims of ``w_q``.

    ``mode="w8"``: bf16 compute on dequantized-in-registers weights (the
    bandwidth mode).  ``mode="w8a8"``: per-row dynamic activation
    quantization, int8 x int8 -> int32 MXU matmul, rescale by
    (row scale x channel scale) in the epilogue (the throughput mode).
    """
    axes = _normalize_axis(axis, x.ndim)
    n_contract = len(axes)
    dims = ((axes, tuple(range(n_contract))), ((), ()))
    if mode == "w8":
        w = dequantize_int8(w_q, w_scale, dtype)
        return jax.lax.dot_general(x.astype(dtype), w, dims)
    if mode != "w8a8":
        raise ValueError(f"mode must be w8|w8a8, got {mode!r}")
    x_q, x_scale = _sym_quantize(x, axes)  # per-row dynamic activation quant
    acc = jax.lax.dot_general(
        x_q, w_q, dims, preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    # x_scale keeps only the batch axes; broadcast it over the out channels.
    out_batch_ndim = x.ndim - n_contract
    out = acc * x_scale.reshape(
        x_scale.shape + (1,) * (acc.ndim - out_batch_ndim)
    ) * w_scale
    return out.astype(dtype)


class Int8DenseGeneral(nn.Module):
    """Drop-in for ``nn.Dense``/``nn.DenseGeneral`` over int8 kernels.

    Parameter layout matches flax's: ``kernel_q`` is
    [*contracted_input_dims, *features] int8 and ``kernel_scale`` is
    [*features] float32 — exactly what :func:`quantize_lm_params` produces
    from the corresponding bf16 ``kernel``, so a quantized tree applies to
    the same module names.

    Init gives zero weights (an untrained quantized model is meaningless;
    the module exists to CONSUME post-training-quantized params — round-trip
    through :func:`quantize_lm_params`).
    """

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    mode: str = "w8"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        feats, _, contract, _ = dense_geometry(x, self.axis, self.features)
        w_q = self.param(
            "kernel_q", nn.initializers.zeros, contract + feats, jnp.int8
        )
        w_scale = self.param("kernel_scale", nn.initializers.ones, feats, jnp.float32)
        return int8_dot_general(
            x, w_q, w_scale, axis=self.axis, mode=self.mode, dtype=self.dtype
        )


def quantize_lm_params(params: Any) -> Any:
    """One-time tree transform: every dense ``kernel`` leaf becomes
    ``kernel_q`` (int8) + ``kernel_scale`` (float32 per output channel).

    Matmul-bearing kernels are recognized structurally: a dict holding a
    ``kernel`` array (flax Dense/DenseGeneral).  Contraction dims are
    inferred from the known transformer sites — every kernel is
    [in..., out...] with ONE output group except attention's ``out``
    projection, whose kernel is [heads, head_dim, hidden] (two contracted
    leading dims).  Embeddings (``embedding``) and norm scales pass through
    untouched: embeds are a gather (no matmul win) and norms are
    precision-critical.
    """

    def convert(name, tree):
        if not isinstance(tree, dict):
            return tree
        if "kernel" in tree and hasattr(tree["kernel"], "ndim"):
            w = tree["kernel"]
            # Contraction dims are inferred by site name, which is only
            # sound for the sites this transform knows.  2-D kernels are
            # unambiguous ([in, out], contract 1).  For 3-D+ the layout is
            # name-dependent — attention's out-projection (DenseGeneral
            # axis=(-2,-1)) is [heads, head_dim, hidden] with TWO
            # contracted leading dims, qkv DenseGeneral is
            # [hidden, heads, head_dim] with one — so any OTHER 3-D+
            # kernel (a future MoE expert kernel [experts, in, out], a
            # renamed projection) must fail loudly here rather than get
            # per-channel scales computed over the wrong axes and a
            # silently wrong quantized tree.
            if w.ndim <= 2:
                contract_ndim = 1
            elif name == "out" and w.ndim == 3:
                contract_ndim = 2
            elif name in ("query", "key", "value") and w.ndim == 3:
                contract_ndim = 1
            else:
                raise ValueError(
                    f"quantize_lm_params: unknown {w.ndim}-D kernel site "
                    f"{name!r} — contraction axes cannot be inferred from "
                    "the name; quantize it explicitly with quantize_int8(w, "
                    "contract_ndim) and splice the result into the tree"
                )
            q, scale = quantize_int8(w, contract_ndim)
            rest = {k: v for k, v in tree.items() if k != "kernel"}
            return {"kernel_q": q, "kernel_scale": scale, **rest}
        return {k: convert(k, v) for k, v in tree.items()}

    return convert("", params)
