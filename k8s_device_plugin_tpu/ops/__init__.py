"""Pallas TPU kernels and fused ops for the benchmark/serving workloads."""

from .flash_attention import flash_attention, mha_reference
from .fused_xent import fused_linear_xent, naive_linear_xent
from .paged_attention import paged_attention
from .quant import (
    Int8DenseGeneral,
    dequantize_int8,
    dequantize_kv,
    dequantize_kv4,
    int8_dot_general,
    pack_int4,
    quantize_int8,
    quantize_kv,
    quantize_kv4,
    quantize_kv_pair,
    quantize_lm_params,
    unpack_int4,
)
from .tuning import DecodeRow, decode_row, device_generation, pick_num_splits

__all__ = [
    "flash_attention",
    "mha_reference",
    "fused_linear_xent",
    "naive_linear_xent",
    "paged_attention",
    "Int8DenseGeneral",
    "DecodeRow",
    "decode_row",
    "dequantize_int8",
    "dequantize_kv",
    "dequantize_kv4",
    "device_generation",
    "int8_dot_general",
    "pack_int4",
    "pick_num_splits",
    "quantize_int8",
    "quantize_kv",
    "quantize_kv4",
    "quantize_kv_pair",
    "quantize_lm_params",
    "unpack_int4",
]
