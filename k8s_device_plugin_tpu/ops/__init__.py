"""Pallas TPU kernels and fused ops for the benchmark/serving workloads."""

from .flash_attention import flash_attention, mha_reference
from .fused_xent import fused_linear_xent, naive_linear_xent
from .paged_attention import paged_attention
from .quant import (
    Int8DenseGeneral,
    dequantize_int8,
    dequantize_kv,
    int8_dot_general,
    quantize_int8,
    quantize_kv,
    quantize_lm_params,
)

__all__ = [
    "flash_attention",
    "mha_reference",
    "fused_linear_xent",
    "naive_linear_xent",
    "paged_attention",
    "Int8DenseGeneral",
    "dequantize_int8",
    "dequantize_kv",
    "int8_dot_general",
    "quantize_int8",
    "quantize_kv",
    "quantize_lm_params",
]
