"""Pallas TPU kernels for the benchmark workloads' hot ops."""

from .flash_attention import flash_attention, mha_reference

__all__ = ["flash_attention", "mha_reference"]
