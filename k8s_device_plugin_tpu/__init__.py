"""TPU-native Kubernetes device plugin and JAX benchmark workloads.

A from-scratch re-design of the capabilities of the AMD ROCm GPU device plugin
(catsdogone/k8s-device-plugin, surveyed in SURVEY.md): discover TPU chips on a
node, register a ``google.com/tpu`` resource with the kubelet over the
device-plugin v1beta1 gRPC API, stream per-chip health, and answer ``Allocate``
by mounting the requested ``/dev/accel*`` nodes and injecting ICI-mesh/topology
environment so JAX/libtpu inside the pod can form the chip mesh.

Subpackages
-----------
- ``kubelet``  — the v1beta1 wire contract (proto, constants, gRPC bindings).
- ``plugin``   — discovery, topology, health, the DevicePlugin server, and the
  lifecycle manager (registration, kubelet-restart recovery, signals).
- ``models``   — JAX/Flax benchmark workloads (AlexNet, ResNet-50, BERT, a
  decoder LM with GQA/sliding-window/KV-cache decode, MoE variant) plus
  orbax checkpoint/resume.
- ``parallel`` — the workload-side parallel layer: dp/FSDP/tensor/sequence/
  expert/pipeline parallelism over jax.sharding meshes, multi-host bootstrap.
- ``ops``      — Pallas/TPU kernels used by the workloads.
- ``utils``    — logging and small shared helpers.
"""

__version__ = "0.1.0"
