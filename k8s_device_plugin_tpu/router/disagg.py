"""Disaggregated prefill/decode split policy: who prefills, who decodes.

The router fronts a fleet whose replicas may carry a ROLE
(``--role`` on the serving CLI, read back off each replica's
``/debug/state?summary=1`` poll): ``prefill`` replicas run long-prompt
prefill and stream the finished KV pages over ``POST /v1/prefill``;
``decode`` replicas pull those pages and serve the interactive decode;
``unified`` replicas do both (today's fleet).  This module is the
routing half of that split (models/engine_handoff.py is the engine
half) — a pure, jax-free policy the server feeds with poll state:

- **Classification** (:meth:`DisaggPolicy.classify`): prompt-length
  threshold × decode-pool pressure.  A prompt at/above
  ``threshold_tokens`` splits; when the decode pool runs HOT (max
  queue-wait pressure at/above ``hot_wait_s`` — the same host-side
  signal the migration planner reads) the bar drops to
  ``hot_threshold_tokens``, because a loaded decode pool is exactly
  when a long local prefill hurts interactive ITL most.  No healthy
  prefill replica → ``no_pool`` and the request rides the unified path
  unchanged — zero new failure modes for short chat traffic.
- **Prefill-source pick** (:func:`pick_prefill`): the least-pressured
  healthy prefill replica; its name becomes the ``X-Handoff-Source``
  locator the decode replica pulls from.

The router never touches KV bytes: it classifies, stamps the locator
on the decode dial, and the decode replica pulls the stream directly
from the prefill replica — so the transfer overlaps the prefill
compute and the router thread is never a copy loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# Classification verdicts (tpu_router_disagg_splits_total label values).
SPLIT = "split"
SHORT = "short"
NO_POOL = "no_pool"

# Serving-replica roles as the summary poll reports them.
ROLE_UNIFIED = "unified"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


@dataclass
class DisaggConfig:
    """Split-policy knobs (docs/disagg.md "Split policy")."""

    # Prompt length (tokens) at/above which a request's prefill is
    # dispatched to the prefill pool when the decode pool is calm.
    threshold_tokens: int = 256
    # The lower bar that applies while the decode pool runs hot — a
    # loaded decode pool is when local prefill hurts ITL most.
    hot_threshold_tokens: int = 64
    # Decode-pool pressure (seconds of queue wait, max over eligible
    # decode-capable replicas — replica_pressure) at/above which the
    # hot threshold applies.
    hot_wait_s: float = 0.5

    def __post_init__(self):
        if self.threshold_tokens < 1:
            raise ValueError(
                f"threshold_tokens must be >= 1, got {self.threshold_tokens}"
            )
        if not 1 <= self.hot_threshold_tokens <= self.threshold_tokens:
            raise ValueError(
                "hot_threshold_tokens must be in [1, threshold_tokens], "
                f"got {self.hot_threshold_tokens}"
            )


class DisaggPolicy:
    """Pure verdict function over (prompt length, decode pressure,
    prefill-pool health); the server owns discovery and dial plumbing."""

    def __init__(self, cfg: Optional[DisaggConfig] = None):
        self.cfg = cfg if cfg is not None else DisaggConfig()

    def classify(
        self,
        prompt_tokens: int,
        decode_pressure_s: float,
        prefill_pool_up: bool,
    ) -> str:
        """``split`` / ``short`` / ``no_pool`` for one request."""
        bar = (
            self.cfg.hot_threshold_tokens
            if decode_pressure_s >= self.cfg.hot_wait_s
            else self.cfg.threshold_tokens
        )
        if prompt_tokens < bar:
            return SHORT
        if not prefill_pool_up:
            return NO_POOL
        return SPLIT

    def snapshot(self) -> dict:
        return {
            "threshold_tokens": self.cfg.threshold_tokens,
            "hot_threshold_tokens": self.cfg.hot_threshold_tokens,
            "hot_wait_s": self.cfg.hot_wait_s,
        }


def pick_prefill(candidates: dict[str, float]) -> Optional[str]:
    """The least-pressured prefill replica (name -> pressure seconds);
    deterministic tie-break by name.  None on an empty pool."""
    if not candidates:
        return None
    return min(sorted(candidates), key=lambda name: candidates[name])
