"""Proactive planned migration + fleet scale signal (ISSUE 14).

The router already REACTS well — breakers, failover, overload backoff —
but every hot session stays pinned to a saturating home replica until
something actually breaks.  This module is the *planning* half: it
watches the host-side overload signals every replica already exports on
its summary poll (queue-wait EWMA and drain-rate forecast — the
Host-Side Telemetry pattern: host-observable signals, not device
counters) and decides

- **when to migrate**: a replica running sustained-hot (queue-wait
  pressure above ``hot_wait_s`` for ``sustain_polls`` consecutive
  polls) while a peer runs cold (pressure at or below ``cold_wait_s``)
  gets its hottest prefix-block sessions PLANNED off — executed by the
  router through the same zero-drop resubmission machinery reactive
  failover uses (server.py), but paced by this planner's migration
  budget and never mid-token-burst; and
- **when to scale**: :func:`scale_recommendation` turns the same
  signals into a fleet-level scale-up/down/hold verdict, served at
  ``GET /debug/fleet`` and rendered by ``tools/fleet_plan.py``.

Pure policy, no I/O, injectable clock — the unit suite drives it with a
fake clock and hand-built signal rows (tests/test_router.py).  The
planner never *executes* anything: the router owns streams and dials;
this object only answers "move N sessions from X to Y now?".
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class MigrationConfig:
    """Tunables for :class:`MigrationPlanner` (CLI: ``--migrate-*``)."""

    # A replica whose queue-wait pressure runs at/above this is hot.
    hot_wait_s: float = 2.0
    # A replica at/below this is a cold migration target.
    cold_wait_s: float = 0.5
    # Consecutive hot polls before the replica counts as SUSTAINED hot
    # (one bursty poll must never trigger a migration storm).
    sustain_polls: int = 3
    # Migration budget: a token bucket of planned moves — burst cap and
    # sustained pace.  Dry bucket = no plan, never a queue of plans.
    budget: float = 4.0
    refill_per_s: float = 1.0
    # Moves per plan() verdict (each spends one budget token).
    max_moves_per_plan: int = 2
    # Per-source cooldown between plans: let the last batch land and the
    # EWMA react before planning the same replica again.
    cooldown_s: float = 5.0


def replica_pressure(
    wait_ewma_s: Optional[float],
    drain_rate_rps: Optional[float],
    queue_depth: int,
) -> float:
    """One replica's queue-wait pressure in seconds: the measured
    queue-wait EWMA when the replica exports one, else the queue-depth /
    drain-rate forecast, else 0 (no data reads as cold — planners must
    never act on a guess, matching the overload controller's own
    degrade-to-no-opinion rule)."""
    if wait_ewma_s is not None:
        return float(wait_ewma_s)
    if drain_rate_rps and drain_rate_rps > 0:
        return queue_depth / drain_rate_rps
    return 0.0


class MigrationPlanner:
    """Sustained-hot detection + budget pacing over per-replica signal
    rows.  Feed one :meth:`observe` per replica per poll sweep, then ask
    :meth:`plan` for at most one (source, target, n_moves) verdict.

    Single-threaded by contract: the router's poll thread owns it (the
    same owner-thread discipline as ReplicaState's poll fields)."""

    def __init__(
        self,
        config: Optional[MigrationConfig] = None,
        *,
        now=time.monotonic,
    ):
        self.cfg = config or MigrationConfig()
        if self.cfg.hot_wait_s <= self.cfg.cold_wait_s:
            raise ValueError(
                "hot_wait_s must exceed cold_wait_s "
                f"({self.cfg.hot_wait_s} <= {self.cfg.cold_wait_s})"
            )
        if self.cfg.sustain_polls < 1:
            raise ValueError("sustain_polls must be >= 1")
        self._now = now
        self._tokens = float(self.cfg.budget)
        self._last_refill = now()
        # Per-replica: latest signal row + hot streak + last-planned.
        self._rows: dict[str, dict] = {}
        self._streaks: dict[str, int] = {}
        self._last_plan: dict[str, float] = {}
        self.plans_total = 0
        self.moves_planned_total = 0

    # -------------------------------------------------------- observation

    def observe(
        self,
        name: str,
        *,
        wait_ewma_s: Optional[float],
        drain_rate_rps: Optional[float],
        queue_depth: int,
        eligible: bool,
    ) -> None:
        """One poll row for ``name``.  ``eligible`` is the router's
        routability verdict (reachable, not draining/fenced): an
        ineligible replica is neither a source (its streams already
        fail over) nor a target, and its streak resets."""
        pressure = replica_pressure(
            wait_ewma_s, drain_rate_rps, queue_depth
        )
        self._rows[name] = {
            "pressure": pressure,
            "queue_depth": int(queue_depth),
            "eligible": bool(eligible),
        }
        if eligible and pressure >= self.cfg.hot_wait_s:
            self._streaks[name] = self._streaks.get(name, 0) + 1
        else:
            self._streaks[name] = 0

    def forget(self, name: str) -> None:
        """Membership removal: drop every trace of the replica."""
        self._rows.pop(name, None)
        self._streaks.pop(name, None)
        self._last_plan.pop(name, None)

    # ------------------------------------------------------------ planning

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(
            float(self.cfg.budget),
            self._tokens
            + (now - self._last_refill) * self.cfg.refill_per_s,
        )
        self._last_refill = now

    def sustained_hot(self, name: str) -> bool:
        return self._streaks.get(name, 0) >= self.cfg.sustain_polls

    def plan(self) -> Optional[tuple[str, str, int]]:
        """At most one (source, target, n_moves) verdict per call: the
        hottest sustained-hot replica paired with the coldest eligible
        target, gated by budget and per-source cooldown.  None when
        nothing should move — the overwhelmingly common answer."""
        self._refill()
        if self._tokens < 1.0:
            return None
        now = self._now()
        hot = [
            (row["pressure"], name)
            for name, row in self._rows.items()
            if row["eligible"]
            and self.sustained_hot(name)
            and now - self._last_plan.get(name, -1e9) >= self.cfg.cooldown_s
        ]
        if not hot:
            return None
        cold = [
            (row["pressure"], name)
            for name, row in self._rows.items()
            if row["eligible"] and row["pressure"] <= self.cfg.cold_wait_s
        ]
        if not cold:
            # Fleet-wide hot: nowhere to move — that is a SCALE signal
            # (scale_recommendation reads the same rows), not a license
            # to shuffle load between two saturated replicas.
            return None
        _, source = max(hot)
        cold = [(p, n) for p, n in cold if n != source]
        if not cold:
            return None
        _, target = min(cold)
        n_moves = min(self.cfg.max_moves_per_plan, int(self._tokens))
        self._tokens -= n_moves
        self._last_plan[source] = now
        self._streaks[source] = 0  # re-arm: re-plan only if STILL hot
        self.plans_total += 1
        self.moves_planned_total += n_moves
        return source, target, n_moves

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """JSON-safe planner state for GET /debug/fleet."""
        self._refill()
        return {
            "enabled": True,
            "hot_wait_s": self.cfg.hot_wait_s,
            "cold_wait_s": self.cfg.cold_wait_s,
            "sustain_polls": self.cfg.sustain_polls,
            "budget_tokens": round(self._tokens, 2),
            "plans_total": self.plans_total,
            "moves_planned_total": self.moves_planned_total,
            "replicas": {
                name: {
                    "pressure_s": round(row["pressure"], 4),
                    "hot_streak": self._streaks.get(name, 0),
                    "eligible": row["eligible"],
                }
                for name, row in sorted(self._rows.items())
            },
        }


def scale_recommendation(
    signals: dict[str, dict],
    *,
    hot_wait_s: float = MigrationConfig.hot_wait_s,
    cold_wait_s: float = MigrationConfig.cold_wait_s,
) -> dict:
    """Fleet scale verdict from per-replica signal rows.

    ``signals``: ``{name: {"pressure_s", "queue_depth", "eligible"}}``
    (the shape ``RouterServer.fleet_state`` builds from poll state).

    - **scale_up** when a majority of the eligible fleet runs hot and no
      cold headroom exists to migrate into — adding replicas is the only
      move left (suggested count grows by the hot replica count).
    - **scale_down** when EVERY eligible replica is cold with empty
      queues and there is more than one — the fleet is paying for
      headroom nobody uses (suggest dropping one at a time: consistent
      hashing remaps ~1/K per removal, so gentle beats bold).
    - **hold** otherwise (including no data: never scale on a guess).

    Every verdict carries the ``hot_wait_s``/``cold_wait_s`` thresholds
    it was judged with, so a downstream consumer (the fleet controller)
    classifies replicas the recommendation doesn't cover — the prefill
    pool is ineligible here by design — with the SAME knobs and a
    decision stays explainable from one snapshot.
    """
    eligible = {
        name: row for name, row in signals.items() if row.get("eligible")
    }
    n = len(eligible)
    if n == 0:
        return {
            "action": "hold",
            "reason": "no eligible replicas polled — not scaling on a guess",
            "replicas": len(signals),
            "suggested_replicas": len(signals),
            "hot": [],
            "cold": [],
            "hot_wait_s": hot_wait_s,
            "cold_wait_s": cold_wait_s,
        }
    hot = sorted(
        name
        for name, row in eligible.items()
        if row["pressure_s"] >= hot_wait_s
    )
    cold = sorted(
        name
        for name, row in eligible.items()
        if row["pressure_s"] <= cold_wait_s
    )
    if len(hot) * 2 >= n and not cold:
        return {
            "action": "scale_up",
            "reason": (
                f"{len(hot)}/{n} replicas sustained-hot with no cold "
                "headroom to migrate into"
            ),
            "replicas": n,
            "suggested_replicas": n + max(1, len(hot)),
            "hot": hot,
            "cold": cold,
            "hot_wait_s": hot_wait_s,
            "cold_wait_s": cold_wait_s,
        }
    total_queue = sum(row["queue_depth"] for row in eligible.values())
    if len(cold) == n and n > 1 and total_queue == 0:
        return {
            "action": "scale_down",
            "reason": (
                f"all {n} replicas cold with empty queues — paying for "
                "idle headroom"
            ),
            "replicas": n,
            "suggested_replicas": n - 1,
            "hot": hot,
            "cold": cold,
            "hot_wait_s": hot_wait_s,
            "cold_wait_s": cold_wait_s,
        }
    return {
        "action": "hold",
        "reason": (
            f"{len(hot)} hot / {len(cold)} cold of {n} — migration "
            "headroom available" if hot else f"fleet within bounds "
            f"({len(cold)} cold of {n})"
        ),
        "replicas": n,
        "suggested_replicas": n,
        "hot": hot,
        "cold": cold,
        "hot_wait_s": hot_wait_s,
        "cold_wait_s": cold_wait_s,
    }
