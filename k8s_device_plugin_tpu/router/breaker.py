"""Per-replica circuit breakers and the global retry budget.

The failure containment half of the router: a replica that starts
failing must be cut out of the dispatch order BEFORE every request pays
its connect timeout (breaker), and a fleet-wide brownout must not let
retries multiply the load that caused it (budget).

Breaker state machine (the classic three states):

    closed ──(N consecutive failures)──> open
    open   ──(cooldown elapsed)────────> half_open   (one probe allowed)
    half_open ──probe success──> closed
    half_open ──probe failure──> open   (fresh cooldown)

``try_acquire()`` is the dispatch-side gate: it consumes the half-open
probe slot, so exactly one request tests a recovering replica while the
rest keep failing over — a thundering herd against a just-restarted
replica is the failure mode half-open exists to prevent.

The retry budget is a token bucket shared by every retry/hedge/failover
in the process: first attempts are free (clients must not be rejected
because the budget is empty), every EXTRA upstream dispatch spends a
token.  When the bucket is dry the router degrades to
one-attempt-per-request instead of amplifying a brownout — the
"retry storm turns a partial outage into a full one" postmortem shape.

Stdlib-only; clocks are injectable so the tier-1 tests step time instead
of sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding for tpu_router_breaker_state (docs/routing.md).
STATE_VALUE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """One replica's failure gate.  Thread-safe.

    ``on_transition(old, new)`` fires OUTSIDE the lock on every state
    change — the router's flight/metrics hook.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        open_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if open_s <= 0:
            raise ValueError(f"open_s must be > 0, got {open_s}")
        self.failure_threshold = failure_threshold
        self.open_s = open_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded by: _lock
        self._failures = 0  # consecutive, closed state only; guarded by: _lock
        self._opened_at = 0.0  # guarded by: _lock
        self._probe_in_flight = False  # guarded by: _lock

    # ---------------------------------------------------------- queries

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "probe_in_flight": self._probe_in_flight,
                "open_remaining_s": (
                    round(
                        max(0.0, self._opened_at + self.open_s - self._clock()),
                        3,
                    )
                    if self._state == OPEN
                    else 0.0
                ),
            }

    # ------------------------------------------------------- transitions

    def _transition(self, new: str) -> Optional[tuple[str, str]]:  # caller holds: _lock
        """Lock-held state change; returns (old, new) for the callback."""
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _notify(self, change: Optional[tuple[str, str]]) -> None:
        if change is not None and self._on_transition is not None:
            self._on_transition(*change)

    def try_acquire(self) -> bool:
        """May a dispatch go to this replica right now?  Open: no (until
        the cooldown elapses, which flips to half-open).  Half-open: yes
        for exactly ONE in-flight probe.  Closed: yes."""
        change = None
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.open_s:
                    return False
                change = self._transition(HALF_OPEN)
                self._probe_in_flight = True
                ok = True
            else:  # HALF_OPEN: one probe at a time
                ok = not self._probe_in_flight
                if ok:
                    self._probe_in_flight = True
        self._notify(change)
        return ok

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            change = self._transition(CLOSED)
        self._notify(change)

    def record_failure(self) -> None:
        change = None
        with self._lock:
            if self._state == HALF_OPEN:
                # Probe failed: straight back to open, fresh cooldown.
                self._probe_in_flight = False
                self._opened_at = self._clock()
                change = self._transition(OPEN)
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._opened_at = self._clock()
                    change = self._transition(OPEN)
            # OPEN: failures while open (e.g. a racing dispatch that
            # acquired before the trip) don't extend the cooldown — the
            # half-open probe owns recovery timing.
        self._notify(change)


class RetryBudget:
    """Global token bucket bounding EXTRA upstream dispatches.

    ``capacity`` tokens, refilled continuously at ``refill_per_s``.
    First attempts never touch the budget; every retry, hedge, or
    failover calls :meth:`try_spend` and backs off to single-attempt
    behavior when refused — retries must not amplify a brownout.
    """

    def __init__(
        self,
        capacity: float = 32.0,
        refill_per_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        if refill_per_s < 0:
            raise ValueError(f"refill_per_s must be >= 0, got {refill_per_s}")
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(capacity)
        self._last = clock()
        self.spent_total = 0
        self.exhausted_total = 0

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.capacity, self._tokens + (now - self._last) * self.refill_per_s
        )
        self._last = now

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                self.spent_total += 1
                return True
            self.exhausted_total += 1
            return False

    def available(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens
