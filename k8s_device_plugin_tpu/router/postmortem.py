"""Fleet postmortem collector: one incident, one fleet-wide bundle.

The single-process capture hook (utils/postmortem.py) saves each
component's OWN forensic state — but a fleet incident's evidence is
scattered: the victim replica's flight ring, the router's placement and
failover events, the plugin daemon's device journal, the controller's
decision log.  This collector (armed by the router's ``--postmortem``
flag) watches for incidents two ways:

- **Summary-poll cursor**: every replica's ``?summary=1`` now carries
  its cumulative ``incidents_total``; the poll thread hands advances to
  :meth:`observe_poll`, which fires a capture for the replica's episode.
- **Local incidents**: the router's own AnomalyMonitors (SLO burn
  alerts, canary mismatches) get this collector as a full-record
  listener.

On any trigger it fans out to every replica's (plus, when configured,
the plugin daemon's and the controller's) ``/debug/flight``,
``/debug/spans``, ``/debug/state``, and ``/metrics``, and writes ONE
fleet bundle keyed by the incident id — the input
``tools/postmortem.py`` joins into a causally-ordered timeline and
classifies.  Served at ``GET /debug/postmortem``; a manual capture can
be forced via the admin-gated ``POST /debug/postmortem/capture``.

Bundle layout (``postmortem-fleet-<ts>-<digest12>/``)::

    manifest.json      schema, incident id/trigger, per-component
                       fetch accounting (ok/error per endpoint),
                       per-file digests, bundle digest
    router.json        the router's own flight/spans/state/metrics
    replica-<name>.json one per replica: the four endpoint bodies
    plugin.json        the plugin daemon's four endpoint bodies
    controller.json    the controller's four endpoint bodies

Capture runs on its own daemon thread (never the poll thread — a slow
replica must not stall the summary cadence) and shares the dump dir's
retention budget with the flight-dump writer
(utils/postmortem.sweep_dump_dir).
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

from ..utils.postmortem import (
    BUNDLE_PREFIX,
    INPROGRESS_SUFFIX,
    metric_families,
    sweep_dump_dir,
)

log = logging.getLogger("tpu.router.postmortem")

FLEET_SCHEMA = "tpu-postmortem-fleet/v1"
# The forensic surfaces pulled from every component.  A component that
# lacks one (the controller serves no /debug/state) gets an error row in
# the manifest, never a failed capture.
ENDPOINTS = ("/debug/flight", "/debug/spans", "/debug/state", "/metrics")

_CONN_ERRORS = (ConnectionError, OSError, TimeoutError)


def _safe_component(name: str) -> str:
    """A component name as a filename fragment (host:port → host_port)."""
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in name)


class FleetPostmortem:
    """The router-side fleet collector (``--postmortem``).

    ``targets_fn`` returns the replica ``host:port`` list at capture
    time (membership may have changed since the trigger — capture
    whoever is in the fleet NOW, the victim included while its summary
    still answers).  ``local_fn`` returns the router's own component
    payload (flight/spans/state/metrics) without a self-dial.
    """

    def __init__(
        self,
        directory: str,
        targets_fn,
        *,
        local_fn=None,
        plugin_url: Optional[str] = None,
        controller_url: Optional[str] = None,
        flight=None,
        registry=None,
        debounce_s: float = 120.0,
        timeout_s: float = 5.0,
        budget_bytes: Optional[int] = None,
        max_entries: Optional[int] = None,
        admin: bool = True,
        keep: int = 32,
        now=time.monotonic,
    ):
        self.directory = directory
        self.targets_fn = targets_fn
        self.local_fn = local_fn
        self.plugin_url = plugin_url
        self.controller_url = controller_url
        self.flight = flight
        self.debounce_s = float(debounce_s)
        self.timeout_s = float(timeout_s)
        self.budget_bytes = budget_bytes
        self.max_entries = max_entries
        self.admin = admin
        self._now = now
        self._lock = threading.Lock()
        self._last_capture: dict[str, float] = {}  # guarded by: _lock
        self._digests: set[str] = set()  # guarded by: _lock
        self._bundles: deque[dict] = deque(maxlen=keep)  # guarded by: _lock
        self.captures = 0
        self.skipped = 0
        self.last_bundle: Optional[str] = None
        self.last_error: Optional[str] = None
        self._captures_total = None
        self._bundle_bytes = None
        if registry is not None:
            self._captures_total, self._bundle_bytes = metric_families(
                registry
            )

    # ---------------------------------------------------------- triggers

    def observe_poll(self, replica: str, incidents_total: int) -> None:
        """A replica's summary-poll incident cursor advanced: capture
        its episode (async — never on the poll thread)."""
        self.trigger(
            f"{replica}#{incidents_total}",
            trigger="summary_poll",
            episode=replica,
        )

    def on_incident(self, incident: dict) -> None:
        """Full-record listener for the router's OWN AnomalyMonitors
        (SLO burn alerts, canary mismatches)."""
        metric = str(incident.get("metric", "incident"))
        self.trigger(
            f"router:{metric}", trigger="local_incident", episode=metric
        )

    def trigger(
        self,
        incident_id: str,
        *,
        trigger: str = "manual",
        episode: Optional[str] = None,
    ) -> None:
        """Fire-and-forget capture on a worker thread, debounced per
        episode key (one bundle per episode, however many incidents the
        cooldown re-fires)."""
        key = episode or incident_id
        now = self._now()
        with self._lock:
            last = self._last_capture.get(key)
            if last is not None and now - last < self.debounce_s:
                debounced = True
            else:
                debounced = False
                self._last_capture[key] = now
        if debounced:
            self._skip(trigger, incident_id, "debounced")
            return
        threading.Thread(
            target=self._capture_guarded,
            args=(incident_id, trigger),
            name="postmortem-capture",
            daemon=True,
        ).start()

    def capture_now(
        self, incident_id: str, trigger: str = "manual"
    ) -> Optional[str]:
        """Synchronous capture, NO debounce — the admin POST and the
        test/bench harnesses.  Returns the bundle path (None on
        duplicate evidence or error)."""
        return self._capture_guarded(incident_id, trigger)

    # ----------------------------------------------------------- capture

    def _skip(self, trigger: str, incident_id: str, reason: str) -> None:
        self.skipped += 1
        if self._captures_total is not None:
            self._captures_total.inc(trigger=trigger, outcome=reason)
        if self.flight is not None:
            self.flight.record(
                "postmortem.skipped",
                key=incident_id,
                trigger=trigger,
                reason=reason,
            )

    def _capture_guarded(self, incident_id, trigger) -> Optional[str]:
        try:
            return self._capture(incident_id, trigger)
        except Exception as e:  # never poison the caller
            log.exception("fleet postmortem capture failed")
            self.last_error = str(e)
            self._skip(trigger, incident_id, "error")
            return None

    def _fetch(self, target: str, path: str):
        """One GET against ``host:port``; returns (body, error) — JSON
        decoded when possible, exposition text for /metrics."""
        host, _, port = target.rpartition(":")
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.timeout_s
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                return None, f"HTTP {resp.status}"
            if path == "/metrics":
                return raw.decode(errors="replace"), None
            return json.loads(raw or b"{}"), None
        except (*_CONN_ERRORS, ValueError) as e:
            return None, str(e)
        finally:
            conn.close()

    def _collect(self, target: str) -> tuple[dict, dict]:
        """All forensic endpoints of one component: (payload, fetch
        accounting).  Endpoint keys are the basenames the classifier
        reads (flight/spans/state/metrics)."""
        payload: dict = {}
        fetched: dict = {}
        for path in ENDPOINTS:
            body, err = self._fetch(target, path)
            name = path.rsplit("/", 1)[-1]
            if err is None:
                payload[name] = body
                fetched[name] = "ok"
            else:
                fetched[name] = f"error: {err}"
        return payload, fetched

    def _capture(self, incident_id: str, trigger: str) -> Optional[str]:
        if not self.directory:
            self._skip(trigger, incident_id, "no_dir")
            return None
        components: dict[str, bytes] = {}
        accounting: dict[str, dict] = {}
        if self.local_fn is not None:
            try:
                local = self.local_fn()
                components["router.json"] = json.dumps(
                    local, separators=(",", ":"), default=str
                ).encode()
                accounting["router"] = {"local": "ok"}
            except Exception as e:
                accounting["router"] = {"local": f"error: {e}"}
        for target in list(self.targets_fn() or ()):
            payload, fetched = self._collect(target)
            accounting[f"replica-{target}"] = fetched
            if payload:
                payload["component"] = f"replica-{target}"
                components[
                    f"replica-{_safe_component(target)}.json"
                ] = json.dumps(
                    payload, separators=(",", ":"), default=str
                ).encode()
        for role, url in (
            ("plugin", self.plugin_url),
            ("controller", self.controller_url),
        ):
            if not url:
                continue
            payload, fetched = self._collect(url)
            accounting[role] = fetched
            if payload:
                payload["component"] = role
                components[f"{role}.json"] = json.dumps(
                    payload, separators=(",", ":"), default=str
                ).encode()
        if not components:
            self._skip(trigger, incident_id, "error")
            self.last_error = "no component answered any forensic endpoint"
            return None

        digest = hashlib.sha256()
        for name in sorted(components):
            digest.update(name.encode())
            digest.update(components[name])
        bundle_digest = digest.hexdigest()
        with self._lock:
            if bundle_digest in self._digests:
                duplicate = True
            else:
                duplicate = False
                self._digests.add(bundle_digest)
        if duplicate:
            self._skip(trigger, incident_id, "duplicate")
            return None

        name = (
            f"{BUNDLE_PREFIX}fleet-{int(time.time())}-{bundle_digest[:12]}"
        )
        final = os.path.join(self.directory, name)
        staging = final + INPROGRESS_SUFFIX
        manifest = {
            "schema": FLEET_SCHEMA,
            "incident_id": incident_id,
            "trigger": trigger,
            "pid": os.getpid(),
            "ts": round(time.time(), 3),
            "digest": bundle_digest,
            "components": accounting,
            "files": {
                n: {
                    "bytes": len(body),
                    "sha256": hashlib.sha256(body).hexdigest(),
                }
                for n, body in components.items()
            },
        }
        os.makedirs(staging, exist_ok=True)
        for fname, body in components.items():
            with open(os.path.join(staging, fname), "wb") as f:
                f.write(body)
        with open(os.path.join(staging, "manifest.json"), "w") as f:
            json.dump(manifest, f, separators=(",", ":"))
        os.rename(staging, final)

        bundle_bytes = sum(len(b) for b in components.values())
        record = {
            "incident_id": incident_id,
            "trigger": trigger,
            "bundle": name,
            "path": final,
            "bytes": bundle_bytes,
            "ts": manifest["ts"],
            "components": sorted(accounting),
            "errors": sum(
                1
                for fetched in accounting.values()
                for v in fetched.values()
                if str(v).startswith("error")
            ),
        }
        with self._lock:
            self._bundles.append(record)
        self.captures += 1
        self.last_bundle = final
        if self._captures_total is not None:
            self._captures_total.inc(trigger=trigger, outcome="captured")
        if self._bundle_bytes is not None:
            self._bundle_bytes.set(bundle_bytes)
        if self.flight is not None:
            self.flight.record(
                "postmortem.captured",
                key=incident_id,
                trigger=trigger,
                bundle=name,
                bytes=bundle_bytes,
                digest=bundle_digest[:12],
            )
        sweep_dump_dir(
            self.directory,
            self.budget_bytes,
            self.max_entries,
            protect=(final,),
            flight=self.flight,
        )
        return final

    # ---------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The ``GET /debug/postmortem`` body."""
        with self._lock:
            bundles = [dict(b) for b in self._bundles]
            keys = len(self._last_capture)
        return {
            "enabled": True,
            "directory": self.directory,
            "debounce_s": self.debounce_s,
            "budget_bytes": self.budget_bytes,
            "plugin_url": self.plugin_url,
            "controller_url": self.controller_url,
            "captures": self.captures,
            "skipped": self.skipped,
            "episodes": keys,
            "last_bundle": self.last_bundle,
            "last_error": self.last_error,
            "bundles": bundles,
        }
