"""The router daemon: a fault-tolerant prefix-affinity /generate proxy.

One process fronts K serving replicas (models/http_server.EngineServer)
and owns everything between "client sent a prompt" and "a replica's
engine decoded it":

- **Placement** — `RoutingPolicy` (prefix-affinity consistent hashing +
  queue-depth overflow over poll state from ``/debug/state?summary=1``).
- **Failure containment** — per-replica `CircuitBreaker`s gate every
  dial; a global `RetryBudget` bounds extra dispatches; retries use
  exponential backoff with full jitter and honor ``Retry-After``.
- **Hedging** (unary, opt-in) — when a response hasn't arrived within
  the rolling TTFT p99, a second dispatch races the first along the
  ring; first response wins, the loser's connection is closed.
- **Mid-stream failover** — a streaming request whose replica dies
  mid-decode is transparently resubmitted to the next ring replica as
  ``prompt + already-emitted tokens`` with the remaining budget, under
  the SAME request id (idempotent: the resubmission carries the emitted
  tokens in its prompt, so nothing can double-emit).  On the failover
  replica the content-addressed KV restore (models/engine_kvcache.py)
  turns the re-prefill into a page restore when the prefix is warm.
  The client sees one uninterrupted token stream — zero-drop is the
  contract the chaos suite scores (docs/chaos.md).
- **Drain awareness** — a replica answering 503/draining (or whose
  summary poll says so) takes no NEW assignments immediately, while its
  in-flight proxied streams run to completion; ``Retry-After`` feeds
  the backoff when nothing else is dialable.  A 503 carrying ``X-Shed``
  is overload, not drain: the replica stays in rotation and only this
  request moves on (still flooring its backoff on ``Retry-After``).
- **Deadline propagation** — a client ``X-Request-Deadline`` (remaining
  seconds; body ``deadline_s``) bounds the whole attempt budget: every
  upstream dial re-stamps the REMAINING budget, retry sleeps and hedges
  spend only when the budget still allows an answer, and a deadline no
  replica's queue forecast can meet fails fast with 504 — never
  enqueued anywhere.  ``X-Request-Priority``/``X-Tenant-Id`` fold into
  the upstream body for the engine's priority admission.

- **Planned migration** (ISSUE 14, ``--migrate``) — the proactive
  cousin of failover: a `MigrationPlanner` (router/migration.py) watches
  the host-side queue-wait EWMA / drain-rate signals each summary poll
  already carries, and when a replica runs sustained-hot while a peer
  runs cold, live streams of its hottest prefix-block sessions are
  resubmitted to the cold target through the SAME zero-drop machinery —
  paced by a migration budget, only at paced token boundaries (never
  mid-token-burst), aborted if the target's breaker refuses.

Surfaces: ``POST /generate`` (unary + SSE passthrough), ``GET /healthz``
(503 until a replica is reachable; ``draining`` during shutdown),
``GET /metrics`` (Prometheus), ``GET /debug/router`` (full snapshot),
``GET /debug/fleet`` (per-replica host-side signals + migration planner
state + the scale-up/down recommendation ``tools/fleet_plan.py``
renders), ``GET /debug/slo`` (fleet error budgets + burn-rate alerts
merged from the per-replica SLI counters every summary poll carries),
``GET /debug/fabric`` (the fleet KV fabric's locator views and
replication ledger — router/fabric.py),
``GET /debug/spans`` (the router's request-span ring;
``?rid=`` filters one trace).  Every fault-handling decision is a
flight event (``router.*``, per-request ones carrying ``rid``) so a
chaos run can join injected replica kills against what the router saw.

Distributed tracing (ISSUE 12): the router records its own span tree
per request — a ``router.request`` root, ``router.route`` selection
children, and one ``router.attempt`` child per upstream leg — and
stamps each leg's span id into the dial's ``X-Trace-Context`` header
(utils/spans.py hop context), so the replica's span tree roots under
exactly the leg that carried it.  ``tools/trace_assemble.py`` joins the
rings into one per-request fleet timeline; the chaos kill scenario
scores that assembly's completeness.

Chaos seam: each upstream dial fires the ``router.replica_conn``
failpoint scoped per replica (``router.replica_conn.<host:port>``) —
error/delay/hang inject dial-level faults without touching sockets.

Stdlib + utils only; jax is never imported here.
"""

from __future__ import annotations

import json
import queue as queue_mod
import random
import socket
import tempfile
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import http.client

from ..utils import failpoints
from ..utils.metrics import MetricsRegistry, write_exposition
from ..utils.slo import SLOTracker
from ..utils.spans import (
    TRACE_CONTEXT_HEADER,
    SpanRecorder,
    format_trace_context,
    sanitize_trace_id,
)
from ..models.engine_handoff import (
    FABRIC_RESIDENT_ONLY_HEADER,
    HANDOFF_LOCAL,
    HANDOFF_SOURCE_HEADER,
    PREFILL_NEEDED_HEADER,
)
from .breaker import STATE_VALUE, CircuitBreaker, RetryBudget
from .disagg import NO_POOL, ROLE_PREFILL, SPLIT, DisaggConfig, DisaggPolicy, pick_prefill
from .fabric import (
    VERDICT_HIT,
    VERDICT_MISS,
    VERDICT_RESIDENT,
    VERDICT_SKIP,
    FabricConfig,
    FabricLocator,
    FabricReplicator,
)
from .migration import (
    MigrationConfig,
    MigrationPlanner,
    replica_pressure,
    scale_recommendation,
)
from .policy import FAILOVER, MIGRATION, ReplicaState, RoutingPolicy
from .prober import CanaryConfig, CanaryProber
from .ring import HashRing

FAILPOINT_CONN = "router.replica_conn"

# Upstream transport failures: everything that means "this replica did
# not answer", as opposed to "this replica answered badly".
_CONN_ERRORS = (OSError, http.client.HTTPException)


class RouterMetrics:
    """The router's Prometheus families (linted live in tier-1)."""

    def __init__(self, registry: MetricsRegistry):
        self.requests = registry.counter(
            "tpu_router_requests_total",
            "Client requests by outcome (ok/error/rejected/timeout/"
            "deadline — deadline = the client's X-Request-Deadline "
            "expired or could not be met, answered 504 without "
            "enqueueing)",
            ("outcome",),
        )
        self.placements = registry.counter(
            "tpu_router_placements_total",
            "Dispatches by placement decision (home/overflow/random/"
            "failover/migration)",
            ("placement",),
        )
        self.migrations = registry.counter(
            "tpu_router_migrations_total",
            "Planned session migrations by outcome (planned: stream "
            "flagged to move off a sustained-hot replica; done: the "
            "move landed on its target; aborted: target "
            "breaker/eligibility or dial refused — the stream stays "
            "put or falls back to ordinary failover)",
            ("outcome",),
        )
        self.retries = registry.counter(
            "tpu_router_retries_total",
            "Upstream re-dispatches after a failed attempt",
        )
        self.disagg_splits = registry.counter(
            "tpu_router_disagg_splits_total",
            "Disaggregation verdicts per request (router/disagg.py): "
            "split = long prompt stamped with an X-Handoff-Source "
            "prefill locator; short = below the (pressure-scaled) "
            "prompt-length threshold, unified dispatch; no_pool = "
            "split-worthy but no healthy prefill replica — degraded to "
            "unified dispatch",
            ("verdict",),
        )
        self.disagg_refusals = registry.counter(
            "tpu_router_disagg_refusals_total",
            "Decode-replica 409 + X-Prefill-Needed refusals observed "
            "on dispatch (the prompt's prefix was not resident and no "
            "locator rode the dial — a misclassified split or a "
            "decode-only fleet without --disagg); the replica is "
            "skipped, not tripped",
        )
        self.failovers = registry.counter(
            "tpu_router_failovers_total",
            "Mid-stream failovers (stream resubmitted to another replica)",
        )
        self.hedges = registry.counter(
            "tpu_router_hedges_total",
            "Hedged dispatches by result (won/lost)",
            ("result",),
        )
        self.breaker_transitions = registry.counter(
            "tpu_router_breaker_transitions_total",
            "Circuit breaker transitions by destination state",
            ("state",),
        )
        self.replica_up = registry.gauge(
            "tpu_router_replica_up",
            "1 when the replica's summary poll succeeds, else 0",
            ("replica",),
        )
        self.replica_queue_depth = registry.gauge(
            "tpu_router_replica_queue_depth",
            "Replica engine queue depth from the last summary poll",
            ("replica",),
        )
        self.replica_draining = registry.gauge(
            "tpu_router_replica_draining",
            "1 while the replica reports draining (no new assignments)",
            ("replica",),
        )
        self.replica_fenced = registry.gauge(
            "tpu_router_replica_fenced",
            "1 while the replica reports fenced (self-fenced on a hung "
            "step / sick chip / operator fence: no new assignments, "
            "in-flight streams fail over)",
            ("replica",),
        )
        self.breaker_state = registry.gauge(
            "tpu_router_breaker_state",
            "Breaker state per replica (0 closed, 1 open, 2 half-open)",
            ("replica",),
        )
        self.retry_budget = registry.gauge(
            "tpu_router_retry_budget",
            "Retry-budget tokens currently available",
        )
        self.ttft_seconds = registry.histogram(
            "tpu_router_ttft_seconds",
            "Client-observed time to first token through the router",
        )
        self.request_seconds = registry.histogram(
            "tpu_router_request_seconds",
            "Client-observed total request latency through the router",
        )
        self.poll_seconds = registry.histogram(
            "tpu_router_poll_seconds",
            "Per-replica summary poll latency",
        )
        # Fleet SLO plane (utils/slo.py, --slo): burn rates over the
        # fleet-merged SLI deltas every summary poll carries, and the
        # alert transitions the multi-window rules fired.  Objective and
        # window are closed label sets (3 objectives x 3 windows), never
        # per-replica or per-tenant.
        self.slo_burn_rate = registry.gauge(
            "tpu_slo_burn_rate",
            "Fleet error-budget burn rate per objective and sliding "
            "window (1.0 = spending exactly the whole budget over the "
            "objective period; the fast-burn page rule fires at 14.4)",
            ("objective", "window"),
        )
        self.slo_burn_alerts = registry.counter(
            "tpu_router_slo_burn_alerts_total",
            "Multi-window burn-rate alerts FIRED per objective and "
            "severity (page: fast burn; ticket: slow burn) — "
            "clears are flight events, not counted here",
            ("objective", "severity"),
        )
        # Active correctness plane (router/prober.py, --canary): the
        # canary prober's verdict counters and probe-latency
        # histograms.  Verdict is a closed set (prober.VERDICTS plus
        # the synthetic "router" replica for the end-to-end path).
        self.canary_probes = registry.counter(
            "tpu_router_canary_probes_total",
            "Canary probe verdicts per replica (capture: oracle "
            "learned; match: bit-exact; mismatch: wrong tokens — K "
            "consecutive fires canary.mismatch + auto-fence; stale: "
            "summary counters frozen while probes land; error: dial "
            "failed; skip_fenced: replica already fenced).  The "
            "synthetic replica \"router\" is the through-router "
            "end-to-end probe",
            ("replica", "verdict"),
        )
        self.canary_fences = registry.counter(
            "tpu_router_canary_fences_total",
            "Auto-fences fired by the canary prober after K "
            "consecutive bit-exactness mismatches (POST /debug/fence "
            "accepted by the replica)",
            ("replica",),
        )
        self.canary_probe_ttft = registry.histogram(
            "tpu_router_canary_probe_ttft_seconds",
            "Canary probe time-to-first-token (direct replica dials; "
            "the active-probing latency SLI, unlabeled on purpose — "
            "per-replica attribution lives in /debug/canary)",
        )
        self.canary_probe_itl = registry.histogram(
            "tpu_router_canary_probe_itl_seconds",
            "Canary probe mean inter-token latency (direct replica "
            "dials)",
        )
        # Fleet KV fabric (router/fabric.py, --fabric): the locator's
        # per-dial resolution verdicts (closed set: hit/resident/miss/
        # skip), the replication plane's pull/drop outcomes (ok/error),
        # and each replica's advertised digest size off the poll.
        self.fabric_resolutions = registry.counter(
            "tpu_router_fabric_resolutions_total",
            "Fabric locator resolutions per upstream dial (hit: a "
            "better owner than the target was stamped as "
            "X-Handoff-Source; resident: the target already advertises "
            "the prompt's prefix; miss: nobody in the fleet advertises "
            "it; skip: adapter prompt — engine-local trie roots the "
            "router cannot address)",
            ("verdict",),
        )
        self.fabric_replications = registry.counter(
            "tpu_router_fabric_replications_total",
            "K-replica hot-prefix replication pulls fired at engines "
            "(POST /debug/fabric/pull) by outcome — an error admits "
            "nothing on the target and self-heals out of the ledger",
            ("outcome",),
        )
        self.fabric_drops = registry.counter(
            "tpu_router_fabric_drops_total",
            "Cold-prefix eviction drops fired at engines "
            "(POST /debug/fabric/drop) by outcome; only router-created "
            "copies are ever dropped, never a traffic-warmed origin",
            ("outcome",),
        )
        self.fabric_advertised_roots = registry.gauge(
            "tpu_router_fabric_advertised_roots",
            "Prefix roots each replica's fabric digest advertised on "
            "its last summary poll (0 = no digest: handoff off, arena "
            "off, or an unparseable advertisement)",
            ("replica",),
        )

    def drop_replica(self, name: str) -> None:
        for gauge in (
            self.replica_up,
            self.replica_queue_depth,
            self.replica_draining,
            self.replica_fenced,
            self.breaker_state,
            self.fabric_advertised_roots,
        ):
            gauge.remove(replica=name)


class _Rolling:
    """Bounded rolling sample for the hedge threshold (TTFT p99): a
    deque of the last N observations, quantile by sort — N is small
    (256), so the sort is nanoseconds next to a network dial."""

    def __init__(self, capacity: int = 256):
        self._values: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            self._values.append(float(value))

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._values:
                return None
            ordered = sorted(self._values)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]


class _ReqTrace:
    """Per-request span bookkeeping threaded through the proxy paths.

    The root span id is reserved at arrival (recorded when the request
    resolves — the engine's cross-thread pattern); every upstream leg —
    first attempt, retry, hedge leg, failover resubmission — draws a
    DISTINCT (attempt index, span id) pair through :meth:`begin_attempt`
    (hedge legs run on spawned threads, hence the lock), and that pair
    rides the dial's ``X-Trace-Context`` header so the replica's span
    tree roots under exactly the leg that carried it."""

    __slots__ = ("rec", "trace_id", "root", "t0", "attrs", "_lock",
                 "n_attempts")

    # The router→replica dial is hop 1 of the request's journey
    # (client→router is hop 0 and needs no header: the router IS the
    # entry point).
    HOP = 1

    def __init__(self, rec: SpanRecorder, trace_id: str):
        self.rec = rec
        self.trace_id = trace_id
        self.root = rec.reserve_id()
        self.t0 = time.monotonic()
        self.attrs: dict = {}
        self._lock = threading.Lock()
        self.n_attempts = 0

    def begin_attempt(self) -> tuple[int, int]:
        """(attempt index, reserved span id) for one upstream leg."""
        with self._lock:
            idx = self.n_attempts
            self.n_attempts += 1
        return idx, self.rec.reserve_id()

    def header(self, span_id: int, attempt: int) -> str:
        return format_trace_context(
            self.trace_id, span_id, self.HOP, attempt
        )

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


class _StreamCtl:
    """One live proxied stream's migration handle (rid-keyed registry).

    ``migrate_to`` is written by :meth:`RouterServer.plan_migration`
    (under the streams lock) and read/cleared by the stream's own relay
    thread at token-event boundaries — plain attribute store/load
    (GIL-atomic); a one-event-stale read is by design.  ``replica`` /
    ``emitted`` are relay-thread-only bookkeeping the planner reads
    racily to rank candidates.  ``prefix_tokens`` is the prompt's
    leading affinity-horizon slice, immutable after registration — the
    fabric replicator's hot-prefix census groups live streams by it
    (the same content addressing the engines' arenas key on)."""

    __slots__ = ("rid", "prefix_key", "prefix_tokens", "replica",
                 "emitted", "migrate_to")

    def __init__(self, rid: str, prefix_key: int, prefix_tokens=()):
        self.rid = rid
        self.prefix_key = prefix_key
        self.prefix_tokens = tuple(prefix_tokens)
        self.replica = ""
        self.emitted = 0
        self.migrate_to: Optional[str] = None


class _Upstream:
    """One dialed upstream attempt: the connection (closable for
    cancel/cleanup) and its response."""

    __slots__ = ("name", "conn", "resp")

    def __init__(self, name, conn, resp):
        self.name = name
        self.conn = conn
        self.resp = resp

    def close(self) -> None:
        try:
            self.conn.close()
        except Exception:  # codelint: ignore[naked-except] best-effort close of a possibly-dead socket; per-close logs would drown failover
            pass


class RouterServer:
    """Threaded HTTP proxy over K serving replicas.  ``port=0`` picks a
    free port (tests); ``.port`` reports it.  ``replicas`` are
    ``"host:port"`` strings (also the ring node names and the `replica`
    metric label values)."""

    def __init__(
        self,
        replicas: list[str],
        host: str = "0.0.0.0",
        port: int = 8100,
        registry: Optional[MetricsRegistry] = None,
        flight=None,
        spans: Optional[SpanRecorder] = None,
        *,
        prefix_block_tokens: int = 16,
        prefix_max_blocks: int = 4,
        vnodes: int = 64,
        poll_interval_s: float = 1.0,
        poll_timeout_s: float = 2.0,
        overflow_depth: int = 4,
        breaker_failures: int = 3,
        breaker_open_s: float = 5.0,
        retry_budget: float = 32.0,
        retry_refill_per_s: float = 2.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        hedge: bool = True,
        hedge_min_s: float = 0.25,
        max_failovers: int = 3,
        request_timeout_s: float = 600.0,
        upstream_timeout_s: float = 30.0,
        policy_mode: str = "affinity",
        seed: int = 0,
        replicas_dns: Optional[str] = None,
        racecheck: bool = False,
        migrate: bool = False,
        migration: Optional[MigrationConfig] = None,
        migration_burst_gap_s: float = 0.005,
        disagg: bool = False,
        disagg_config: Optional[DisaggConfig] = None,
        prefill_replicas: Optional[list[str]] = None,
        slo: bool = False,
        canary: bool = False,
        canary_config: Optional[CanaryConfig] = None,
        fabric: bool = False,
        fabric_config: Optional[FabricConfig] = None,
        postmortem: bool = False,
        postmortem_dir: Optional[str] = None,
        postmortem_plugin_url: Optional[str] = None,
        postmortem_controller_url: Optional[str] = None,
        postmortem_debounce_s: float = 120.0,
        postmortem_budget_bytes: Optional[int] = None,
        postmortem_admin: bool = True,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.metrics = RouterMetrics(self.registry)
        self.flight = flight
        # Router-side request spans (utils/spans.py, always on — the
        # recorder is a lock + deque append per span): route selection,
        # per-attempt dial/TTFB, SSE relay, failover.  Served at
        # GET /debug/spans and embedded in SIGUSR2/atexit flight dumps;
        # tools/trace_assemble.py joins these against the replicas'
        # rings into one fleet timeline per request.
        self.spans = spans if spans is not None else SpanRecorder(
            capacity=2048, name="router"
        )
        # Ring/replica-set membership AND the license to touch replica
        # poll state off the poll thread (see _poll_guard below).
        # Reentrant so OwnerGuard's _is_owned introspection works.
        self._lock = threading.RLock()
        # Poll-state owner discipline (utils/racecheck.py): the poll
        # thread owns ReplicaState's poll-derived fields (reachable /
        # queue_depth / active_slots / draining / fenced / last_poll —
        # annotated `guarded by: owner-thread` in policy.py) off-lock;
        # request/stream threads marking a replica draining or fenced on
        # the failover path must hold self._lock, which serializes them
        # against the owner without stealing ownership
        # (steal_on_lock=False — a transient request thread becoming
        # owner would false-trip the long-lived poll loop).  Opt-in like
        # the engine's racecheck: the contract is free in production,
        # CHECKED in the suites that run with racecheck=True.
        self._poll_guard = None
        if racecheck:
            from ..utils.racecheck import OwnerGuard

            self._poll_guard = OwnerGuard(
                lock=self._lock, name="replica_poll", steal_on_lock=False
            )
        self._stop = threading.Event()
        self._first_poll = threading.Event()
        self._draining = threading.Event()
        self.drained = threading.Event()
        self._active = 0  # in-flight client requests (drain watches this)
        self._active_lock = threading.Lock()
        self.ring = HashRing(vnodes=vnodes)
        self.replicas: dict[str, ReplicaState] = {}
        self.budget = RetryBudget(retry_budget, retry_refill_per_s)
        self._backoff_base = backoff_base_s
        self._backoff_max = backoff_max_s
        self._hedge = hedge
        self._hedge_min_s = hedge_min_s
        self._max_failovers = max_failovers
        self._timeout = request_timeout_s
        self._upstream_timeout = upstream_timeout_s
        self._poll_interval = poll_interval_s
        self._poll_timeout = poll_timeout_s
        self._breaker_failures = breaker_failures
        self._breaker_open_s = breaker_open_s
        self._ttft_rolling = _Rolling()
        self._rng = random.Random(seed)
        self._dns = replicas_dns
        # Proactive planned migration (router/migration.py; library
        # default OFF like the engine's overload controller — the CLI
        # arms it).  The planner runs on the poll thread; live streams
        # register a _StreamCtl here so a plan can flag them to move.
        self.planner = (
            MigrationPlanner(migration) if migrate else None
        )
        self._migration_burst_gap = migration_burst_gap_s
        self._streams: dict[str, _StreamCtl] = {}  # guarded by: _streams_lock
        self._streams_lock = threading.Lock()
        self.policy = RoutingPolicy(
            self.ring,
            self.replicas,
            overflow_depth=overflow_depth,
            prefix_block_tokens=prefix_block_tokens,
            prefix_max_blocks=prefix_max_blocks,
            mode=policy_mode,
            seed=seed,
        )
        # Fleet SLO plane (utils/slo.py; library default OFF like
        # migration — the CLI arms it).  Every summary poll carries each
        # replica's cumulative per-objective [good, total] SLI counters;
        # the poll thread deltas them into this fleet-level tracker and
        # evaluates the multi-window burn-rate rules once per sweep.
        # Alert transitions fan out three ways: slo.burn_alert flight
        # events, direct incidents (the AnomalyMonitor below — the
        # router's first; served at nothing yet, rides flight dumps and
        # the on_incident log), and tpu_slo_burn_rate gauges.  Owner
        # discipline: the tracker and the per-replica baselines are poll
        # state, mutated only on the poll thread.
        self.slo = SLOTracker() if slo else None
        self.slo_anomaly = None
        self.canary_anomaly = None
        if slo:
            from ..utils.anomaly import AnomalyMonitor

            self.slo_anomaly = AnomalyMonitor(flight=flight)
        # Disaggregated prefill/decode split (router/disagg.py; library
        # default OFF like migration — the CLI arms it).  Roles are
        # discovered from each replica's summary poll; --prefill-replicas
        # names replicas that are prefill-role from the start (they are
        # polled like any other but never join the /generate ring).
        self.disagg = (
            DisaggPolicy(disagg_config) if disagg else None
        )
        # Fleet KV fabric (router/fabric.py; library default OFF like
        # migration/disagg — the CLI arms it).  The locator holds each
        # replica's bloom digest off the summary poll; the replicator
        # is poll-thread-owned planning state (MigrationPlanner
        # discipline).  Resolution counters are racy plain ints, the
        # dispatches/failures idiom.
        self.fabric_cfg = fabric_config or FabricConfig()
        self.fabric = (
            FabricLocator(self.fabric_cfg.default_page_size)
            if fabric
            else None
        )
        self.replicator = FabricReplicator(self.fabric_cfg) if fabric else None
        self._fabric_inflight: set = set()  # guarded by: _lock
        self._fabric_resolutions = 0
        self._fabric_hits = 0
        # Statically configured prefill replicas survive DNS
        # reconciliation (they are not in the headless Service's
        # records).
        self._static_prefill = set(prefill_replicas or ())
        for name in replicas:
            self.add_replica(name)
        for name in self._static_prefill:
            self.add_replica(name, role=ROLE_PREFILL)

        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                post_path = self.path.split("?")[0]
                if post_path == "/debug/postmortem/capture":
                    # Admin-gated manual capture: an operator forcing a
                    # fleet bundle NOW (synchronous, no debounce) — the
                    # "grab everything before I restart it" button.
                    if server.postmortem is None:
                        self._reply(
                            404,
                            {"error": "postmortem collector off "
                             "(--postmortem)"},
                        )
                        return
                    if not server.postmortem.admin:
                        self._reply(
                            403,
                            {"error": "postmortem admin capture "
                             "disabled (--postmortem-admin)"},
                        )
                        return
                    try:
                        length = int(
                            self.headers.get("Content-Length", "0")
                        )
                        body = json.loads(
                            self.rfile.read(length) or b"{}"
                        )
                    except ValueError:
                        body = {}
                    incident_id = str(
                        body.get("incident_id") or "manual"
                    )
                    bundle = server.postmortem.capture_now(
                        incident_id, trigger="manual"
                    )
                    self._reply(
                        200,
                        {
                            "captured": bundle is not None,
                            "bundle": bundle,
                            "error": (
                                None
                                if bundle is not None
                                else server.postmortem.last_error
                            ),
                        },
                    )
                    return
                if post_path != "/generate":
                    self.send_error(404)
                    return
                trace_id = sanitize_trace_id(self.headers.get("X-Request-Id"))
                if server._draining.is_set():
                    self._reply(
                        503,
                        {"error": "router is draining", "trace_id": trace_id},
                        trace_id,
                        retry_after="1",
                    )
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    prompt = list(body["prompt"])
                    if not prompt:
                        raise ValueError("empty prompt")
                    # Overload contract: the client's deadline/priority/
                    # tenant arrive as headers or body fields; the
                    # router folds headers INTO the body (replicas read
                    # both, but the re-stamped deadline always rides the
                    # header — see _dial).
                    raw_deadline = self.headers.get("X-Request-Deadline")
                    if raw_deadline is None:
                        raw_deadline = body.pop("deadline_s", None)
                    else:
                        body.pop("deadline_s", None)
                    deadline_s = (
                        None if raw_deadline is None else float(raw_deadline)
                    )
                    priority = self.headers.get("X-Request-Priority")
                    if priority is not None:
                        body["priority"] = priority
                    tenant = self.headers.get("X-Tenant-Id")
                    if tenant is not None:
                        body["tenant"] = tenant
                except (KeyError, TypeError, ValueError) as e:
                    server.metrics.requests.inc(outcome="rejected")
                    self._reply(
                        400, {"error": f"bad request: {e}"}, trace_id
                    )
                    return
                if deadline_s is not None and deadline_s <= 0:
                    # Fail fast, never dial: a spent deadline cannot be
                    # served by ANY replica — 504 without spending a
                    # connection, a retry token, or a queue entry.
                    server.metrics.requests.inc(outcome="deadline")
                    server._record(
                        "router.deadline_exceeded",
                        where="arrival",
                        deadline_s=deadline_s,
                    )
                    self._reply(
                        504,
                        {
                            "error": "deadline expired before routing",
                            "trace_id": trace_id,
                        },
                        trace_id,
                    )
                    return
                # Disaggregation verdict (router/disagg.py): a long
                # prompt gets a prefill-pool locator stamped on every
                # upstream dial (failover legs included — the next
                # decode replica can pull the same prefix); everything
                # else rides the unified path byte-for-byte.
                handoff_source = None
                if server.disagg is not None:
                    verdict, handoff_source = server._classify_disagg(
                        prompt
                    )
                    server.metrics.disagg_splits.inc(verdict=verdict)
                    if verdict == SPLIT:
                        server._record(
                            "router.disagg_split",
                            rid=trace_id,
                            source=handoff_source,
                            prompt_tokens=len(prompt),
                        )
                with server._active_lock:
                    server._active += 1
                # Root span reserved NOW; attempt legs parent on it and
                # the finally records it with the request's outcome —
                # the router half of the fleet timeline.
                tr = _ReqTrace(server.spans, trace_id)
                tr.set(stream=bool(body.get("stream")))
                if handoff_source is not None:
                    tr.set(handoff_source=handoff_source)
                try:
                    if body.get("stream"):
                        server._proxy_stream(
                            self, body, prompt, trace_id, deadline_s, tr,
                            handoff=handoff_source,
                        )
                    else:
                        server._proxy_unary(
                            self, body, prompt, trace_id, deadline_s, tr,
                            handoff=handoff_source,
                        )
                finally:
                    with server._active_lock:
                        server._active -= 1
                    tr.set(attempts=tr.n_attempts)
                    server.spans.record_span(
                        "router.request",
                        trace_id,
                        start_monotonic=tr.t0,
                        span_id=tr.root,
                        attrs=tr.attrs,
                    )

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0]
                if path == "/healthz":
                    if server._draining.is_set():
                        self._reply(503, {"status": "draining"})
                        return
                    reachable = sum(
                        1 for s in server.replicas.values() if s.reachable
                    )
                    ok = reachable > 0 and not server._stop.is_set()
                    self._reply(
                        200 if ok else 503,
                        {
                            "status": "ok" if ok else "no reachable replicas",
                            "replicas": len(server.replicas),
                            "reachable": reachable,
                        },
                    )
                elif path == "/metrics":
                    server.metrics.retry_budget.set(server.budget.available())
                    write_exposition(self, server.registry)
                elif path == "/debug/router":
                    self._reply(200, server.snapshot())
                elif path == "/debug/fleet":
                    # Elastic-fleet surface: per-replica host-side
                    # signals, migration planner state, and the
                    # scale-up/down recommendation (tools/fleet_plan.py
                    # renders this; a warm-joining replica reads the
                    # membership keys to pick its snapshot donor).
                    self._reply(200, server.fleet_state())
                elif path == "/debug/slo":
                    # Fleet SLO view (utils/slo.py): the burn rates and
                    # error budgets over the poll-merged SLI deltas,
                    # plus each replica's own cumulative counters — a
                    # single-replica fleet's totals here match that
                    # replica's /debug/slo exactly.
                    self._reply(200, server.slo_state())
                elif path == "/debug/fabric":
                    # Fleet KV fabric (router/fabric.py): per-replica
                    # digest views, locator resolution counters, and
                    # the replication ledger.
                    self._reply(200, server.fabric_state())
                elif path == "/debug/canary":
                    # Active correctness plane (router/prober.py):
                    # per-replica probe verdicts, mismatch streaks,
                    # captured oracles, and fences fired.
                    if server.prober is None:
                        self._reply(
                            404, {"error": "canary prober off (--canary)"}
                        )
                    else:
                        self._reply(200, server.prober.snapshot())
                elif path == "/debug/postmortem":
                    # Fleet postmortem collector (router/postmortem.py):
                    # capture/skip counters and the bundle ledger —
                    # where an operator finds what evidence exists for
                    # tools/postmortem.py to classify.
                    if server.postmortem is None:
                        self._reply(
                            404,
                            {"error": "postmortem collector off "
                             "(--postmortem)"},
                        )
                    else:
                        self._reply(200, server.postmortem.snapshot())
                elif path == "/debug/spans":
                    # ?rid=<trace id>: one request's tree only — the
                    # trace assembler's live mode pulls per-request,
                    # not whole rings.
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query
                    )
                    rid = (query.get("rid") or [None])[0]
                    self._reply(200, server.spans.dump(trace_id=rid))
                else:
                    self.send_error(404)

            def _reply(
                self,
                code: int,
                obj: dict,
                trace_id: Optional[str] = None,
                retry_after: Optional[str] = None,
            ) -> None:
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if trace_id:
                    self.send_header("X-Request-Id", trace_id)
                if retry_after:
                    self.send_header("Retry-After", retry_after)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                try:
                    self.wfile.write(payload)
                except OSError:
                    pass  # client vanished; nothing upstream to cancel

            def log_message(self, *args):  # quiet under load
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._http_thread: Optional[threading.Thread] = None
        self._poll_thread: Optional[threading.Thread] = None
        # Active correctness plane (router/prober.py; library default
        # OFF like migration/slo — the CLI arms it).  Built after the
        # HTTP server so the through-router probes can dial our own
        # bound port.  The prober runs its own thread and never touches
        # poll state: it reads each replica's summary itself and acts
        # only through the replica's public /debug/fence endpoint — the
        # poll loop then notices fenced=true and demotes normally.
        self.prober: Optional[CanaryProber] = None
        if canary:
            from ..utils.anomaly import AnomalyMonitor

            self.canary_anomaly = AnomalyMonitor(flight=flight)
            self.prober = CanaryProber(
                lambda: list(self.replicas.keys()),
                config=canary_config,
                router_url=f"127.0.0.1:{self.port}",
                metrics=self.metrics,
                flight=flight,
                anomaly=self.canary_anomaly,
            )
        # Fleet postmortem collector (router/postmortem.py; library
        # default OFF like migration/canary — the CLI arms it).  Two
        # trigger paths: the summary poll's incidents_total cursor
        # (any replica's incident), and the router's OWN monitors (SLO
        # burn alerts, canary mismatches) via the full-record listener
        # seam.  Capture runs on its own worker thread — never the poll
        # thread.
        self.postmortem = None
        if postmortem:
            from ..utils.flight import default_dump_dir
            from .postmortem import FleetPostmortem

            directory = (
                postmortem_dir
                or default_dump_dir()
                or tempfile.gettempdir()
            )

            def _local_state():
                return {
                    "component": "router",
                    "flight": (
                        self.flight.snapshot()
                        if self.flight is not None
                        else None
                    ),
                    "spans": self.spans.dump(),
                    "state": self.snapshot(),
                    "metrics": self.registry.render(),
                }

            self.postmortem = FleetPostmortem(
                directory,
                lambda: list(self.replicas.keys()),
                local_fn=_local_state,
                plugin_url=postmortem_plugin_url,
                controller_url=postmortem_controller_url,
                flight=flight,
                registry=self.registry,
                debounce_s=postmortem_debounce_s,
                budget_bytes=postmortem_budget_bytes,
                admin=postmortem_admin,
            )
            for monitor in (self.slo_anomaly, self.canary_anomaly):
                if monitor is not None:
                    monitor.add_listener(self.postmortem.on_incident)

    # ------------------------------------------------------- membership

    def add_replica(self, name: str, role: str = "unified") -> None:
        """Add one ``host:port`` replica to the replica set — and, for
        decode-capable roles, the affinity ring (idempotent).
        Consistent hashing keeps existing placements for all but ~1/K
        of the keyspace.  Prefill-role replicas are polled and
        breaker-tracked like any other but never own ring segments:
        they serve ``POST /v1/prefill`` pulls, not ``/generate``."""
        with self._lock:
            if name in self.replicas:
                return
            breaker = CircuitBreaker(
                failure_threshold=self._breaker_failures,
                open_s=self._breaker_open_s,
                on_transition=lambda old, new, n=name: self._on_breaker(
                    n, old, new
                ),
            )
            st = ReplicaState(name, breaker)
            st.role = role
            self.replicas[name] = st
            if role != ROLE_PREFILL:
                self.ring.add(name)
        self.metrics.replica_up.set(1, replica=name)
        self.metrics.replica_fenced.set(0, replica=name)
        self.metrics.breaker_state.set(STATE_VALUE["closed"], replica=name)
        self._record("router.replica_added", replica=name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            if name not in self.replicas:
                return
            self.ring.remove(name)
            del self.replicas[name]
        if self.planner is not None:
            self.planner.forget(name)
        if self.fabric is not None:
            self.fabric.forget(name)
            self.replicator.forget(name)
        self.metrics.drop_replica(name)
        self._record("router.replica_removed", replica=name)

    # ----------------------------------------------------------- wiring

    def _record(self, kind: str, **fields) -> None:
        if self.flight is not None:
            self.flight.record(kind, **fields)

    def _on_breaker(self, name: str, old: str, new: str) -> None:
        self.metrics.breaker_transitions.inc(state=new)
        self.metrics.breaker_state.set(STATE_VALUE[new], replica=name)
        self._record(f"router.breaker_{new}", replica=name, previous=old)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -------------------------------------------------------- poll loop

    def _poll_once(self) -> None:
        if self._poll_guard is not None:
            # Poll state is owner-thread-only: the first off-lock caller
            # (the poll thread) owns it; any other thread polling
            # off-lock is a contract violation racecheck raises on.
            self._poll_guard.check("poll_once")
        for name, st in list(self.replicas.items()):
            if self._stop.is_set():
                return
            try:
                with self.metrics.poll_seconds.time():
                    conn = http.client.HTTPConnection(
                        st.host, st.port, timeout=self._poll_timeout
                    )
                    try:
                        conn.request("GET", "/debug/state?summary=1")
                        resp = conn.getresponse()
                        payload = json.loads(resp.read() or b"{}")
                        if resp.status != 200:
                            raise OSError(f"summary poll HTTP {resp.status}")
                    finally:
                        conn.close()
            except (*_CONN_ERRORS, ValueError) as e:
                if st.reachable:
                    st.reachable = False
                    self.metrics.replica_up.set(0, replica=name)
                    self._record(
                        "router.replica_down", replica=name, error=str(e)
                    )
                continue
            if not st.reachable:
                st.reachable = True
                self.metrics.replica_up.set(1, replica=name)
                self._record("router.replica_up", replica=name)
            role = str(payload.get("role") or "unified")
            if role != st.role:
                self._set_role(name, role)
            st.queue_depth = int(payload.get("queue_depth", 0))
            st.active_slots = int(payload.get("active_slots", 0))
            # Host-side overload signals (queue-wait EWMA + drain-rate
            # forecast): what the migration planner and /debug/fleet
            # scale signal read.  Absent on pre-overload replicas.
            raw_wait = payload.get("queue_wait_ewma_s")
            st.queue_wait_ewma_s = (
                float(raw_wait) if raw_wait is not None else None
            )
            raw_drain = payload.get("drain_rate_rps")
            st.drain_rate_rps = (
                float(raw_drain) if raw_drain is not None else None
            )
            # Replica process uptime (replica-minutes accounting for the
            # fleet controller).  Absent on replicas predating the field.
            raw_uptime = payload.get("uptime_s")
            st.uptime_s = (
                float(raw_uptime) if raw_uptime is not None else None
            )
            # Anomaly-incident cursor (fleet postmortem trigger): an
            # advance since the LAST poll means the replica just
            # emitted an incident — capture its forensic state before
            # the rings roll.  The first observation only seeds the
            # cursor (a router joining a fleet with historical
            # incidents must not back-fire on the backlog).
            raw_incidents = payload.get("incidents_total")
            if raw_incidents is not None:
                try:
                    incidents = int(raw_incidents)
                except (TypeError, ValueError):
                    incidents = None
                if incidents is not None:
                    previous = st.incidents_total
                    st.incidents_total = incidents
                    if (
                        self.postmortem is not None
                        and previous is not None
                        and incidents > previous
                    ):
                        self.postmortem.observe_poll(name, incidents)
            draining = bool(payload.get("draining", False))
            if draining != st.draining:
                self._mark_draining(name, draining)
            fenced = bool(payload.get("fenced", False))
            if fenced != st.fenced:
                self._mark_fenced(name, fenced)
            self._merge_slo(st, payload.get("slo"))
            if self.fabric is not None:
                # Fabric digest off the same poll (fleet KV fabric,
                # router/fabric.py): an absent or unparseable digest
                # clears the replica's view — the locator never places
                # on stale advertisements after a restart.
                self.metrics.fabric_advertised_roots.set(
                    self.fabric.update(name, payload.get("fabric_digest")),
                    replica=name,
                )
            st.last_poll = time.monotonic()
            self.metrics.replica_queue_depth.set(
                st.queue_depth, replica=name
            )
        # Proactive migration rides the poll cadence: feed the planner
        # this sweep's signals, then execute at most one plan verdict.
        self._maybe_plan_migrations()
        # The fleet burn-rate rules ride the same cadence: one
        # evaluation per sweep over the freshly merged SLI deltas.
        self._evaluate_slo()
        # K-replica hot-prefix replication rides the same cadence too:
        # host-side pressure signals + the live-stream census, bounded
        # actions per sweep — never device counters.
        self._fabric_tick()

    def _merge_slo(self, st, slo_block) -> None:
        """Delta one replica's cumulative SLI counters into the fleet
        tracker (poll thread only — the tracker is poll state).  A
        counter that SHRANK means the replica restarted: its fresh
        totals ARE the delta (the new process's events), so a restart
        re-baselines without inventing negative traffic."""
        if self.slo is None or not slo_block:
            return
        totals = slo_block.get("objectives")
        if not isinstance(totals, dict):
            return
        previous = st.slo_totals or {}
        clean: dict = {}
        for objective, pair in totals.items():
            try:
                good, total = int(pair[0]), int(pair[1])
            except (TypeError, ValueError, IndexError):
                continue
            clean[objective] = [good, total]
            prev_good, prev_total = previous.get(objective, (0, 0))
            d_good, d_total = good - prev_good, total - prev_total
            if d_good < 0 or d_total < 0:
                d_good, d_total = good, total
            self.slo.ingest(objective, d_good, d_total)
        st.slo_totals = clean

    def _evaluate_slo(self) -> None:
        """Per-sweep burn-rate evaluation (poll thread only): refresh
        the tpu_slo_burn_rate gauges and fan out every alert
        transition — slo.burn_alert flight event, a direct incident on
        fire (page/ticket severity rides both), and the fired counter."""
        if self.slo is None:
            return
        for objective in self.slo.objectives:
            for wname, wsec in self.slo.windows.items():
                self.metrics.slo_burn_rate.set(
                    round(self.slo.burn_rate(objective, wsec), 4),
                    objective=objective,
                    window=wname,
                )
        for transition in self.slo.evaluate():
            self._record("slo.burn_alert", **transition)
            if transition["state"] == "fired":
                self.metrics.slo_burn_alerts.inc(
                    objective=transition["objective"],
                    severity=transition["severity"],
                )
                if self.slo_anomaly is not None:
                    self.slo_anomaly.report(
                        "slo.burn_rate",
                        observed=max(
                            transition["burn_rates"].values(), default=0.0
                        ),
                        objective=transition["objective"],
                        rule=transition["rule"],
                        severity=transition["severity"],
                    )

    def _mark_draining(self, name: str, draining: bool) -> None:
        st = self.replicas.get(name)
        if st is None:
            return
        # Called from the poll thread (summary says draining) AND from
        # request/stream threads (a 503 without X-Shed): the lock is the
        # cross-thread license to touch poll state — the OwnerGuard
        # contract's "other side" (see __init__).  Instruments fire
        # outside the lock: leaf locks only ever nest under this one.
        with self._lock:
            if self._poll_guard is not None:
                self._poll_guard.check("mark_draining")
            if st.draining == draining:
                return
            st.draining = draining
        self.metrics.replica_draining.set(1 if draining else 0, replica=name)
        self._record(
            "router.drain_begin" if draining else "router.drain_end",
            replica=name,
        )

    def _mark_fenced(self, name: str, fenced: bool) -> None:
        """A replica self-fenced (hung-step watchdog, chip-health feed,
        or operator POST /debug/fence): demote it exactly like a
        draining one — no new assignments; its cut streams fail over
        through the ordinary zero-drop path — until the summary clears."""
        st = self.replicas.get(name)
        if st is None:
            return
        with self._lock:  # same cross-thread license as _mark_draining
            if self._poll_guard is not None:
                self._poll_guard.check("mark_fenced")
            if st.fenced == fenced:
                return
            st.fenced = fenced
        self.metrics.replica_fenced.set(1 if fenced else 0, replica=name)
        self._record(
            "router.replica_fenced" if fenced else "router.replica_unfenced",
            replica=name,
        )

    def _set_role(self, name: str, role: str) -> None:
        """A replica's summary poll reported a different role (a
        redeploy flipped --role): reconcile ring membership — prefill
        replicas own no ring segments; a replica becoming
        decode-capable joins the ring (~1/K remap, like any membership
        change)."""
        st = self.replicas.get(name)
        if st is None:
            return
        with self._lock:  # same cross-thread license as _mark_draining
            if self._poll_guard is not None:
                self._poll_guard.check("set_role")
            if st.role == role:
                return
            st.role = role
            if role == ROLE_PREFILL:
                self.ring.remove(name)
            else:
                self.ring.add(name)
        self._record("router.replica_role", replica=name, role=role)

    def _refresh_dns(self) -> None:
        """Re-resolve ``--replicas-dns`` (a headless Service name) and
        reconcile ring membership — replicas scale without a router
        restart, and consistent hashing keeps warm prefixes where they
        are for the survivors."""
        if not self._dns:
            return
        host, _, port = self._dns.rpartition(":")
        try:
            infos = socket.getaddrinfo(
                host, int(port), socket.AF_INET, socket.SOCK_STREAM
            )
        except OSError as e:
            self._record("router.dns_error", target=self._dns, error=str(e))
            return
        resolved = {f"{info[4][0]}:{info[4][1]}" for info in infos}
        if not resolved:
            return
        current = set(self.replicas)
        for name in resolved - current:
            self.add_replica(name)
        for name in current - resolved - self._static_prefill:
            self.remove_replica(name)

    def _poll_loop(self) -> None:
        # The FIRST poll runs here too (not in start()): the poll thread
        # is the single off-lock owner of replica poll state, and
        # start() blocks on _first_poll instead — same no-cold-blind-
        # spot contract, one owner thread.
        self._poll_once()
        self._first_poll.set()
        while not self._stop.wait(self._poll_interval):
            self._refresh_dns()
            self._poll_once()

    # ------------------------------------------------- planned migration

    def _maybe_plan_migrations(self) -> None:
        """Poll-thread tick: feed this sweep's host-side signals to the
        planner and execute at most one plan verdict (flag streams; the
        relay threads perform the actual zero-drop moves at their next
        paced token boundary)."""
        planner = self.planner
        if planner is None:
            return
        for name, st in list(self.replicas.items()):
            planner.observe(
                name,
                wait_ewma_s=st.queue_wait_ewma_s,
                drain_rate_rps=st.drain_rate_rps,
                queue_depth=st.queue_depth,
                eligible=(
                    st.reachable
                    and not st.draining
                    and not st.fenced
                    and st.role != ROLE_PREFILL
                ),
            )
        verdict = planner.plan()
        if verdict is None:
            return
        source, target, n_moves = verdict
        self.plan_migration(source, target=target, max_moves=n_moves)

    def plan_migration(
        self,
        replica: str,
        target: Optional[str] = None,
        max_moves: int = 1,
    ) -> int:
        """Plan moves of the hottest prefix-block sessions off
        ``replica``: flag up to ``max_moves`` live streams to resubmit
        (prompt + emitted tokens, same rid — the PR 8 failover shape,
        but PLANNED) onto ``target`` (default: the coldest eligible
        peer).  Hotness ranks by live streams sharing the prefix key
        (the shard the KV tiers are sweating for), then by emitted
        length.  Returns how many streams were flagged; the relay
        threads execute at their next paced token boundary and abort if
        the target's breaker refuses."""
        if target is None:
            target = self._coldest_peer(replica)
        if target is None or target == replica:
            return 0
        with self._streams_lock:
            cands = [
                c
                for c in self._streams.values()
                if c.replica == replica and c.migrate_to is None
            ]
            by_key: dict[int, int] = {}
            for c in cands:
                by_key[c.prefix_key] = by_key.get(c.prefix_key, 0) + 1
            cands.sort(
                key=lambda c: (-by_key[c.prefix_key], -c.emitted, c.rid)
            )
            flagged = cands[: max(0, int(max_moves))]
            for c in flagged:
                c.migrate_to = target
        # Instruments OUTSIDE the streams lock (leaf-lock discipline).
        for c in flagged:
            self.metrics.migrations.inc(outcome="planned")
            self._record(
                "router.migration_planned",
                rid=c.rid,
                replica=replica,
                target=target,
                emitted=c.emitted,
            )
        return len(flagged)

    def _coldest_peer(self, source: str) -> Optional[str]:
        """The least-pressured routable replica other than ``source``
        (the default migration target when the caller names none)."""
        best: Optional[tuple[float, str]] = None
        for name, st in self.replicas.items():
            if (
                name == source
                or not st.reachable
                or st.draining
                or st.fenced
                or st.role == ROLE_PREFILL
            ):
                continue
            pressure = replica_pressure(
                st.queue_wait_ewma_s, st.drain_rate_rps, st.queue_depth
            )
            if best is None or (pressure, name) < best:
                best = (pressure, name)
        return best[1] if best is not None else None

    def _acquire_migration_target(self, target: str) -> bool:
        """Planned-move admission: the target must be routable RIGHT
        NOW and its breaker must grant the dial — a migration aborts
        rather than dogpile a tripping or demoted target."""
        st = self.replicas.get(target)
        if (
            st is None
            or st.draining
            or st.fenced
            or not st.reachable
            or st.role == ROLE_PREFILL
        ):
            return False
        return st.breaker.try_acquire()

    def _migration_aborted(self, rid: str, target: str, reason: str) -> None:
        self.metrics.migrations.inc(outcome="aborted")
        self._record(
            "router.migration_aborted", rid=rid, target=target, reason=reason
        )

    def _classify_disagg(
        self, prompt
    ) -> tuple[str, Optional[str]]:
        """(verdict, prefill source): classify one request against the
        split policy (prompt length × decode-pool pressure) and pick
        the least-pressured healthy prefill replica as its
        ``X-Handoff-Source`` locator.  ``no_pool`` (no healthy prefill
        replica) degrades to unified dispatch — the caller stamps
        nothing."""
        prefills: dict[str, float] = {}
        decode_pressure = 0.0
        for name, st in list(self.replicas.items()):
            if not st.reachable or st.draining or st.fenced:
                continue
            pressure = replica_pressure(
                st.queue_wait_ewma_s, st.drain_rate_rps, st.queue_depth
            )
            if st.role == ROLE_PREFILL:
                prefills[name] = pressure
            else:
                decode_pressure = max(decode_pressure, pressure)
        verdict = self.disagg.classify(
            len(prompt), decode_pressure, bool(prefills)
        )
        if verdict != SPLIT:
            # Short prompt or no healthy prefill pool: the LOCAL
            # sentinel tells a decode-role replica to run its own
            # prefill instead of refusing — the unified degradation
            # (a unified replica ignores the header entirely).
            return verdict, HANDOFF_LOCAL
        return verdict, pick_prefill(prefills) or HANDOFF_LOCAL

    def _prefill_needed(self, name: str, trace_id: str, missing) -> None:
        """One decode replica answered 409 + X-Prefill-Needed: the
        prompt's prefix is neither resident nor fetchable there.  Not a
        fault (no breaker hit) — skip the replica and keep walking the
        ring (a unified replica serves it; with --disagg the locator
        normally prevents this entirely)."""
        self.metrics.disagg_refusals.inc()
        self._record(
            "router.prefill_needed",
            replica=name,
            rid=trace_id,
            missing_pages=missing,
        )

    # ------------------------------------------------------- fleet fabric

    def _fabric_source_for(
        self, target: str, payload: dict
    ) -> Optional[str]:
        """Per-dial locator resolution (fleet KV fabric): the best
        owner of this prompt's deepest advertised cumulative prefix,
        or None when the TARGET already advertises it (or nobody
        does).  Called immediately before every upstream dial —
        primary, retry, hedge, failover and migration legs alike — so
        a re-dialed leg re-resolves against CURRENT membership and
        can never be pointed at a dead, fenced, or draining peer."""
        if self.fabric is None:
            return None
        prompt = payload.get("prompt")
        if not prompt:
            return None
        self._fabric_resolutions += 1
        if payload.get("adapter"):
            # Adapter trie roots are engine-local indices the router
            # cannot address; adapter traffic rides affinity + the
            # classic prefill-pool path unchanged.
            self.metrics.fabric_resolutions.inc(verdict=VERDICT_SKIP)
            return None
        resident = self.fabric.coverage(target, prompt)
        candidates = [
            name
            for name, st in list(self.replicas.items())
            if name != target
            and st.reachable
            and not st.draining
            and not st.fenced
        ]
        best = self.fabric.best_owner(prompt, candidates)
        if best is None or best[1] <= resident:
            self.metrics.fabric_resolutions.inc(
                verdict=VERDICT_RESIDENT if resident else VERDICT_MISS
            )
            return None
        owner, covered = best
        self._fabric_hits += 1
        self.metrics.fabric_resolutions.inc(verdict=VERDICT_HIT)
        self._record(
            "router.fabric_locate",
            target=target,
            source=owner,
            covered_tokens=covered,
            prompt_tokens=len(prompt),
        )
        return owner

    def _fabric_tick(self) -> None:
        """Poll-thread sweep: census the live streams' prefixes, feed
        the replicator the fleet's pressure signals, and fire its
        bounded pull/drop verdicts at the engines off-thread."""
        if self.replicator is None:
            return
        with self._streams_lock:
            hot: dict[tuple, int] = {}
            for c in self._streams.values():
                if c.prefix_tokens:
                    hot[c.prefix_tokens] = hot.get(c.prefix_tokens, 0) + 1
        pressures = {
            name: replica_pressure(
                st.queue_wait_ewma_s, st.drain_rate_rps, st.queue_depth
            )
            for name, st in list(self.replicas.items())
            if st.reachable
            and not st.draining
            and not st.fenced
            and st.role != ROLE_PREFILL
        }
        for action in self.replicator.plan(self.fabric, hot, pressures):
            self._fabric_execute(action)

    def _fabric_execute(self, action: dict) -> None:
        """Fire one replication verdict at its target engine on a
        worker thread (a pull streams the whole prefix over the
        handoff wire — the poll loop must not wait on it).  The
        in-flight set keeps one sweep's action from being re-fired
        while a slow transfer is still running."""
        target = action["target"]
        st = self.replicas.get(target)
        if st is None:
            return
        op = action["op"]
        key = (op, target, tuple(action["prompt"]))
        with self._lock:
            if key in self._fabric_inflight:
                return
            self._fabric_inflight.add(key)
        path = "/debug/fabric/pull" if op == "pull" else "/debug/fabric/drop"
        body: dict = {"prompt": action["prompt"]}
        if op == "pull":
            body["source"] = action["source"]

        def run():
            ok = False
            detail: dict = {}
            try:
                conn = http.client.HTTPConnection(
                    st.host, st.port, timeout=self.fabric_cfg.pull_timeout_s
                )
                try:
                    conn.request(
                        "POST",
                        path,
                        json.dumps(body).encode(),
                        {"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    detail = json.loads(resp.read() or b"{}")
                    ok = resp.status == 200 and bool(detail.get("ok"))
                finally:
                    conn.close()
            except (*_CONN_ERRORS, ValueError) as e:
                detail = {"error": str(e)}
            finally:
                with self._lock:
                    self._fabric_inflight.discard(key)
            outcome = "ok" if ok else "error"
            if op == "pull":
                self.metrics.fabric_replications.inc(outcome=outcome)
            else:
                self.metrics.fabric_drops.inc(outcome=outcome)
            self._record(
                "router.fabric_replicated" if op == "pull"
                else "router.fabric_dropped",
                target=target,
                source=action.get("source"),
                prompt_tokens=len(action["prompt"]),
                ok=ok,
                detail=detail.get("error") or detail.get("outcome"),
            )

        threading.Thread(
            target=run, name="router-fabric", daemon=True
        ).start()

    def fabric_state(self) -> dict:
        """GET /debug/fabric: digest views, locator counters, and the
        replication ledger."""
        if self.fabric is None:
            return {"enabled": False}
        resolutions = self._fabric_resolutions
        hits = self._fabric_hits
        return {
            "enabled": True,
            "replicas": self.fabric.snapshot(),
            "resolutions": resolutions,
            "cross_peer_hits": hits,
            "cross_peer_hit_rate": (
                round(hits / resolutions, 4) if resolutions else 0.0
            ),
            "replication": self.replicator.snapshot(),
        }

    def _fabric_summary(self) -> dict:
        """The /debug/fleet fabric block ``tools/fleet_plan.py``
        renders: per-replica advertised-root counts, the hottest live
        prefixes' current replication factors, and the cross-peer hit
        rate."""
        if self.fabric is None:
            return {"enabled": False}
        with self._streams_lock:
            hot: dict[tuple, int] = {}
            for c in self._streams.values():
                if c.prefix_tokens:
                    hot[c.prefix_tokens] = hot.get(c.prefix_tokens, 0) + 1
        names = list(self.replicas)
        ps = self.fabric.page_size()
        hottest = [
            {
                "prefix_tokens": len(prefix),
                "streams": count,
                "replication_factor": self.replicator.replication_factor(
                    self.fabric, prefix, names
                ),
            }
            for prefix, count in sorted(
                hot.items(),
                key=lambda item: (
                    -(item[1] * (len(item[0]) // ps)),
                    item[0],
                ),
            )[:5]
        ]
        resolutions = self._fabric_resolutions
        hits = self._fabric_hits
        return {
            "enabled": True,
            "advertised_roots": self.fabric.advertised_roots(),
            "hottest_prefixes": hottest,
            "cross_peer_hits": hits,
            "cross_peer_hit_rate": (
                round(hits / resolutions, 4) if resolutions else 0.0
            ),
        }

    def fleet_state(self) -> dict:
        """GET /debug/fleet: per-replica host-side signals, planner
        state, and the fleet scale recommendation — what
        ``tools/fleet_plan.py`` renders and an autoscaler would poll."""
        cfg = self.planner.cfg if self.planner is not None else MigrationConfig()
        now = time.monotonic()
        signals = {}
        for name, st in list(self.replicas.items()):
            eligible = (
                st.reachable
                and not st.draining
                and not st.fenced
                and st.role != ROLE_PREFILL
            )
            pressure = round(
                replica_pressure(
                    st.queue_wait_ewma_s,
                    st.drain_rate_rps,
                    st.queue_depth,
                ),
                4,
            )
            signals[name] = {
                "role": st.role,
                "pressure_s": pressure,
                "queue_depth": st.queue_depth,
                "active_slots": st.active_slots,
                "queue_wait_ewma_s": st.queue_wait_ewma_s,
                "drain_rate_rps": st.drain_rate_rps,
                "slo_totals": st.slo_totals,
                "eligible": eligible,
                "reachable": st.reachable,
                "draining": st.draining,
                "fenced": st.fenced,
                # Replica-minutes accounting (ISSUE 19): the replica's
                # self-reported process uptime, falling back to
                # age-since-registration for replicas predating the
                # summary field.
                "uptime_s": (
                    st.uptime_s
                    if st.uptime_s is not None
                    else round(now - st.first_seen, 3)
                ),
                # Per-replica scale_recommendation inputs, pre-judged
                # with the SAME thresholds the verdict below uses — a
                # controller decision (including over the prefill pool
                # the recommendation excludes) is explainable from this
                # one snapshot.
                "hot": pressure >= cfg.hot_wait_s,
                "cold": pressure <= cfg.cold_wait_s,
            }
        with self._streams_lock:
            active_streams = len(self._streams)
        return {
            "replicas": signals,
            "active_streams": active_streams,
            "migration": (
                self.planner.snapshot()
                if self.planner is not None
                else {"enabled": False}
            ),
            "recommendation": scale_recommendation(
                signals,
                hot_wait_s=cfg.hot_wait_s,
                cold_wait_s=cfg.cold_wait_s,
            ),
            # Compact fleet SLO view (the full version is /debug/slo):
            # burn rates + active alerts so fleet_plan.py — and, later,
            # ROADMAP #5's autoscaler — can act on budget burn, not
            # just queue pressure.
            "slo": self._fleet_slo_summary(),
            # Compact fleet KV fabric view (the full version is
            # /debug/fabric): advertised-root counts, hottest-prefix
            # replication factors, cross-peer hit rate.
            "fabric": self._fabric_summary(),
        }

    def _fleet_slo_summary(self) -> dict:
        if self.slo is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "burn_rates": {
                objective: {
                    wname: round(self.slo.burn_rate(objective, wsec), 3)
                    for wname, wsec in self.slo.windows.items()
                }
                for objective in self.slo.objectives
            },
            "budget_remaining": {
                objective: round(self.slo.budget_remaining(objective), 4)
                for objective in self.slo.objectives
            },
            "alerts": self.slo.active_alerts(),
        }

    def slo_state(self) -> dict:
        """GET /debug/slo: the fleet-merged tracker's full snapshot
        plus each replica's own cumulative SLI counters.  For a
        single-replica fleet the fleet totals equal that replica's own
        /debug/slo totals — the aggregation-correctness check the
        chaos suite pins."""
        if self.slo is None:
            return {"enabled": False}
        snap = self.slo.snapshot()
        snap["enabled"] = True
        snap["replicas"] = {
            name: st.slo_totals for name, st in list(self.replicas.items())
        }
        return snap

    # ------------------------------------------------------ dispatching

    def _per_request_s(self) -> float:
        """Router-measured mean request service time (from the
        request_seconds histogram operators already scrape) — the
        multiplier behind every queue-depth wait forecast.  0.0 until
        anything completed (forecasts then read as 'feasible')."""
        hist = self.metrics.request_seconds
        count = hist.count
        if not count:
            return 0.0
        return hist.snapshot()[2] / count

    def _deadline_infeasible(self, remaining_s: Optional[float]) -> bool:
        """True when even the emptiest non-draining replica's queue
        forecast exceeds the remaining deadline — the fail-fast (504,
        never enqueue) gate."""
        if remaining_s is None:
            return False
        if remaining_s <= 0:
            return True
        return (
            self.policy.min_wait_estimate_s(self._per_request_s())
            > remaining_s
        )

    def _dial(
        self,
        name: str,
        payload: dict,
        trace_id: str,
        stream: bool,
        deadline: Optional[float] = None,
        hop_header: Optional[str] = None,
        handoff: Optional[str] = None,
    ) -> _Upstream:
        """One upstream POST /generate.  Fires the per-replica
        ``router.replica_conn`` failpoint first (the chaos seam: an
        armed error here looks exactly like a dial failure).  When the
        request carries a deadline, the REMAINING budget is re-computed
        at dial time and stamped as ``X-Request-Deadline`` — each hop
        subtracts the time it already spent, so the replica's expiry
        sweep judges the same clock the client does.  ``hop_header``
        is this leg's ``X-Trace-Context`` (distinct per attempt) — the
        replica roots its span tree under it.  Raises
        ``_CONN_ERRORS`` / ``FailpointError`` on transport failure."""
        failpoints.fire_scoped(FAILPOINT_CONN, name, replica=name)
        st = self.replicas[name]
        body = dict(payload)
        body["stream"] = stream
        headers = {
            "Content-Type": "application/json",
            "X-Request-Id": trace_id,
        }
        if hop_header is not None:
            headers[TRACE_CONTEXT_HEADER] = hop_header
        if handoff is not None and handoff != HANDOFF_LOCAL:
            # Disaggregation locator: the decode replica pulls this
            # prompt's prefix from the named prefill replica before
            # admitting (models/engine_handoff.py).
            headers[HANDOFF_SOURCE_HEADER] = handoff
        else:
            # Fleet KV fabric: no prefill-pool locator rides this leg
            # (unified fleet, short prompt, or the LOCAL sentinel), so
            # resolve the best advertised owner of the prompt's prefix
            # against current membership and stamp it — resident-only,
            # so a bloom FP or stale digest degrades the TARGET to
            # local prefill instead of moving the prefill to the
            # wrong replica.  Re-resolved on EVERY dial: failover and
            # migration legs never inherit a dead peer.
            fabric_source = self._fabric_source_for(name, payload)
            if fabric_source is not None:
                headers[HANDOFF_SOURCE_HEADER] = fabric_source
                headers[FABRIC_RESIDENT_ONLY_HEADER] = "1"
            elif handoff is not None:
                headers[HANDOFF_SOURCE_HEADER] = handoff
        if deadline is not None:
            headers["X-Request-Deadline"] = (
                f"{max(deadline - time.monotonic(), 0.0):.3f}"
            )
        conn = http.client.HTTPConnection(
            st.host, st.port, timeout=self._upstream_timeout
        )
        try:
            conn.request(
                "POST",
                "/generate",
                json.dumps(body).encode(),
                headers=headers,
            )
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        return _Upstream(name, conn, resp)

    def _span_route(
        self, tr: Optional[_ReqTrace], t0: float, picked, exclude: set
    ) -> None:
        """One ``router.route`` span per candidate selection: the
        placement decision, breaker-gated skips (the exclude set), and
        the retry-budget level at decision time — the
        breaker/budget-decision record of the timeline."""
        if tr is None:
            return
        attrs: dict = {
            "excluded": len(exclude),
            "budget": round(self.budget.available(), 1),
        }
        if picked is None:
            attrs["outcome"] = "none_dialable"
        else:
            attrs["replica"], attrs["placement"] = picked
        self.spans.record_span(
            "router.route",
            tr.trace_id,
            start_monotonic=t0,
            parent_id=tr.root,
            attrs=attrs,
        )

    def _span_attempt(
        self,
        tr: Optional[_ReqTrace],
        span_id: int,
        t0: float,
        replica: str,
        attempt: int,
        kind: str,
        **attrs,
    ) -> None:
        """Record one upstream leg's ``router.attempt`` span under the
        span id its ``X-Trace-Context`` carried — the cross-process
        anchor the replica's tree parents on.  ``kind`` is
        primary/retry/hedge/failover."""
        if tr is None:
            return
        self.spans.record_span(
            "router.attempt",
            tr.trace_id,
            start_monotonic=t0,
            span_id=span_id,
            parent_id=tr.root,
            attrs={
                "replica": replica,
                "attempt": attempt,
                "hop": _ReqTrace.HOP,
                "kind": kind,
                **attrs,
            },
        )

    def _next_candidate(
        self, prompt, exclude: set, attempt_index: int
    ) -> Optional[tuple[str, str]]:
        """(replica, placement) for the next dial, or None when nothing
        is currently dialable.  Breaker acquisition happens HERE (it
        consumes the half-open probe slot)."""
        order, tag = self.policy.candidates(prompt)
        for i, name in enumerate(order):
            if name in exclude:
                continue
            st = self.replicas.get(name)
            if st is None or not st.breaker.try_acquire():
                continue
            placement = tag if (i == 0 and attempt_index == 0) else FAILOVER
            return name, placement
        return None

    def _backoff(self, attempt: int, retry_after: Optional[float]) -> float:
        """Exponential backoff with full jitter, floored at the
        strictest Retry-After a replica sent (the drain/overload
        contract: the fleet told us when to come back)."""
        exp = min(self._backoff_max, self._backoff_base * (2**attempt))
        delay = self._rng.uniform(0, exp)
        if retry_after is not None:
            # Honor the fleet's Retry-After even past the backoff cap —
            # the replicas told us when to come back.
            delay = max(delay, retry_after)
        return delay

    def _classify(self, up: _Upstream) -> tuple[str, bytes, dict]:
        """Read + classify a unary upstream response:
        ``("ok"|"relay"|"draining"|"shed"|"error", body, headers)``."""
        resp = up.resp
        data = resp.read()
        headers = {
            k: v
            for k, v in resp.getheaders()
            if k.lower()
            in (
                "content-type",
                "x-request-id",
                "retry-after",
                "x-shed",
                "x-prefill-needed",
            )
        }
        if resp.status == 200:
            return "ok", data, headers
        if resp.status == 409 and headers.get(PREFILL_NEEDED_HEADER):
            # Decode-role refusal: prefix not resident, no locator.
            # Skip the replica (no breaker hit) and keep walking.
            return "prefill_needed", data, headers
        if resp.status == 503:
            if headers.get("X-Shed"):
                # Overload shed, not drain: the replica is healthy and
                # stays in rotation — honor its Retry-After and try the
                # next candidate instead of ejecting it.
                return "shed", data, headers
            # The begin_drain() contract: not a fault, a polite no.
            return "draining", data, headers
        if resp.status >= 500:
            return "error", data, headers
        # 4xx (validation) and 504 (the replica already timed the
        # request out): deterministic verdicts retrying cannot change.
        return "relay", data, headers

    # ------------------------------------------------------------ unary

    def _proxy_unary(
        self, handler, body, prompt, trace_id, deadline_s=None, tr=None,
        handoff=None,
    ) -> None:
        t0 = time.monotonic()
        # The client's deadline bounds the whole attempt budget: every
        # retry sleep, hedge, and re-dial below checks the remaining
        # budget before spending — a doomed request 504s fast instead
        # of churning through the ring.
        deadline = t0 + (
            self._timeout
            if deadline_s is None
            else min(self._timeout, deadline_s)
        )
        exclude: set = set()
        retry_after: Optional[float] = None
        attempt = 0
        sleeps = 0
        while time.monotonic() < deadline:
            if deadline_s is not None and self._deadline_infeasible(
                deadline - time.monotonic()
            ):
                # Even the emptiest replica's queue forecast outruns the
                # remaining budget: fail fast, never enqueue.
                self.metrics.requests.inc(outcome="deadline")
                if tr:
                    tr.set(outcome="deadline")
                self._record(
                    "router.deadline_exceeded",
                    where="forecast",
                    rid=trace_id,
                    remaining_s=round(deadline - time.monotonic(), 3),
                )
                handler._reply(
                    504,
                    {
                        "error": "deadline cannot be met by any replica",
                        "trace_id": trace_id,
                    },
                    trace_id,
                )
                return
            route_t0 = time.monotonic()
            picked = self._next_candidate(prompt, exclude, attempt)
            self._span_route(tr, route_t0, picked, exclude)
            if picked is None:
                if exclude:
                    # Everything failed (or shed) once: start over — but
                    # when a replica told us WHEN to come back
                    # (Retry-After on an overload shed), honor it before
                    # re-dialing, or the restart degenerates into a
                    # hammer loop against a fleet that just said no.
                    exclude.clear()
                    if retry_after is not None:
                        delay = self._backoff(sleeps, retry_after)
                        sleeps += 1
                        if (
                            time.monotonic() + delay >= deadline
                            or sleeps > 16
                        ):
                            break
                        time.sleep(delay)
                        retry_after = None
                    continue
                delay = self._backoff(sleeps, retry_after)
                sleeps += 1
                if time.monotonic() + delay >= deadline or sleeps > 16:
                    break
                time.sleep(delay)
                retry_after = None
                continue
            name, placement = picked
            if attempt > 0:
                if not self.budget.try_spend():
                    self._record(
                        "router.retry_budget_exhausted",
                        replica=name,
                        rid=trace_id,
                    )
                    break
                self.metrics.retries.inc()
                self._record(
                    "router.retry",
                    replica=name,
                    attempt=attempt,
                    rid=trace_id,
                )
            st = self.replicas[name]
            try:
                result = self._dial_with_hedge(
                    name, body, prompt, trace_id, exclude, deadline=
                    deadline if deadline_s is not None else None,
                    tr=tr, kind="retry" if attempt > 0 else "primary",
                    handoff=handoff,
                )
            except (failpoints.FailpointError, *_CONN_ERRORS) as e:
                st.failures += 1
                st.breaker.record_failure()
                self._record(
                    "router.dispatch_error",
                    replica=name,
                    error=str(e),
                    rid=trace_id,
                )
                exclude.add(name)
                attempt += 1
                continue
            up, winner_placement = result
            kind, data, headers = self._classify(up)
            up.close()
            if kind == "prefill_needed":
                self._prefill_needed(
                    up.name, trace_id, headers.get(PREFILL_NEEDED_HEADER)
                )
                exclude.add(up.name)
                continue
            if kind in ("draining", "shed"):
                ra = headers.get("Retry-After")
                retry_after = float(ra) if ra else retry_after
                if kind == "draining":
                    self._mark_draining(up.name, True)
                else:
                    # Overload shed: the replica is healthy — keep it
                    # in rotation, just not for THIS request.
                    self._record(
                        "router.replica_shed",
                        replica=up.name,
                        shed=headers.get("X-Shed"),
                        retry_after=ra,
                        rid=trace_id,
                    )
                exclude.add(up.name)
                # A polite 503 is not a breaker failure and not a retry:
                # the replica is healthy, just leaving the rotation.
                continue
            if kind == "error":
                st2 = self.replicas.get(up.name)
                if st2 is not None:
                    st2.failures += 1
                    st2.breaker.record_failure()
                self._record(
                    "router.dispatch_error",
                    replica=up.name,
                    status=up.resp.status,
                    rid=trace_id,
                )
                exclude.add(up.name)
                attempt += 1
                continue
            # ok or relay: this is the client's answer.
            st2 = self.replicas.get(up.name)
            if st2 is not None:
                st2.dispatches += 1
                if kind == "ok":
                    st2.breaker.record_success()
            elapsed = time.monotonic() - t0
            if kind == "ok":
                self._ttft_rolling.add(elapsed)
                self.metrics.ttft_seconds.observe(elapsed)
                self.metrics.request_seconds.observe(elapsed)
                self.metrics.placements.inc(
                    placement=winner_placement or placement
                )
                self.metrics.requests.inc(outcome="ok")
            else:
                self.metrics.requests.inc(outcome="error")
            if tr:
                tr.set(
                    outcome="ok" if kind == "ok" else "error",
                    replica=up.name,
                    placement=winner_placement or placement,
                )
            handler.send_response(up.resp.status)
            for key, value in headers.items():
                if key.lower() != "x-request-id":
                    handler.send_header(key, value)
            handler.send_header("X-Request-Id", trace_id)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            try:
                handler.wfile.write(data)
            except OSError:
                pass
            return
        if deadline_s is not None and time.monotonic() >= deadline:
            self.metrics.requests.inc(outcome="deadline")
            if tr:
                tr.set(outcome="deadline")
            self._record(
                "router.deadline_exceeded", where="retry_loop", rid=trace_id
            )
            handler._reply(
                504,
                {"error": "deadline exceeded", "trace_id": trace_id},
                trace_id,
            )
            return
        self.metrics.requests.inc(outcome="timeout")
        if tr:
            tr.set(outcome="timeout")
        handler._reply(
            503,
            {"error": "no replica available", "trace_id": trace_id},
            trace_id,
            retry_after="1",
        )

    def _dial_with_hedge(
        self, name, body, prompt, trace_id, exclude, deadline=None,
        tr=None, kind="primary", handoff=None,
    ) -> tuple[_Upstream, Optional[str]]:
        """Dial ``name``; when hedging is on and no response lands
        within the rolling TTFT p99, race a second dispatch along the
        ring.  Returns the winning upstream (loser closed) and its
        placement override (``failover`` when the hedge won).  Raises
        the primary's error when every leg fails.  With a client
        deadline, the hedge only fires while enough budget remains for
        the second leg to actually answer — a hedge that cannot beat
        the deadline is a wasted retry token.  Every leg — primary AND
        hedge — draws its own attempt index + span id from ``tr``, so
        the two race legs are distinct, separately-linked children in
        the assembled timeline."""
        results: queue_mod.Queue = queue_mod.Queue()

        def leg(leg_name: str, leg_kind: str):
            attempt_idx, span_id = (
                tr.begin_attempt() if tr else (0, 0)
            )
            leg_t0 = time.monotonic()
            try:
                up = self._dial(
                    leg_name, body, trace_id, False, deadline,
                    hop_header=tr.header(span_id, attempt_idx)
                    if tr
                    else None,
                    handoff=handoff,
                )
            except (failpoints.FailpointError, *_CONN_ERRORS) as e:
                self._span_attempt(
                    tr, span_id, leg_t0, leg_name, attempt_idx, leg_kind,
                    outcome="conn_error", error=type(e).__name__,
                )
                results.put((leg_name, None, e))
                return
            # Unary: the response headers are in — dial + TTFB is the
            # leg's span; the body relay happens on the handler thread.
            self._span_attempt(
                tr, span_id, leg_t0, leg_name, attempt_idx, leg_kind,
                status=up.resp.status,
            )
            results.put((leg_name, up, None))

        threading.Thread(
            target=leg, args=(name, kind), name="router-dial", daemon=True
        ).start()
        in_flight = 1
        hedged_name = None
        p99 = self._ttft_rolling.quantile(0.99)
        hedge_after = max(self._hedge_min_s, p99 if p99 else 0.0)
        hedge_deadline = time.monotonic() + hedge_after
        first_error: Optional[Exception] = None
        while in_flight:
            timeout = None
            if self._hedge and hedged_name is None:
                timeout = max(0.0, hedge_deadline - time.monotonic())
            try:
                leg_name, up, err = results.get(
                    timeout=timeout if timeout is not None else self._upstream_timeout
                )
            except queue_mod.Empty:
                if self._hedge and hedged_name is None:
                    if (
                        deadline is not None
                        and deadline - time.monotonic() <= hedge_after
                    ):
                        # Not enough budget left for a second leg to
                        # win: spend nothing.
                        hedged_name = ""
                        continue
                    route_t0 = time.monotonic()
                    picked = self._next_candidate(
                        prompt, exclude | {name}, 1
                    )
                    if picked is not None and self.budget.try_spend():
                        self._span_route(
                            tr, route_t0, picked, exclude | {name}
                        )
                        hedged_name = picked[0]
                        self._record(
                            "router.hedge",
                            replica=hedged_name,
                            primary=name,
                            after_s=round(hedge_after, 3),
                            rid=trace_id,
                        )
                        threading.Thread(
                            target=leg,
                            args=(hedged_name, "hedge"),
                            name="router-hedge",
                            daemon=True,
                        ).start()
                        in_flight += 1
                    else:
                        hedged_name = ""  # nothing to hedge with; stop trying
                continue
            in_flight -= 1
            if err is not None:
                st = self.replicas.get(leg_name)
                if st is not None:
                    st.failures += 1
                    st.breaker.record_failure()
                if leg_name == name:
                    first_error = err
                else:
                    self.metrics.hedges.inc(result="lost")
                continue
            # First response wins; the loser leg (if still in flight)
            # is drained and closed in the background — the losing
            # replica sees a broken pipe and cancels its request.
            if in_flight:
                self._drain_legs(results, in_flight)
            if hedged_name and leg_name == hedged_name:
                self.metrics.hedges.inc(result="won")
                self._record(
                    "router.hedge_won",
                    replica=leg_name,
                    primary=name,
                    rid=trace_id,
                )
                return up, FAILOVER
            if hedged_name and leg_name == name:
                self.metrics.hedges.inc(result="lost")
            return up, None
        raise first_error if first_error is not None else OSError(
            "all hedge legs failed"
        )

    def _drain_legs(self, results: queue_mod.Queue, n: int) -> None:
        """Close the remaining hedge legs off-thread (their sockets must
        not outlive the request, and the handler must not wait)."""

        def drain():
            for _ in range(n):
                try:
                    _, up, _err = results.get(
                        timeout=self._upstream_timeout * 2
                    )
                except queue_mod.Empty:
                    return
                if up is not None:
                    up.close()

        threading.Thread(
            target=drain, name="router-hedge-drain", daemon=True
        ).start()

    # ----------------------------------------------------------- stream

    def _proxy_stream(
        self, handler, body, prompt, trace_id, deadline_s=None, tr=None,
        handoff=None,
    ) -> None:
        """SSE passthrough wrapper: register the stream's migration
        handle (the planner flags it through this registry), relay, and
        always unregister — a dead handler thread must never leave a
        ghost stream for the planner to keep planning against."""
        # The affinity-horizon slice (block x max-blocks leading tokens)
        # is the stream's hot-prefix identity for the fabric replicator:
        # shared system prompts collapse to one census entry.
        horizon = (
            self.policy.prefix_block_tokens * self.policy.prefix_max_blocks
        )
        ctl = _StreamCtl(
            trace_id, self.policy.key_of(prompt), tuple(prompt[:horizon])
        )
        with self._streams_lock:
            self._streams[trace_id] = ctl
        try:
            self._relay_stream(
                handler, body, prompt, trace_id, deadline_s, tr, ctl,
                handoff=handoff,
            )
        finally:
            with self._streams_lock:
                self._streams.pop(trace_id, None)

    def _relay_stream(
        self, handler, body, prompt, trace_id, deadline_s, tr, ctl,
        handoff=None,
    ) -> None:
        """SSE passthrough with zero-drop mid-stream failover AND
        planned migration.

        Token events are re-emitted with a GLOBAL index (continuations
        restart at 0 upstream); the final done event carries every
        token the client was streamed.  A replica dying mid-stream
        triggers resubmission of ``prompt + emitted`` with the
        remaining budget to the next ring replica — the client stream
        never breaks unless every replica is gone or the failover/retry
        budget is spent.  A client deadline bounds the whole attempt
        budget (dial, retry sleeps, failovers) and rides every upstream
        dial as a re-stamped ``X-Request-Deadline``.

        Planned migration (ISSUE 14) rides the same resubmission: when
        the planner flags ``ctl.migrate_to``, the relay — at a PACED
        token boundary only, never mid-token-burst — validates the
        target (eligibility + breaker; abort otherwise), ends the
        current leg cleanly (``migrated``, no breaker failure: the
        source is healthy, just hot), and dials the target with
        ``prompt + emitted`` under the same rid.  The source engine
        sees a client disconnect and frees its slot/pages; the client
        sees one uninterrupted stream."""
        max_new = int(body.get("max_new_tokens", 16))
        emitted: list = []
        headers_sent = False
        exclude: set = set()
        failovers = 0
        attempt = 0
        sleeps = 0
        retry_after: Optional[float] = None
        t0 = time.monotonic()
        deadline = t0 + (
            self._timeout
            if deadline_s is None
            else min(self._timeout, deadline_s)
        )
        upstream_deadline = deadline if deadline_s is not None else None
        first_token_at: Optional[float] = None
        # Planned migration state: `migrate_target` carries a validated
        # (breaker-acquired) target from the event boundary that ended
        # the previous leg into the next loop iteration's dial;
        # `last_token_t` feeds the paced-boundary gate (never move
        # mid-token-burst).
        migrate_target: Optional[str] = None
        last_token_t: Optional[float] = None

        def client_error(message: str) -> None:
            if headers_sent:
                self._sse(handler, {"error": message, "trace_id": trace_id})
            else:
                handler._reply(
                    503, {"error": message, "trace_id": trace_id}, trace_id,
                    retry_after="1",
                )

        while True:
            if time.monotonic() >= deadline:
                if deadline_s is not None:
                    self.metrics.requests.inc(outcome="deadline")
                    if tr:
                        tr.set(outcome="deadline")
                    self._record(
                        "router.deadline_exceeded",
                        where="stream",
                        emitted=len(emitted),
                        rid=trace_id,
                    )
                    client_error("deadline exceeded")
                    return
                self.metrics.requests.inc(outcome="timeout")
                if tr:
                    tr.set(outcome="timeout")
                client_error("generation timed out")
                return
            migration_leg = False
            if migrate_target is not None:
                # Planned move: the target was validated (breaker slot
                # acquired) at the token boundary that ended the old
                # leg — dial it directly.  No candidate walk, and no
                # retry-budget spend: planned moves are paced by the
                # planner's own migration budget, never by the fault
                # budget.
                name, placement = migrate_target, MIGRATION
                migrate_target = None
                migration_leg = True
            else:
                route_t0 = time.monotonic()
                picked = self._next_candidate(prompt, exclude, attempt)
                self._span_route(tr, route_t0, picked, exclude)
                if picked is None:
                    if exclude:
                        # Same Retry-After floor as the unary restart: a
                        # fleet-wide overload shed must back the stream
                        # off, not hammer-loop the ring.
                        exclude.clear()
                        if retry_after is not None:
                            delay = self._backoff(sleeps, retry_after)
                            sleeps += 1
                            if (
                                sleeps > 16
                                or time.monotonic() + delay >= deadline
                            ):
                                self.metrics.requests.inc(outcome="error")
                                client_error("no replica available")
                                return
                            time.sleep(delay)
                            retry_after = None
                        continue
                    delay = self._backoff(sleeps, retry_after)
                    sleeps += 1
                    if sleeps > 16 or time.monotonic() + delay >= deadline:
                        self.metrics.requests.inc(outcome="error")
                        client_error("no replica available")
                        return
                    time.sleep(delay)
                    retry_after = None
                    continue
                name, placement = picked
            if attempt > 0 and not migration_leg:
                if not self.budget.try_spend():
                    self._record(
                        "router.retry_budget_exhausted",
                        replica=name,
                        rid=trace_id,
                    )
                    self.metrics.requests.inc(outcome="error")
                    if tr:
                        tr.set(outcome="error")
                    client_error("retry budget exhausted")
                    return
                if not emitted:
                    self.metrics.retries.inc()
                    self._record(
                        "router.retry",
                        replica=name,
                        attempt=attempt,
                        rid=trace_id,
                    )
            attempt += 1
            st = self.replicas.get(name)
            if st is None:
                # Membership changed under the leg (DNS reconciliation
                # removed it between selection and dial): skip it.
                if migration_leg:
                    self._migration_aborted(trace_id, name, "removed")
                exclude.add(name)
                continue
            upstream_body = dict(body)
            upstream_body["prompt"] = prompt + emitted
            upstream_body["max_new_tokens"] = max_new - len(emitted)
            # One leg = one attempt span; its id rides the dial's
            # X-Trace-Context so the replica's tree roots under it.
            # Every leg after a mid-stream death is a failover
            # resubmission (even one that died before emitting — the
            # resubmitted prompt is just the original); the leg whose
            # relay dies records outcome "died", which is exactly what
            # tpu_router_failovers_total meters — the assembler's
            # attempt-count cross-check.
            leg_kind = (
                "migration"
                if migration_leg
                else "failover"
                if failovers
                else ("retry" if attempt > 1 else "primary")
            )
            attempt_idx, leg_span = (
                tr.begin_attempt() if tr else (0, 0)
            )
            leg_t0 = time.monotonic()
            try:
                up = self._dial(
                    name, upstream_body, trace_id, True, upstream_deadline,
                    hop_header=tr.header(leg_span, attempt_idx)
                    if tr
                    else None,
                    handoff=handoff,
                )
            except (failpoints.FailpointError, *_CONN_ERRORS) as e:
                st.failures += 1
                st.breaker.record_failure()
                self._span_attempt(
                    tr, leg_span, leg_t0, name, attempt_idx, leg_kind,
                    outcome="conn_error", error=type(e).__name__,
                )
                self._record(
                    "router.dispatch_error",
                    replica=name,
                    error=str(e),
                    rid=trace_id,
                )
                if migration_leg:
                    # The planned target refused the dial: the move
                    # aborts and the ordinary machinery resubmits the
                    # stream wherever the ring says — still zero-drop.
                    self._migration_aborted(trace_id, name, "dial_error")
                exclude.add(name)
                continue
            if up.resp.status == 503:
                up_headers = dict(up.resp.getheaders())
                ra = up_headers.get("Retry-After")
                retry_after = float(ra) if ra else retry_after
                up.close()
                shed = up_headers.get("X-Shed")
                self._span_attempt(
                    tr, leg_span, leg_t0, name, attempt_idx, leg_kind,
                    status=503, outcome="shed" if shed else "draining",
                )
                if shed:
                    # Overload shed: healthy replica, keep in rotation.
                    self._record(
                        "router.replica_shed",
                        replica=name,
                        shed=shed,
                        retry_after=ra,
                        rid=trace_id,
                    )
                else:
                    self._mark_draining(name, True)
                if migration_leg:
                    self._migration_aborted(
                        trace_id, name, "shed" if shed else "draining"
                    )
                exclude.add(name)
                continue
            if up.resp.status == 409 and up.resp.getheader(
                PREFILL_NEEDED_HEADER
            ):
                missing = up.resp.getheader(PREFILL_NEEDED_HEADER)
                up.resp.read()
                up.close()
                self._span_attempt(
                    tr, leg_span, leg_t0, name, attempt_idx, leg_kind,
                    status=409, outcome="prefill_needed",
                )
                self._prefill_needed(name, trace_id, missing)
                if migration_leg:
                    self._migration_aborted(trace_id, name, "prefill_needed")
                exclude.add(name)
                continue
            if up.resp.status != 200:
                data = up.resp.read()
                self._span_attempt(
                    tr, leg_span, leg_t0, name, attempt_idx, leg_kind,
                    status=up.resp.status, outcome="error",
                )
                if migration_leg:
                    # The stream was HEALTHY before the planned move —
                    # a target verdict must never kill it.  Abort the
                    # move and resubmit through the ordinary ring walk.
                    up.close()
                    self._migration_aborted(
                        trace_id, name, f"http_{up.resp.status}"
                    )
                    exclude.add(name)
                    continue
                if headers_sent:
                    up.close()
                    self.metrics.requests.inc(outcome="error")
                    if tr:
                        tr.set(outcome="error")
                    client_error(f"replica HTTP {up.resp.status}")
                    return
                handler.send_response(up.resp.status)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("X-Request-Id", trace_id)
                handler.send_header("Content-Length", str(len(data)))
                handler.end_headers()
                try:
                    handler.wfile.write(data)
                except OSError:
                    pass
                up.close()
                self.metrics.requests.inc(outcome="error")
                if tr:
                    tr.set(outcome="error")
                return
            st.dispatches += 1
            ctl.replica = name  # the planner ranks streams by home
            if migration_leg:
                # The move landed: the target accepted the resubmission
                # and the relay continues there.  (A later death on the
                # target is ordinary failover, separately metered.)
                self.metrics.migrations.inc(outcome="done")
                self._record(
                    "router.migration_done",
                    rid=trace_id,
                    target=name,
                    emitted=len(emitted),
                )
            if not headers_sent:
                handler.send_response(200)
                handler.send_header("Content-Type", "text/event-stream")
                handler.send_header("Cache-Control", "no-cache")
                handler.send_header("X-Request-Id", trace_id)
                handler.end_headers()
                headers_sent = True
                self.metrics.placements.inc(placement=placement)
                if tr:
                    tr.set(placement=placement)
            done = False
            leg_tokens = 0  # tokens relayed by THIS leg (attempt attrs)

            def end_leg(outcome: str) -> None:
                # The leg's attempt span covers dial → relay end: TTFB
                # and SSE relay in one timed child, the relayed-token
                # count in its attrs.
                self._span_attempt(
                    tr, leg_span, leg_t0, name, attempt_idx, leg_kind,
                    status=200, outcome=outcome, tokens=leg_tokens,
                )

            try:
                for event in self._iter_sse(up.resp):
                    if event is None:  # heartbeat comment
                        try:
                            handler.wfile.write(b": ping\n\n")
                            handler.wfile.flush()
                        except OSError:
                            up.close()
                            end_leg("client_gone")
                            return  # client vanished; upstream cancels
                        continue
                    if "token" in event:
                        token_t = time.monotonic()
                        if first_token_at is None:
                            first_token_at = token_t
                            self._ttft_rolling.add(first_token_at - t0)
                            self.metrics.ttft_seconds.observe(
                                first_token_at - t0
                            )
                        token_gap = (
                            token_t - last_token_t
                            if last_token_t is not None
                            else None
                        )
                        last_token_t = token_t
                        out = dict(event)
                        out["index"] = len(emitted)
                        out["trace_id"] = trace_id
                        emitted.append(event["token"])
                        leg_tokens += 1
                        ctl.emitted = len(emitted)
                        try:
                            self._sse(handler, out)
                        except OSError:
                            up.close()
                            end_leg("client_gone")
                            return
                        # Planned migration fires ONLY at a paced token
                        # boundary: a measured inter-token gap at/above
                        # the burst threshold means single-token decode
                        # cadence — never mid-token-burst (a blocked
                        # decode round's tokens arrive back-to-back; a
                        # deferred flag is simply re-checked at the
                        # next token).
                        want = ctl.migrate_to
                        if (
                            want is not None
                            and token_gap is not None
                            and token_gap >= self._migration_burst_gap
                            and len(emitted) < max_new
                        ):
                            ctl.migrate_to = None
                            if self._acquire_migration_target(want):
                                migrate_target = want
                                break  # end this leg at the boundary
                            self._migration_aborted(
                                trace_id, want, "target_ineligible"
                            )
                        continue
                    if event.get("done"):
                        fin = dict(event)
                        fin["tokens"] = list(emitted)
                        fin["trace_id"] = trace_id
                        if failovers:
                            # Per-token logprobs cannot be stitched
                            # across a failover; drop rather than lie.
                            fin.pop("logprobs", None)
                        try:
                            self._sse(handler, fin)
                        except OSError:
                            pass
                        done = True
                        break
                    if "error" in event:
                        # The REPLICA gave up (its own request timeout):
                        # a deterministic verdict, relayed not retried.
                        out = dict(event)
                        out["trace_id"] = trace_id
                        try:
                            self._sse(handler, out)
                        except OSError:
                            pass
                        up.close()
                        end_leg("relay_error")
                        self.metrics.requests.inc(outcome="error")
                        if tr:
                            tr.set(outcome="error")
                        return
            except (*_CONN_ERRORS, ValueError):
                pass  # transport death mid-stream; handled below
            up.close()
            if migrate_target is not None:
                # Planned move: this leg ends CLEANLY — "migrated", not
                # "died".  No breaker failure and no failover metric
                # (the source is healthy, just hot); closing the
                # upstream makes the source engine see a client
                # disconnect and cancel, freeing its slot and pages.
                # The loop re-dials `migrate_target` with
                # prompt + emitted under the same rid.
                end_leg("migrated")
                continue
            if done:
                end_leg("done")
                st.breaker.record_success()
                elapsed = time.monotonic() - t0
                self.metrics.request_seconds.observe(elapsed)
                self.metrics.requests.inc(outcome="ok")
                if tr:
                    tr.set(outcome="ok", failovers=failovers)
                return
            # Transport error or EOF before `done`: either way the
            # replica died mid-stream.  Fail the stream over.
            end_leg("died")
            st.failures += 1
            st.breaker.record_failure()
            failovers += 1
            if failovers > self._max_failovers:
                self.metrics.requests.inc(outcome="error")
                if tr:
                    tr.set(outcome="error", failovers=failovers)
                client_error("failover budget exhausted")
                return
            self.metrics.failovers.inc()
            self._record(
                "router.failover",
                replica=name,
                emitted=len(emitted),
                remaining=max_new - len(emitted),
                rid=trace_id,
            )
            if len(emitted) >= max_new:
                # Nothing left to generate: the death landed after the
                # last token — finish the stream ourselves.
                fin = {
                    "done": True,
                    "tokens": list(emitted),
                    "trace_id": trace_id,
                }
                try:
                    self._sse(handler, fin)
                except OSError:
                    pass
                self.metrics.requests.inc(outcome="ok")
                if tr:
                    tr.set(outcome="ok", failovers=failovers)
                return
            exclude.add(name)

    @staticmethod
    def _sse(handler, obj: dict) -> None:
        handler.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
        handler.wfile.flush()

    @staticmethod
    def _iter_sse(resp):
        """Yield parsed ``data:`` events from an upstream SSE response;
        ``None`` for heartbeat comments.  Returns on EOF (the caller
        decides whether that EOF was a clean close or a death)."""
        while True:
            line = resp.readline()
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            if line.startswith(b":"):
                yield None
                continue
            if line.startswith(b"data:"):
                yield json.loads(line[5:].strip())

    # -------------------------------------------------------- lifecycle

    def snapshot(self) -> dict:
        """JSON-safe router state for /debug/router."""
        return {
            "draining": self._draining.is_set(),
            "active_requests": self._active,
            "policy": {
                "mode": self.policy.mode,
                "overflow_depth": self.policy.overflow_depth,
                "prefix_block_tokens": self.policy.prefix_block_tokens,
                "prefix_max_blocks": self.policy.prefix_max_blocks,
            },
            "ring": self.ring.snapshot(),
            "disagg": (
                self.disagg.snapshot()
                if self.disagg is not None
                else {"enabled": False}
            ),
            "retry_budget": round(self.budget.available(), 2),
            "retry_budget_spent": self.budget.spent_total,
            "retry_budget_exhausted": self.budget.exhausted_total,
            "replicas": {
                name: st.snapshot() for name, st in self.replicas.items()
            },
        }

    def start(self) -> "RouterServer":
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="router-poll", daemon=True
        )
        self._poll_thread.start()
        # First poll before serving: no cold blind spot.  It runs ON the
        # poll thread (the poll-state owner); start() just waits for it.
        self._first_poll.wait(
            timeout=self._poll_timeout * (len(self.replicas) + 1) + 2.0
        )
        self._http_thread = threading.Thread(
            # 50ms shutdown poll (vs the 0.5s default): drains and test
            # teardowns should not stall on the accept loop.
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            name="router-http",
            daemon=True,
        )
        self._http_thread.start()
        if self.prober is not None:
            self.prober.start()
        return self

    def begin_drain(self, grace_s: float = 10.0) -> None:
        """SIGTERM path: stop admitting (503 + Retry-After, /healthz →
        draining), wait for in-flight proxied requests to finish (at
        most ``grace_s``), then set :attr:`drained`.  Idempotent."""
        if self._draining.is_set():
            return
        self._draining.set()
        self._record("router.drain_begin_self", grace_s=grace_s)

        def watch():
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline:
                with self._active_lock:
                    if self._active == 0:
                        break
                time.sleep(0.05)
            self._record(
                "router.drain_end_self", cut_requests=self._active
            )
            self.drained.set()

        threading.Thread(
            target=watch, name="router-drain", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop.set()
        if self.prober is not None:
            self.prober.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)

    def serve_forever(self) -> None:
        try:
            while not self._stop.is_set():
                self._stop.wait(1.0)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()


def main(argv: Optional[list[str]] = None) -> None:
    """Router daemon entry (`python -m k8s_device_plugin_tpu.router`):
    deploy/k8s-deploy-router.yaml runs this in front of the serve
    replicas."""
    import argparse
    import sys

    from ..utils import flight as flight_mod

    p = argparse.ArgumentParser(prog="tpu-serving-router")
    p.add_argument(
        "--replicas",
        default="",
        help="comma-separated host:port serving replicas (static set)",
    )
    p.add_argument(
        "--replicas-dns",
        default="",
        help="name:port of a HEADLESS Service over the serving replicas: "
        "A records are re-resolved every poll interval and ring "
        "membership reconciled — replicas scale without a router restart",
    )
    p.add_argument("--http-port", type=int, default=8100)
    p.add_argument(
        "--prefix-block-tokens",
        type=int,
        default=16,
        help="tokens per prefix block in the affinity key (match the "
        "replicas' --page-size so one block is one KV page)",
    )
    p.add_argument(
        "--prefix-blocks",
        type=int,
        default=4,
        help="leading blocks hashed into the affinity key (the shared "
        "system-prompt horizon; the unique tail stays out of the key)",
    )
    p.add_argument("--vnodes", type=int, default=64)
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument(
        "--overflow-depth",
        type=int,
        default=4,
        help="queue-depth gap (home vs least-loaded) beyond which a "
        "request overflows along the ring instead of joining the hot "
        "shard",
    )
    p.add_argument("--breaker-failures", type=int, default=3)
    p.add_argument("--breaker-open-s", type=float, default=5.0)
    p.add_argument("--retry-budget", type=float, default=32.0)
    p.add_argument("--retry-refill", type=float, default=2.0)
    p.add_argument(
        "--hedge",
        type=int,
        choices=[0, 1],
        default=1,
        help="hedged dispatch for unary requests: when no response "
        "lands within the rolling TTFT p99, race a second replica; "
        "first response wins, loser cancelled (costs retry budget)",
    )
    p.add_argument("--hedge-min-s", type=float, default=0.25)
    p.add_argument("--max-failovers", type=int, default=3)
    p.add_argument(
        "--migrate",
        type=int,
        choices=[0, 1],
        default=1,
        help="proactive planned migration (router/migration.py, default "
        "on): when a replica's queue-wait EWMA runs sustained-hot while "
        "a peer runs cold, live streams of its hottest prefix-block "
        "sessions are resubmitted to the cold peer through the zero-drop "
        "failover machinery — paced by a migration budget, never "
        "mid-token-burst, aborted if the target's breaker refuses; 0 "
        "leaves only reactive failover",
    )
    p.add_argument(
        "--migrate-hot-wait",
        type=float,
        default=2.0,
        help="queue-wait pressure (seconds) at/above which a replica "
        "counts as hot for migration/scale planning",
    )
    p.add_argument(
        "--migrate-cold-wait",
        type=float,
        default=0.5,
        help="queue-wait pressure (seconds) at/below which a replica is "
        "a cold migration target",
    )
    p.add_argument(
        "--migrate-sustain",
        type=int,
        default=3,
        help="consecutive hot summary polls before a replica counts as "
        "SUSTAINED hot (one bursty poll never triggers a migration)",
    )
    p.add_argument(
        "--migrate-budget",
        type=float,
        default=4.0,
        help="migration token bucket: burst cap on planned moves "
        "(each flagged stream spends one token)",
    )
    p.add_argument(
        "--migrate-refill",
        type=float,
        default=1.0,
        help="migration budget refill rate (moves per second) — the "
        "sustained pacing knob",
    )
    p.add_argument(
        "--disagg",
        type=int,
        choices=[0, 1],
        default=0,
        help="disaggregated prefill/decode routing (router/disagg.py, "
        "docs/disagg.md): classify requests by prompt-length threshold "
        "x decode-pool pressure, stamp long prompts with an "
        "X-Handoff-Source prefill locator (the decode replica pulls "
        "the KV prefix over POST /v1/prefill), and fall back to "
        "unified dispatch whenever the prefill pool is down; requires "
        "prefill-role replicas (--prefill-replicas, or summary-poll "
        "role discovery)",
    )
    p.add_argument(
        "--disagg-threshold",
        type=int,
        default=256,
        help="prompt length (tokens) at/above which a request's "
        "prefill dispatches to the prefill pool while the decode pool "
        "is calm",
    )
    p.add_argument(
        "--disagg-hot-threshold",
        type=int,
        default=64,
        help="the lower split bar that applies while the decode pool "
        "runs hot (pressure >= --disagg-hot-wait)",
    )
    p.add_argument(
        "--disagg-hot-wait",
        type=float,
        default=0.5,
        help="decode-pool queue-wait pressure (seconds, max over "
        "eligible replicas) at/above which the hot threshold applies",
    )
    p.add_argument(
        "--prefill-replicas",
        default="",
        help="comma-separated host:port replicas that are prefill-role "
        "from the start (polled like any replica, never on the "
        "/generate ring); replicas discovered via --replicas/-dns "
        "whose summary reports role=prefill are reconciled the same "
        "way",
    )
    p.add_argument(
        "--slo",
        type=int,
        choices=[0, 1],
        default=1,
        help="fleet SLO plane (utils/slo.py, default on): merge the "
        "per-replica SLI counters each summary poll carries into "
        "fleet-level sliding-window burn rates, evaluate the "
        "multi-window fast-burn/slow-burn alert rules every sweep "
        "(slo.burn_alert flight events + direct incidents + "
        "tpu_slo_burn_rate gauges), and serve the fleet view at GET "
        "/debug/slo; 0 disables fleet SLO accounting",
    )
    p.add_argument(
        "--canary",
        type=int,
        choices=[0, 1],
        default=0,
        help="active correctness plane (router/prober.py, "
        "docs/operations.md \"Active probing\"): continuously probe "
        "every replica with seeded deterministic canary prompts, "
        "verdict bit-exactness against oracles captured from the "
        "fleet's own first clean response per params fingerprint, "
        "detect summary-counter staleness, and serve GET /debug/canary",
    )
    p.add_argument(
        "--canary-interval",
        type=float,
        default=5.0,
        help="seconds between canary sweeps (every replica probed once "
        "per sweep; the probe budget IS the overhead budget — the "
        "serving bench pins it at <=1%% of throughput)",
    )
    p.add_argument(
        "--canary-tokens",
        type=int,
        default=4,
        help="new tokens per canary probe",
    )
    p.add_argument(
        "--canary-k",
        type=int,
        default=3,
        help="consecutive bit-exactness mismatches before the "
        "canary.mismatch incident and auto-fence (one blip never acts)",
    )
    p.add_argument(
        "--canary-stale-sweeps",
        type=int,
        default=5,
        help="consecutive sweeps with a frozen requests_total summary "
        "counter (while probes land) before the canary.stale incident",
    )
    p.add_argument(
        "--canary-fence",
        type=int,
        choices=[0, 1],
        default=1,
        help="auto-fence policy: 1 = a confirmed mismatch POSTs the "
        "replica's /debug/fence so the fenced-demotion machinery "
        "drains it; 0 = observe-only (incidents still fire)",
    )
    p.add_argument(
        "--fabric",
        type=int,
        choices=[0, 1],
        default=0,
        help="fleet-wide content-addressed KV fabric (router/fabric.py, "
        "docs/routing.md \"Fleet KV fabric\"): parse each replica's "
        "bloom prefix digest off the summary poll, stamp the best "
        "advertised owner as a resident-only X-Handoff-Source on every "
        "dial whose prompt prefix is non-resident at the target (the "
        "target pulls the KV pages peer-to-peer instead of re-running "
        "the prefill), and run the K-replica hot-prefix "
        "replication/eviction sweep each poll tick; requires the "
        "replicas to run with --enable-admin for the replication "
        "pull/drop endpoints",
    )
    p.add_argument(
        "--fabric-k",
        type=int,
        default=2,
        help="target replication factor for hot prefixes (copies are "
        "planned until this many replicas advertise the prefix)",
    )
    p.add_argument(
        "--fabric-hot-wait",
        type=float,
        default=2.0,
        help="owner queue-wait pressure (seconds) at/above which its "
        "hot prefixes are proactively replicated",
    )
    p.add_argument(
        "--fabric-cold-wait",
        type=float,
        default=0.5,
        help="replication-target pressure ceiling (seconds) — copies "
        "only land on replicas with cold headroom",
    )
    p.add_argument(
        "--fabric-hot-score",
        type=float,
        default=2.0,
        help="minimum hotness (live streams x full prefix pages) "
        "before a prefix is worth replicating",
    )
    p.add_argument(
        "--fabric-actions",
        type=int,
        default=2,
        help="replication/eviction actions fired per poll sweep, "
        "fleet-wide (the pacing bound)",
    )
    p.add_argument(
        "--postmortem",
        type=int,
        choices=[0, 1],
        default=0,
        help="fleet postmortem collector (router/postmortem.py, "
        "docs/operations.md \"Postmortem archaeology\"): on any "
        "incident — a replica's incidents_total cursor advancing on "
        "the summary poll, or the router's own SLO/canary monitors "
        "firing — fan out to every replica's (plus the plugin "
        "daemon's and controller's, when given) /debug/flight, "
        "/debug/spans, /debug/state, and /metrics, and write ONE "
        "fleet evidence bundle under --dump-dir for "
        "tools/postmortem.py to classify; served at GET "
        "/debug/postmortem, manual capture via the admin-gated POST "
        "/debug/postmortem/capture",
    )
    p.add_argument(
        "--postmortem-plugin-url",
        default="",
        help="host:port of the plugin daemon's metrics server — its "
        "forensic endpoints join every fleet bundle",
    )
    p.add_argument(
        "--postmortem-controller-url",
        default="",
        help="host:port of the fleet controller's debug server — its "
        "forensic endpoints join every fleet bundle",
    )
    p.add_argument(
        "--postmortem-debounce",
        type=float,
        default=120.0,
        help="per-episode capture debounce (seconds): however many "
        "incidents an episode re-fires, one bundle per window",
    )
    p.add_argument(
        "--postmortem-admin",
        type=int,
        choices=[0, 1],
        default=0,
        help="1 arms the manual POST /debug/postmortem/capture "
        "endpoint (same opt-in posture as the replicas' "
        "--enable-admin)",
    )
    p.add_argument(
        "--dump-budget-mb",
        type=int,
        default=0,
        help="retention budget (MiB) for --dump-dir, shared by flight "
        "dumps and postmortem bundles: after every write the oldest "
        "entries are pruned until the directory fits (0 = unbounded)",
    )
    p.add_argument("--request-timeout", type=float, default=600.0)
    p.add_argument(
        "--policy",
        choices=["affinity", "random"],
        default="affinity",
        help="random = uniform placement control (what the serving "
        "benchmark diffs affinity against)",
    )
    p.add_argument("--drain-grace", type=float, default=10.0)
    p.add_argument("--flight-ring", type=int, default=2048)
    p.add_argument(
        "--span-ring",
        type=int,
        default=2048,
        help="capacity of the router's request-span ring (route "
        "selection, per-attempt dial/TTFB, SSE relay, failover legs) "
        "served at GET /debug/spans and embedded in flight dumps — "
        "tools/trace_assemble.py joins it with the replicas' rings "
        "into per-request fleet timelines",
    )
    p.add_argument(
        "--dump-dir", default=flight_mod.default_dump_dir() or ""
    )
    p.add_argument("--failpoints", default="")
    args = p.parse_args(argv)
    replicas = [r for r in args.replicas.split(",") if r]
    if not replicas and not args.replicas_dns:
        raise SystemExit("need --replicas and/or --replicas-dns")
    box = flight_mod.register(
        flight_mod.FlightRecorder(capacity=args.flight_ring, name="router")
    )
    # The span ring rides the same SIGUSR2/atexit dumps the flight
    # recorder does: a dead router still leaves the per-request
    # timelines tools/trace_assemble.py needs on disk.
    spans = flight_mod.register_spans(
        SpanRecorder(capacity=args.span_ring, name="router")
    )
    flight_mod.install_dump_handlers(args.dump_dir or None)
    if args.dump_budget_mb:
        flight_mod.set_dump_budget(args.dump_budget_mb * 1024 * 1024)
    failpoints.set_flight(box)
    failpoints.arm_from_env()
    if args.failpoints:
        failpoints.arm_spec(args.failpoints)
    server = RouterServer(
        replicas,
        port=args.http_port,
        flight=box,
        spans=spans,
        prefix_block_tokens=args.prefix_block_tokens,
        prefix_max_blocks=args.prefix_blocks,
        vnodes=args.vnodes,
        poll_interval_s=args.poll_interval,
        overflow_depth=args.overflow_depth,
        breaker_failures=args.breaker_failures,
        breaker_open_s=args.breaker_open_s,
        retry_budget=args.retry_budget,
        retry_refill_per_s=args.retry_refill,
        hedge=bool(args.hedge),
        hedge_min_s=args.hedge_min_s,
        max_failovers=args.max_failovers,
        request_timeout_s=args.request_timeout,
        policy_mode=args.policy,
        replicas_dns=args.replicas_dns or None,
        disagg=bool(args.disagg),
        disagg_config=DisaggConfig(
            threshold_tokens=args.disagg_threshold,
            # The hot bar can never sit above the calm bar; clamp so a
            # lone --disagg-threshold below the default hot bar keeps
            # working ("split everything past N, hot or not").
            hot_threshold_tokens=min(
                args.disagg_hot_threshold, args.disagg_threshold
            ),
            hot_wait_s=args.disagg_hot_wait,
        ),
        prefill_replicas=[
            r for r in args.prefill_replicas.split(",") if r
        ],
        slo=bool(args.slo),
        canary=bool(args.canary),
        canary_config=CanaryConfig(
            interval_s=args.canary_interval,
            probe_tokens=args.canary_tokens,
            k_mismatch=args.canary_k,
            stale_sweeps=args.canary_stale_sweeps,
            fence=bool(args.canary_fence),
        ),
        migrate=bool(args.migrate),
        migration=MigrationConfig(
            hot_wait_s=args.migrate_hot_wait,
            cold_wait_s=args.migrate_cold_wait,
            sustain_polls=args.migrate_sustain,
            budget=args.migrate_budget,
            refill_per_s=args.migrate_refill,
        ),
        postmortem=bool(args.postmortem),
        postmortem_dir=args.dump_dir or None,
        postmortem_plugin_url=args.postmortem_plugin_url or None,
        postmortem_controller_url=args.postmortem_controller_url or None,
        postmortem_debounce_s=args.postmortem_debounce,
        postmortem_budget_bytes=(
            args.dump_budget_mb * 1024 * 1024
            if args.dump_budget_mb
            else None
        ),
        postmortem_admin=bool(args.postmortem_admin),
        fabric=bool(args.fabric),
        fabric_config=FabricConfig(
            replicate_k=args.fabric_k,
            hot_wait_s=args.fabric_hot_wait,
            cold_wait_s=args.fabric_cold_wait,
            hot_score=args.fabric_hot_score,
            max_actions_per_sweep=args.fabric_actions,
            default_page_size=args.prefix_block_tokens,
        ),
    ).start()

    import signal

    def _on_signal(signum, _frame):
        print(
            f"received {signal.Signals(signum).name}; draining "
            f"(grace {args.drain_grace:.1f}s)",
            file=sys.stderr,
            flush=True,
        )
        server.begin_drain(args.drain_grace)
        server.drained.wait(args.drain_grace + 1.0)
        server.stop()

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
    except ValueError:
        pass
    print(
        f"routing on :{server.port} over {len(server.replicas)} replicas "
        "(POST /generate, GET /healthz /metrics /debug/router "
        "/debug/fleet /debug/slo /debug/fabric /debug/canary "
        "/debug/postmortem /debug/spans)",
        file=sys.stderr,
        flush=True,
    )
    server.serve_forever()


if __name__ == "__main__":
    main()
