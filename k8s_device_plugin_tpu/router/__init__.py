"""Prefix-affinity serving router (ROADMAP item 2, the fleet brain).

The 2-replica serve Deployment (deploy/k8s-deploy-serve-http.yaml) has
no request placement: a Service round-robins, so the retained/host-arena
KV built by the cache tiers gets shredded across replicas, and a replica
dying mid-decode drops every in-flight stream.  This package is the
standalone daemon that fronts K serving replicas with:

- **prefix affinity** — consistent hashing over the tokenized prompt's
  leading prefix blocks routes a repeated system prompt to the replica
  whose KV tiers already hold it (`ring.py`), with queue-depth-aware
  overflow read from each replica's cheap ``/debug/state?summary=1``;
- **first-class fault handling** — per-replica closed→open→half-open
  circuit breakers and a global retry budget (`breaker.py`), retries
  with exponential backoff + jitter honoring ``Retry-After``, optional
  hedged dispatch when TTFT exceeds the rolling p99, drain awareness
  (the replica ``begin_drain()`` 503 contract), and zero-drop
  mid-stream failover: a replica killed mid-decode gets its stream
  transparently resubmitted — prompt + already-emitted tokens,
  idempotent by request id — to the next ring replica, where the
  content-addressed prefix restore turns re-prefill into a KV restore
  (`server.py`).

Scored, not assumed: the chaos suite kills replicas under burst traffic
and scores the router's flight events against injected ground truth
(docs/routing.md, docs/chaos.md).  Stdlib + utils only — jax-free.
"""

from .breaker import CircuitBreaker, RetryBudget
from .ring import HashRing, prefix_key
from .server import RouterServer

__all__ = [
    "CircuitBreaker",
    "HashRing",
    "RetryBudget",
    "RouterServer",
    "prefix_key",
]
