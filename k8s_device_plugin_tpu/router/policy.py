"""Replica state + the placement policy (affinity, overflow, failover).

The policy answers one question per request: in what order should the
router try the replicas?  The answer composes three signals:

- **ring affinity** (`ring.py`): the prompt's prefix-block key names a
  home replica whose KV tiers likely hold the prefix; the ring order
  after it is the deterministic failover sequence.
- **liveness/drain state** (this module, fed by the poll loop): a
  draining replica takes NO new assignments (its in-flight streams keep
  running — the `begin_drain()` rollout contract), an unreachable one
  sorts last (poll state may be stale; it is still dialed as a final
  resort, where its breaker decides).
- **queue depth** (read from ``/debug/state?summary=1``): affinity is a
  preference, not a law — when the home replica's queue is
  ``overflow_depth`` deeper than the least-loaded eligible replica, the
  request overflows along the ring instead of piling onto a hot shard.

Breaker state is deliberately NOT consulted here: `try_acquire()` has
side effects (it consumes the half-open probe slot), so the dispatch
loop in server.py applies it per dial attempt.

``mode="random"`` is the control policy the serving benchmark uses to
measure what affinity buys (uniform seeded placement over the same
eligible set, same failover semantics).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from .breaker import CircuitBreaker
from .ring import HashRing, prefix_key

# Placement tags (tpu_router_placements_total label values).
HOME = "home"
OVERFLOW = "overflow"
RANDOM = "random"
FAILOVER = "failover"
# Planned migration (router/migration.py): a live stream moved OFF a
# sustained-hot replica by the planner — the proactive cousin of
# `failover`, same zero-drop resubmission, different cause.
MIGRATION = "migration"


class ReplicaState:
    """One replica's router-side view: address, poll-derived load/drain
    state, and its circuit breaker.  Mutable fields are plain scalars
    read racily by dispatch (GIL-atomic; a one-poll-stale read is by
    design).  The poll-derived fields are owner-thread-only by contract
    (annotated ``guarded by: owner-thread``): the router's poll loop
    mutates them off-lock, and any other thread — the request/stream
    paths marking a replica draining or fenced on failover — must hold
    the router lock, which serializes against the owner.
    ``RouterServer(racecheck=True)`` arms a racecheck.OwnerGuard that
    raises at any off-contract toucher (tests/test_router.py pins it)."""

    def __init__(self, name: str, breaker: CircuitBreaker):
        self.name = name  # "host:port" — the ring node AND dial target
        host, _, port = name.rpartition(":")
        self.host = host
        self.port = int(port)
        self.breaker = breaker
        # Disaggregation role off the summary poll (router/disagg.py):
        # prefill-role replicas serve POST /v1/prefill only and take NO
        # /generate assignments (candidates() excludes them; the server
        # keeps them off the affinity ring).
        self.role = "unified"  # guarded by: owner-thread
        self.reachable = True  # optimistic until a poll says otherwise; guarded by: owner-thread
        self.draining = False  # guarded by: owner-thread
        # Replica self-fencing (summary ``fenced``): a sick replica —
        # hung step, unhealthy chip, operator fence — is treated exactly
        # like a draining one (no new assignments, in-flight streams
        # fail over through the ordinary zero-drop path) until its
        # summary clears.
        self.fenced = False  # guarded by: owner-thread
        self.queue_depth = 0  # guarded by: owner-thread
        self.active_slots = 0  # guarded by: owner-thread
        # Host-side overload signals off the summary poll (queue-wait
        # EWMA + drain-rate forecast, engine_overload.py): what the
        # migration planner and the /debug/fleet scale signal read.
        # None until the replica exports them (no controller / no
        # traffic yet) — planners treat None as "no opinion".
        self.queue_wait_ewma_s = None  # guarded by: owner-thread
        self.drain_rate_rps = None  # guarded by: owner-thread
        # Cumulative per-objective [good, total] SLI counters off the
        # summary poll (utils/slo.py): the poll thread deltas them
        # against the previous poll into the router's fleet SLO tracker
        # (a shrunk counter = replica restart -> re-baselined from the
        # fresh totals).  None until the replica exports an SLO block.
        self.slo_totals = None  # guarded by: owner-thread
        # Replica process uptime off the summary poll (``uptime_s``):
        # the fleet controller's replica-minutes accounting and the
        # scale_down victim tie-breaker.  None until the replica
        # exports it; first_seen is the router-side fallback (when the
        # replica predates the field, age-since-registration still
        # bounds the bill).
        self.uptime_s = None  # guarded by: owner-thread
        # Cumulative anomaly-incident counter off the summary poll
        # (``incidents_total``): the fleet postmortem collector's
        # trigger cursor — an advance between polls means the replica
        # emitted an incident and its forensic state is worth
        # capturing NOW, before the rings roll.  None until the
        # replica exports the field (and on the first observation, so
        # joining a fleet with historical incidents never back-fires).
        self.incidents_total = None  # guarded by: owner-thread
        self.first_seen = time.monotonic()
        self.last_poll = 0.0  # last successful poll (monotonic); guarded by: owner-thread
        self.dispatches = 0
        self.failures = 0

    def snapshot(self) -> dict:
        return {
            "role": self.role,
            "reachable": self.reachable,
            "draining": self.draining,
            "fenced": self.fenced,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "queue_wait_ewma_s": self.queue_wait_ewma_s,
            "drain_rate_rps": self.drain_rate_rps,
            "slo_totals": self.slo_totals,
            "uptime_s": self.uptime_s,
            "age_s": round(time.monotonic() - self.first_seen, 3),
            "breaker": self.breaker.snapshot(),
            "dispatches": self.dispatches,
            "failures": self.failures,
            "last_poll_age_s": (
                round(time.monotonic() - self.last_poll, 3)
                if self.last_poll
                else None
            ),
        }


class RoutingPolicy:
    """Turns (prompt, replica states) into a dial order + placement tag.

    Thread-safe for the reads it does; ring membership changes go
    through the owning server's lock.
    """

    def __init__(
        self,
        ring: HashRing,
        replicas: dict[str, ReplicaState],
        *,
        overflow_depth: int = 4,
        prefix_block_tokens: int = 16,
        prefix_max_blocks: int = 4,
        mode: str = "affinity",
        seed: int = 0,
    ):
        if mode not in ("affinity", "random"):
            raise ValueError(f"unknown policy mode {mode!r}")
        if overflow_depth < 1:
            raise ValueError(f"overflow_depth must be >= 1, got {overflow_depth}")
        self.ring = ring
        self.replicas = replicas
        self.overflow_depth = overflow_depth
        self.prefix_block_tokens = prefix_block_tokens
        self.prefix_max_blocks = prefix_max_blocks
        self.mode = mode
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()

    def key_of(self, prompt) -> int:
        return prefix_key(
            prompt, self.prefix_block_tokens, self.prefix_max_blocks
        )

    def candidates(self, prompt) -> tuple[list[str], str]:
        """(ordered replica names, primary placement tag).

        Draining replicas are excluded outright (no new assignments —
        ever); unreachable ones are appended last as a stale-poll
        hedge.  The tag describes position 0 only; the dispatch loop
        tags anything after it ``failover``.
        """
        ring_order = self.ring.order(self.key_of(prompt))

        def _out(st: ReplicaState) -> bool:
            # Draining and fenced replicas take NO new assignments —
            # not even as a stale-poll hedge (a fenced replica answers
            # 503 by contract; dialing it just burns a retry token).
            # Prefill-role replicas never decode (/generate answers
            # 409 by contract — router/disagg.py).
            return st.draining or st.fenced or st.role == "prefill"

        eligible = [
            n
            for n in ring_order
            if not _out(self.replicas[n]) and self.replicas[n].reachable
        ]
        stale = [
            n
            for n in ring_order
            if not _out(self.replicas[n]) and not self.replicas[n].reachable
        ]
        if self.mode == "random":
            with self._rng_lock:
                self._rng.shuffle(eligible)
            return eligible + stale, RANDOM
        if not eligible:
            return stale, FAILOVER
        depths = {n: self.replicas[n].queue_depth for n in eligible}
        home = eligible[0]
        least = min(depths.values())
        if depths[home] - least >= self.overflow_depth:
            # Home is a hot shard: start at the least-loaded eligible
            # replica, keeping ring order after it (rotation preserves
            # the deterministic failover sequence).
            start = min(
                range(len(eligible)), key=lambda i: depths[eligible[i]]
            )
            rotated = eligible[start:] + eligible[:start]
            return rotated + stale, OVERFLOW
        return eligible + stale, HOME

    def min_wait_estimate_s(self, per_request_s: float) -> float:
        """The fleet's BEST-case queue forecast: the smallest
        (queue depth x router-measured per-request service time) over
        the non-draining reachable replicas.  The deadline fast-fail
        gate asks this before dispatching: when even the emptiest
        replica cannot answer inside the remaining budget, 504 now
        beats enqueueing work whose tokens will arrive too late.
        Conservatively 0.0 (always feasible) when nothing is polled or
        the service-time estimate is missing — fail-fast must never
        fire on a guess."""
        if per_request_s <= 0:
            return 0.0
        depths = [
            st.queue_depth
            for st in self.replicas.values()
            if st.reachable
            and not st.draining
            and not st.fenced
            and st.role != "prefill"
        ]
        if not depths:
            return 0.0
        return min(depths) * per_request_s
