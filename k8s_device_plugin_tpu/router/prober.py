"""Active correctness plane — the fleet canary prober (ISSUE 17).

Every observability layer before this one is *passive*: tracing, flight
rings, SLO burn rates all watch traffic that already happened, and none
of them can tell a replica that is **fast but wrong** from a healthy
one.  Silent data corruption on accelerators is a real fleet-scale
failure mode (Exploration of TPUs for AI Applications, PAPERS.md), and
host-side probing is the recommended way to catch it without device
counters (Host-Side Telemetry, PAPERS.md).

:class:`CanaryProber` continuously dials every replica — direct, and
optionally through the router itself — with seeded deterministic greedy
canary prompts.  Because decoding is greedy and the weights are fixed,
the token stream for a canary prompt is a *pure function of the params
fingerprint*: the oracle is captured once from the fleet's own first
clean response per ``(params_fingerprint, prompt)`` pair and every
later probe anywhere in the fleet must reproduce it **bit-exactly**.  A
redeploy with new weights shows up as a new fingerprint on the
``?summary=1`` poll and simply re-captures — no operator-maintained
golden files.

Verdicts per probe:

- ``capture``  — first clean response for this (fingerprint, prompt):
  becomes the oracle.
- ``match``    — bit-exact against the oracle (also feeds the TTFT/ITL
  probe-latency histograms).
- ``mismatch`` — wrong tokens.  One blip NEVER acts: only ``k_mismatch``
  *consecutive* mismatches fire the ``canary.mismatch`` incident and —
  policy on by default, ``fence=False`` to observe-only — auto-fence
  the replica via its existing ``POST /debug/fence`` admin endpoint, so
  the router's fenced-demotion machinery (PR 10) drains it with zero
  client-visible wrong tokens.
- ``stale``    — the replica answers probes but its ``requests_total``
  summary counter stopped advancing (our own probes should bump it):
  zombie telemetry, ``canary.stale`` incident, no fence.
- ``error``    — probe dial failed (the router's breaker/poll plane
  already owns liveness; the prober just records and moves on).
- ``skip_fenced`` — replica reports fenced (by us or anyone): probing
  is pointless until it is unfenced/replaced.

Through-router probes verdict the *serving path* end to end but fire no
incidents and never fence: a wrong answer through the router cannot be
attributed to a replica — attribution is the direct probes' job.

jax-free, compile-free, fake-clock injectable: the unit suite drives
:meth:`CanaryProber.probe_once` sweep by sweep against FakeReplicas
with an injected clock; production wires :meth:`start`'s daemon thread
into RouterServer (``--canary=1``).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
from typing import Callable, Optional

VERDICTS = (
    "capture", "match", "mismatch", "stale", "error", "skip_fenced",
)

_CONN_ERRORS = (OSError, http.client.HTTPException, ValueError)

# Default seeded canary prompts: small fixed token ids, disjoint from
# nothing in particular — determinism, not meaning, is the point.
DEFAULT_PROMPTS = ((11, 13, 17, 19), (101, 103, 107))


@dataclasses.dataclass
class CanaryConfig:
    """Tunables for :class:`CanaryProber` (CLI: ``--canary-*``)."""

    # Seconds between sweeps (every replica probed once per sweep).
    interval_s: float = 5.0
    # New tokens per probe — tiny on purpose: the probe budget is the
    # overhead budget (bench pins it at <=1% of serving throughput).
    probe_tokens: int = 4
    # Canary prompt token lists; sweeps rotate through them so one
    # poisoned oracle can't blind the whole plane.
    prompts: tuple = DEFAULT_PROMPTS
    # Consecutive mismatches before the incident + auto-fence.  One
    # blip (a probe racing a restart, a torn read) must never fence.
    k_mismatch: int = 3
    # Consecutive sweeps with a frozen requests_total (while probes
    # land!) before the staleness incident.
    stale_sweeps: int = 5
    # Auto-fence policy: False = observe-only (incidents still fire).
    fence: bool = True
    # Per-dial timeout.
    timeout_s: float = 5.0
    # Also probe THROUGH the router (end-to-end path verdict)?
    via_router: bool = True

    def __post_init__(self):
        if self.k_mismatch < 1:
            raise ValueError("k_mismatch must be >= 1")
        if self.stale_sweeps < 2:
            raise ValueError("stale_sweeps must be >= 2")
        if self.probe_tokens < 1:
            raise ValueError("probe_tokens must be >= 1")
        if not self.prompts:
            raise ValueError("at least one canary prompt required")


class _ReplicaTrack:
    """Per-replica prober state (prober thread owns it; snapshot()
    reads under the lock)."""

    __slots__ = (
        "verdict", "mismatch_streak", "stale_streak", "last_requests",
        "probed_since_requests", "ttft_s", "itl_s", "fingerprint",
        "fenced_by_canary", "stale_reported", "probes", "mismatches",
    )

    def __init__(self):
        self.verdict = None
        self.mismatch_streak = 0
        self.stale_streak = 0
        self.last_requests = None
        self.probed_since_requests = False
        self.ttft_s = None
        self.itl_s = None
        self.fingerprint = None
        self.fenced_by_canary = False
        self.stale_reported = False
        self.probes = 0
        self.mismatches = 0


class CanaryProber:
    """Continuously verdict every replica on *correctness*, not just
    liveness.  ``targets_fn`` returns the current fleet as ``host:port``
    names (the router passes a snapshot of its replica table); the
    prober dials each directly and optionally dials ``router_url`` for
    the end-to-end path.

    Injectables: ``now`` (latency clock), ``metrics`` (RouterMetrics —
    canary families optional via getattr), ``flight``
    (FlightRecorder), ``anomaly`` (AnomalyMonitor for incidents)."""

    def __init__(
        self,
        targets_fn: Callable[[], list],
        *,
        config: Optional[CanaryConfig] = None,
        router_url: Optional[str] = None,
        metrics=None,
        flight=None,
        anomaly=None,
        now=time.monotonic,
    ):
        self.cfg = config or CanaryConfig()
        self._targets_fn = targets_fn
        self._router_url = router_url
        self._metrics = metrics
        self._flight = flight
        self._anomaly = anomaly
        self._now = now
        self._lock = threading.Lock()
        # (params_fingerprint, prompt_index) -> tuple of oracle tokens.
        # Shared across replicas on purpose: same weights, greedy
        # decode => same tokens, so replica B is verdicted against the
        # oracle replica A captured — cross-replica SDC detection.
        self._oracles: dict = {}
        self._tracks: dict[str, _ReplicaTrack] = {}
        self._router_verdict: Optional[str] = None
        self.sweeps = 0
        self.fences_fired = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ dials

    def _split(self, name: str):
        host, _, port = name.rpartition(":")
        return host, int(port)

    def _get_summary(self, name: str) -> dict:
        host, port = self._split(name)
        conn = http.client.HTTPConnection(
            host, port, timeout=self.cfg.timeout_s
        )
        try:
            conn.request("GET", "/debug/state?summary=1")
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            if resp.status != 200:
                raise OSError(f"summary HTTP {resp.status}")
            return payload
        finally:
            conn.close()

    def _probe_dial(self, name: str, prompt) -> tuple:
        """One streamed greedy probe: returns (tokens, ttft_s, itl_s).
        Streaming on purpose — TTFT/ITL are per-probe *latency* SLIs,
        and a unary dial can't see first-token time."""
        host, port = self._split(name)
        body = json.dumps({
            "prompt": list(prompt),
            "max_new_tokens": self.cfg.probe_tokens,
            "stream": True,
        }).encode()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.cfg.timeout_s
        )
        try:
            t0 = self._now()
            conn.request(
                "POST", "/generate", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            if resp.status != 200:
                raise OSError(f"probe HTTP {resp.status}")
            tokens: list = []
            final = None
            ttft = None
            gaps: list = []
            last = t0
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                ev = json.loads(line[5:].strip() or b"{}")
                if ev.get("done"):
                    final = ev.get("tokens")
                    break
                if "token" in ev:
                    t = self._now()
                    if ttft is None:
                        ttft = t - t0
                    else:
                        gaps.append(t - last)
                    last = t
                    tokens.append(int(ev["token"]))
            if final is not None:
                tokens = [int(t) for t in final]
            if not tokens:
                raise OSError("probe stream ended with no tokens")
            itl = sum(gaps) / len(gaps) if gaps else 0.0
            return tokens, (ttft if ttft is not None else 0.0), itl
        finally:
            conn.close()

    def _fence_dial(self, name: str) -> bool:
        host, port = self._split(name)
        body = json.dumps({"reason": "canary-mismatch"}).encode()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.cfg.timeout_s
        )
        try:
            conn.request(
                "POST", "/debug/fence", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except _CONN_ERRORS:
            return False
        finally:
            conn.close()

    # ---------------------------------------------------------- verdicts

    def _record(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.record(kind, **fields)

    def _count(self, name: str, verdict: str) -> None:
        m = getattr(self._metrics, "canary_probes", None)
        if m is not None:
            m.inc(replica=name, verdict=verdict)

    def _verdict_one(self, name: str, prompt_idx: int) -> str:
        """Probe one replica, return its verdict (prober thread)."""
        cfg = self.cfg
        prompt = cfg.prompts[prompt_idx]
        with self._lock:
            track = self._tracks.setdefault(name, _ReplicaTrack())

        try:
            summary = self._get_summary(name)
        except _CONN_ERRORS as e:
            return self._finish(track, name, "error", error=str(e))

        if bool(summary.get("fenced", False)):
            # Already fenced (by us, an operator, or a watchdog):
            # probing a fenced replica proves nothing — it answers 503.
            return self._finish(track, name, "skip_fenced")

        fp = summary.get("params_fingerprint")
        raw_requests = summary.get("requests_total")
        requests_total = (
            int(raw_requests) if raw_requests is not None else None
        )

        # Staleness: our OWN probes bump the engine's requests counter,
        # so a summary whose requests_total sat frozen across a sweep
        # in which we landed a probe is lying about its traffic —
        # zombie telemetry (metrics thread wedged, ring detached).
        stale_now = False
        if requests_total is not None:
            if (
                track.last_requests is not None
                and requests_total <= track.last_requests
                and track.probed_since_requests
            ):
                track.stale_streak += 1
            elif requests_total > (track.last_requests or -1):
                track.stale_streak = 0
                track.stale_reported = False
            track.last_requests = requests_total
            stale_now = track.stale_streak >= cfg.stale_sweeps

        try:
            tokens, ttft, itl = self._probe_dial(name, prompt)
        except _CONN_ERRORS as e:
            track.probed_since_requests = False
            return self._finish(track, name, "error", error=str(e))
        track.probed_since_requests = True
        track.ttft_s = ttft
        track.itl_s = itl
        h = getattr(self._metrics, "canary_probe_ttft", None)
        if h is not None:
            h.observe(ttft)
        h = getattr(self._metrics, "canary_probe_itl", None)
        if h is not None:
            h.observe(itl)

        if stale_now and not track.stale_reported:
            track.stale_reported = True
            self._record(
                "canary.stale", replica=name,
                requests_total=requests_total,
                sweeps=track.stale_streak,
            )
            if self._anomaly is not None:
                self._anomaly.report(
                    "canary.stale", observed=float(track.stale_streak),
                    replica=name,
                )
        if stale_now:
            return self._finish(track, name, "stale", fingerprint=fp)

        if fp is None:
            # Pre-contract replica (old build): nothing to key an
            # oracle by — latency histograms still fed above.
            return self._finish(track, name, "error",
                                error="no params_fingerprint")

        key = (fp, prompt_idx)
        with self._lock:
            oracle = self._oracles.get(key)
            if oracle is None:
                # First clean response for this (weights, prompt):
                # becomes the fleet-wide oracle.  A redeploy is a new
                # fingerprint, hence a fresh capture — self-refreshing.
                self._oracles[key] = tuple(tokens)
        if oracle is None:
            track.fingerprint = fp
            self._record(
                "canary.capture", replica=name, fingerprint=fp,
                prompt=prompt_idx, tokens=list(tokens),
            )
            return self._finish(track, name, "capture", fingerprint=fp)

        track.fingerprint = fp
        if tuple(tokens) == oracle:
            track.mismatch_streak = 0
            return self._finish(track, name, "match", fingerprint=fp)

        # Wrong tokens.  Count the streak; act only on K consecutive.
        track.mismatch_streak += 1
        track.mismatches += 1
        self._record(
            "canary.mismatch_observed", replica=name,
            streak=track.mismatch_streak, prompt=prompt_idx,
            got=list(tokens), want=list(oracle),
        )
        if track.mismatch_streak == cfg.k_mismatch:
            # The confirmed-SDC incident: exactly once per episode.
            self._record(
                "canary.mismatch", replica=name, fingerprint=fp,
                streak=track.mismatch_streak,
            )
            if self._anomaly is not None:
                self._anomaly.report(
                    "canary.mismatch",
                    observed=float(track.mismatch_streak),
                    replica=name,
                )
        if track.mismatch_streak >= cfg.k_mismatch and cfg.fence:
            # Auto-fence through the replica's own admin endpoint: the
            # router's summary poll sees fenced=true and demotes it
            # through the PR-10 fenced-demotion path (in-flight streams
            # fail over, new work re-routes).  Retried every sweep the
            # mismatch persists, in case admin was briefly down.
            if self._fence_dial(name):
                track.fenced_by_canary = True
                self.fences_fired += 1
                c = getattr(self._metrics, "canary_fences", None)
                if c is not None:
                    c.inc(replica=name)
                self._record("canary.fence", replica=name, fingerprint=fp)
            else:
                self._record("canary.fence_failed", replica=name)
        return self._finish(track, name, "mismatch", fingerprint=fp)

    def _finish(self, track, name: str, verdict: str, **fields) -> str:
        with self._lock:
            track.verdict = verdict
            track.probes += 1
        self._count(name, verdict)
        return verdict

    def _probe_router(self, prompt_idx: int) -> None:
        """One through-router probe: end-to-end path verdict.  Never an
        incident, never a fence — a wrong answer here cannot be pinned
        on a replica; the direct probes own attribution."""
        prompt = self.cfg.prompts[prompt_idx]
        with self._lock:
            fps = {
                t.fingerprint for t in self._tracks.values()
                if t.fingerprint is not None
            }
        try:
            tokens, ttft, itl = self._probe_dial(self._router_url, prompt)
        except _CONN_ERRORS:
            verdict = "error"
        else:
            if len(fps) != 1:
                # Mixed-fingerprint fleet mid-rollout (or nothing
                # captured yet): no single oracle to hold the router
                # path to — capture-equivalent no-op.
                verdict = "capture"
            else:
                oracle = self._oracles.get((next(iter(fps)), prompt_idx))
                if oracle is None:
                    verdict = "capture"
                elif tuple(tokens) == oracle:
                    verdict = "match"
                else:
                    verdict = "mismatch"
                    self._record(
                        "canary.router_mismatch", prompt=prompt_idx,
                        got=list(tokens), want=list(oracle),
                    )
        with self._lock:
            self._router_verdict = verdict
        self._count("router", verdict)

    # ------------------------------------------------------------ sweeps

    def probe_once(self) -> dict:
        """One full sweep: every current target direct-probed, plus the
        through-router probe.  Returns {name: verdict} (the unit-test
        driving seam — production calls this from the daemon thread)."""
        prompt_idx = self.sweeps % len(self.cfg.prompts)
        verdicts = {}
        for name in list(self._targets_fn()):
            if self._stop.is_set():
                break
            verdicts[str(name)] = self._verdict_one(str(name), prompt_idx)
        if self.cfg.via_router and self._router_url:
            self._probe_router(prompt_idx)
        self.sweeps += 1
        return verdicts

    def snapshot(self) -> dict:
        """The ``GET /debug/canary`` body (any thread)."""
        with self._lock:
            replicas = {
                name: {
                    "verdict": t.verdict,
                    "mismatch_streak": t.mismatch_streak,
                    "stale_streak": t.stale_streak,
                    "probes": t.probes,
                    "mismatches": t.mismatches,
                    "ttft_s": t.ttft_s,
                    "itl_s": t.itl_s,
                    "params_fingerprint": t.fingerprint,
                    "fenced_by_canary": t.fenced_by_canary,
                }
                for name, t in self._tracks.items()
            }
            oracles = [
                {"params_fingerprint": fp, "prompt": idx, "tokens": list(v)}
                for (fp, idx), v in self._oracles.items()
            ]
            router_verdict = self._router_verdict
        return {
            "sweeps": self.sweeps,
            "fences_fired": self.fences_fired,
            "router_verdict": router_verdict,
            "oracles": oracles,
            "replicas": replicas,
            "config": {
                "interval_s": self.cfg.interval_s,
                "probe_tokens": self.cfg.probe_tokens,
                "prompts": [list(p) for p in self.cfg.prompts],
                "k_mismatch": self.cfg.k_mismatch,
                "stale_sweeps": self.cfg.stale_sweeps,
                "fence": self.cfg.fence,
                "via_router": bool(
                    self.cfg.via_router and self._router_url
                ),
            },
        }

    # --------------------------------------------------------- lifecycle

    def start(self) -> "CanaryProber":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="canary-prober", daemon=True
        )
        self._thread.start()
        self._record("canary.started", interval_s=self.cfg.interval_s)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.probe_once()
            except Exception as e:  # pragma: no cover - belt and braces
                self._record("canary.sweep_error", error=str(e))

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._record("canary.stopped", sweeps=self.sweeps)
