"""`python -m k8s_device_plugin_tpu.router` — the router daemon entry
(deploy/k8s-deploy-router.yaml)."""

from .server import main

if __name__ == "__main__":
    main()
