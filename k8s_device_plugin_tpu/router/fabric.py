"""Fleet-wide content-addressed KV fabric: prefix locator + replication.

The engines already move KV pages peer-to-peer (``POST /v1/prefill``,
models/engine_handoff.py) and already advertise a bloom digest of their
resident prefix roots on every ``?summary=1`` poll
(``fabric_digest``, utils/prefixbloom.py).  This module is the router
half that turns those digests into fleet behavior:

- **Locator** (:class:`FabricLocator`): per-replica digest views parsed
  off the poll, answering "who in the fleet advertises the deepest
  page-aligned cumulative prefix of THIS prompt?".  The server asks per
  upstream dial — primary, retry, hedge, failover and migration legs
  alike — and stamps the best owner as ``X-Handoff-Source`` (plus
  ``X-Fabric-Resident-Only``) whenever the dial target itself does not
  advertise the prefix.  Candidates are filtered to live, unfenced,
  undraining replicas AT RESOLVE TIME, so a re-dialed leg can never be
  pointed at a dead or fenced peer: every leg re-resolves.
- **Replication/eviction policy** (:class:`FabricReplicator`): the
  poll-thread planner that keeps HOT prefixes (live-stream count x
  prefix depth — the migration planner's hottest-prefix ranking, made
  depth-aware) on up to ``replicate_k`` replicas while their owners run
  hot, and drops the router-created copies back down when the prefix
  goes cold.  Actions are bounded per sweep and ride the engines'
  admin ``POST /debug/fabric/pull`` / ``/debug/fabric/drop`` endpoints;
  both move HOST-ARENA bytes only (pressure-driven, host-observable
  signals — never device counters).

Failure semantics inherited from the layers below: a bloom false
positive or a stale digest stamps an owner that serves nothing — the
puller's parse-before-admit verifier admits ZERO entries and the
request degrades to a local prefill, bit-identical output.  The fabric
can waste a fetch; it cannot corrupt a stream.

Pure stdlib + utils; jax is never imported here.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..utils.prefixbloom import PrefixBloom

# The base model's trie pseudo-root (engine_paging.py).  Adapter
# requests use engine-local negative roots the router cannot know, so
# the locator resolves base-model prompts only and reports ``skip``
# for adapter traffic (which still rides affinity + the classic
# prefill-pool path unchanged).
BASE_ROOT = -1

# Locator verdicts (tpu_router_fabric_resolutions_total label values).
VERDICT_HIT = "hit"            # stamped a better owner than the target
VERDICT_RESIDENT = "resident"  # target already advertises the prefix
VERDICT_MISS = "miss"          # nobody in the fleet advertises it
VERDICT_SKIP = "skip"          # adapter prompt — engine-local roots
VERDICTS = (VERDICT_HIT, VERDICT_RESIDENT, VERDICT_MISS, VERDICT_SKIP)


@dataclasses.dataclass
class FabricConfig:
    """Tunables for the fabric plane (CLI: ``--fabric-*``)."""

    # Target replication factor for hot prefixes: copies are planned
    # until a hot prefix is advertised by this many replicas.
    replicate_k: int = 2
    # An owner whose queue-wait pressure runs at/above this is hot —
    # the trigger for proactive copies of its hot prefixes.
    hot_wait_s: float = 2.0
    # A replication TARGET must sit at/below this pressure: copying
    # into a busy replica trades one hotspot for another.
    cold_wait_s: float = 0.5
    # Minimum hotness score (live streams x full prefix pages) before
    # a prefix is worth replicating at all.
    hot_score: float = 2.0
    # Replication + eviction actions fired per poll sweep, fleet-wide
    # (each is one engine-side pull or drop) — the pacing bound.
    max_actions_per_sweep: int = 2
    # Consecutive zero-stream sweeps before a router-created copy is
    # dropped back (one idle poll tick must never thrash the arena).
    cold_sweeps: int = 3
    # Ledgered copies whose target still does not advertise the prefix
    # after this many sweeps are presumed failed and forgotten (the
    # self-healing path for a pull that errored or was evicted).
    confirm_sweeps: int = 3
    # Engine-side pull deadline (the whole wire transfer).
    pull_timeout_s: float = 30.0
    # Page size assumed until a digest advertises one (fleets are
    # homogeneous; the per-replica advertised value always wins).
    default_page_size: int = 16

    def __post_init__(self):
        if self.replicate_k < 1:
            raise ValueError(
                f"replicate_k must be >= 1, got {self.replicate_k}"
            )
        if self.hot_wait_s <= self.cold_wait_s:
            raise ValueError(
                "hot_wait_s must exceed cold_wait_s "
                f"({self.hot_wait_s} <= {self.cold_wait_s})"
            )
        if self.max_actions_per_sweep < 1:
            raise ValueError("max_actions_per_sweep must be >= 1")


class _DigestView:
    """One replica's parsed advertisement: an immutable-after-publish
    bloom plus the page geometry it was built against."""

    __slots__ = ("bloom", "page_size", "at")

    def __init__(self, bloom: PrefixBloom, page_size: int):
        self.bloom = bloom
        self.page_size = page_size
        self.at = time.monotonic()


class FabricLocator:
    """Per-replica digest views + the best-owner query.

    Views are written by the poll thread (one :meth:`update` per
    replica per sweep) and read by every request/stream thread at dial
    time, so the view dict sits behind a leaf lock; the blooms
    themselves are never mutated after publish and are queried
    lock-free."""

    def __init__(self, default_page_size: int = 16):
        self._default_page_size = int(default_page_size)
        self._views: dict[str, _DigestView] = {}  # guarded by: _lock
        self._lock = threading.Lock()

    # ------------------------------------------------------- poll side

    def update(self, name: str, wire: object) -> int:
        """Parse one replica's advertised digest (poll thread).
        Returns the advertised root count (0 when the replica sent no
        digest or an unparseable one — either way the locator simply
        cannot place that replica until a good poll)."""
        bloom = PrefixBloom.from_wire(wire)
        if bloom is None:
            with self._lock:
                self._views.pop(name, None)
            return 0
        page_size = self._default_page_size
        if isinstance(wire, dict):
            try:
                page_size = max(1, int(wire.get("page_size", page_size)))
            except (TypeError, ValueError):
                pass
        with self._lock:
            self._views[name] = _DigestView(bloom, page_size)
        return bloom.count

    def forget(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    # ---------------------------------------------------- query side

    def _view(self, name: str) -> Optional[_DigestView]:
        with self._lock:
            return self._views.get(name)

    def page_size(self) -> int:
        """The fleet's advertised page size (first view's; fleets are
        homogeneous by deployment contract), or the default."""
        with self._lock:
            for view in self._views.values():
                return view.page_size
        return self._default_page_size

    def coverage(
        self, name: str, prompt, root: int = BASE_ROOT
    ) -> int:
        """Deepest advertised page-aligned cumulative prefix of
        ``prompt`` on ``name``, in TOKENS (0 = nothing advertised).
        Walks deepest-first: the digest has no false negatives, so the
        first hit is the true depth — or a bloom FP overclaiming, which
        the serving side's resident-only 409 turns into a degraded
        local prefill, never wrong tokens."""
        view = self._view(name)
        if view is None:
            return 0
        ps = view.page_size
        for pages in range(len(prompt) // ps, 0, -1):
            if view.bloom.contains(root, prompt[: pages * ps]):
                return pages * ps
        return 0

    def best_owner(
        self, prompt, candidates, root: int = BASE_ROOT
    ) -> Optional[tuple[str, int]]:
        """(owner, covered tokens) — the candidate advertising the
        deepest cumulative prefix of ``prompt`` (deterministic name
        tie-break), or None when nobody advertises anything.  The
        CALLER filters ``candidates`` to live/unfenced/undraining
        peers at resolve time — the never-a-dead-peer contract."""
        best: Optional[tuple[int, str]] = None
        for name in candidates:
            covered = self.coverage(name, prompt, root)
            if covered <= 0:
                continue
            # Deepest coverage wins; ties break toward the smaller
            # name so repeated resolutions are stable.
            if best is None or (-covered, name) < (-best[0], best[1]):
                best = (covered, name)
        if best is None:
            return None
        return best[1], best[0]

    def owners(
        self, prompt, candidates, root: int = BASE_ROOT
    ) -> list[str]:
        """Candidates advertising the FULL page-aligned prefix of
        ``prompt`` (every complete page — the replication-factor
        census, not the best-effort dial locator)."""
        out = []
        for name in candidates:
            view = self._view(name)
            if view is None:
                continue
            pages = len(prompt) // view.page_size
            if pages < 1:
                continue
            if self.coverage(name, prompt, root) >= pages * view.page_size:
                out.append(name)
        return out

    def advertised_roots(self) -> dict[str, int]:
        """{replica: advertised prefix-root count} — what
        ``tools/fleet_plan.py`` renders per replica."""
        with self._lock:
            return {
                name: view.bloom.count
                for name, view in self._views.items()
            }

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                name: {
                    "advertised_roots": view.bloom.count,
                    "page_size": view.page_size,
                    "age_s": round(now - view.at, 3),
                }
                for name, view in sorted(self._views.items())
            }


class FabricReplicator:
    """K-replica hot-prefix replication + cold eviction planner.

    Single-threaded by contract: the router's poll thread owns it (the
    MigrationPlanner discipline).  Feed one :meth:`plan` per sweep with
    the live hot-prefix census and the eligible replicas' pressures; it
    answers a BOUNDED list of pull/drop actions and keeps the ledger of
    copies the router itself created — eviction only ever drops those,
    never a replica's traffic-warmed working set."""

    def __init__(self, config: Optional[FabricConfig] = None):
        self.cfg = config or FabricConfig()
        # Ledger of router-created copies:
        # (prefix tokens) -> {target: sweeps since the pull was planned}.
        self._ledger: dict[tuple, dict[str, int]] = {}
        # Consecutive zero-stream sweeps per replicated prefix.
        self._cold_streaks: dict[tuple, int] = {}
        self.pulls_planned = 0
        self.drops_planned = 0

    def forget(self, name: str) -> None:
        """Membership removal: a vanished replica's ledger entries are
        moot (its arena died with it)."""
        for targets in self._ledger.values():
            targets.pop(name, None)

    def plan(
        self,
        locator: FabricLocator,
        hot_prefixes: dict[tuple, int],
        pressures: dict[str, float],
    ) -> list[dict]:
        """One sweep's actions (at most ``max_actions_per_sweep``).

        ``hot_prefixes``: {prefix token tuple: live stream count} from
        the router's stream registry.  ``pressures``: {name: queue-wait
        pressure seconds} over the ELIGIBLE decode-capable replicas —
        the same host-side signals migration planning reads.
        """
        cfg = self.cfg
        ps = locator.page_size()
        actions: list[dict] = []
        names = list(pressures)

        # Ledger upkeep: age every entry; forget copies whose target
        # still does not advertise the prefix after the confirm window
        # (failed pull, or the target evicted it under memory pressure).
        for prefix, targets in list(self._ledger.items()):
            pages = len(prefix) // ps
            for target in list(targets):
                targets[target] += 1
                if targets[target] >= cfg.confirm_sweeps and (
                    locator.coverage(target, list(prefix)) < pages * ps
                ):
                    del targets[target]
            if not targets:
                self._ledger.pop(prefix, None)
                self._cold_streaks.pop(prefix, None)

        # --- replication: hottest prefixes first, owners running hot.
        ranked = sorted(
            hot_prefixes.items(),
            key=lambda item: (-(item[1] * (len(item[0]) // ps)), item[0]),
        )
        for prefix, streams in ranked:
            if len(actions) >= cfg.max_actions_per_sweep:
                break
            pages = len(prefix) // ps
            if pages < 1 or streams * pages < cfg.hot_score:
                continue
            owners = locator.owners(list(prefix), names)
            if not owners:
                # Nobody advertises it yet — the next local prefill
                # warms an owner; nothing to copy FROM.
                continue
            # Copies already planned count as owners until confirmed,
            # or one hot prefix would fan out past K while digests lag
            # a poll tick behind the pulls.
            effective = set(owners) | set(self._ledger.get(prefix, ()))
            if len(effective) >= cfg.replicate_k:
                continue
            if max(pressures[o] for o in owners) < cfg.hot_wait_s:
                continue  # owners comfortable; affinity already works
            targets = sorted(
                (pressures[n], n)
                for n in names
                if n not in effective and pressures[n] <= cfg.cold_wait_s
            )
            if not targets:
                continue  # no cold headroom — a scale signal, not a copy
            target = targets[0][1]
            source = min(owners, key=lambda o: (pressures[o], o))
            self._ledger.setdefault(prefix, {})[target] = 0
            self._cold_streaks.pop(prefix, None)
            self.pulls_planned += 1
            actions.append(
                {
                    "op": "pull",
                    "target": target,
                    "source": source,
                    "prompt": list(prefix[: pages * ps]),
                    "streams": streams,
                    "pages": pages,
                }
            )

        # --- eviction: router-created copies of prefixes gone cold are
        # dropped back toward replication factor 1 (the traffic-warmed
        # origin keeps its own copy; we only release what we added).
        for prefix in sorted(self._ledger):
            if len(actions) >= cfg.max_actions_per_sweep:
                break
            if hot_prefixes.get(prefix, 0) > 0:
                self._cold_streaks.pop(prefix, None)
                continue
            streak = self._cold_streaks.get(prefix, 0) + 1
            self._cold_streaks[prefix] = streak
            if streak < cfg.cold_sweeps:
                continue
            targets = self._ledger.get(prefix, {})
            while targets and len(actions) < cfg.max_actions_per_sweep:
                target = sorted(targets)[0]
                del targets[target]
                self.drops_planned += 1
                actions.append(
                    {
                        "op": "drop",
                        "target": target,
                        "prompt": list(prefix),
                    }
                )
            if not targets:
                self._ledger.pop(prefix, None)
                self._cold_streaks.pop(prefix, None)
        return actions

    def replication_factor(
        self, locator: FabricLocator, prefix: tuple, names
    ) -> int:
        """How many replicas advertise this full prefix right now."""
        return len(locator.owners(list(prefix), names))

    def snapshot(self) -> dict:
        """JSON-safe planner state for GET /debug/fabric."""
        return {
            "replicate_k": self.cfg.replicate_k,
            "hot_wait_s": self.cfg.hot_wait_s,
            "cold_wait_s": self.cfg.cold_wait_s,
            "pulls_planned": self.pulls_planned,
            "drops_planned": self.drops_planned,
            "ledger": [
                {
                    "prefix_tokens": len(prefix),
                    "targets": sorted(targets),
                    "cold_streak": self._cold_streaks.get(prefix, 0),
                }
                for prefix, targets in sorted(self._ledger.items())
            ],
        }
