"""KV-affine consistent hashing: prefix keys + the replica ring.

Why a hash ring and not a least-loaded pick: the serving replicas keep
content-addressed KV tiers (models/engine_kvcache.py) — a repeated
system prompt is only cheap on the replica that already holds its prefix
pages.  The router therefore needs a placement function that is (a)
**sticky** — the same prompt prefix always lands on the same replica,
across router restarts and across routers (no shared state), and (b)
**minimally disruptive** — adding or removing one replica must remap
only ~1/K of the keyspace, not reshuffle every session's warm prefix.
Consistent hashing with virtual nodes is exactly that function; the
ring order after the home replica doubles as the deterministic failover
order, so a failed-over stream re-prefills on the SAME replica every
time (where its restore then hits).

Keys are built from the prompt's leading **prefix blocks** (page-sized
token groups, `prefix_key`): requests sharing a system prompt share
their leading blocks, hash to one key, and ride one replica's KV —
while the long unique tail stays out of the key so it cannot scatter a
shared prefix across the fleet.

Stdlib-only and jax-free (hashlib, bisect); deterministic everywhere —
no process-seeded hashing (`hash()` is salted per process and would
desync routers).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional, Sequence

# Page-sized default: matches the serving default --page-size=16, so a
# prefix block is exactly one KV page worth of tokens.
DEFAULT_BLOCK_TOKENS = 16
DEFAULT_MAX_BLOCKS = 4


def _hash64(data: bytes) -> int:
    """Stable 64-bit hash (sha1 prefix): identical across processes,
    platforms, and restarts — the property builtin hash() lacks."""
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


def prefix_key(
    prompt: Sequence[int],
    block_tokens: int = DEFAULT_BLOCK_TOKENS,
    max_blocks: int = DEFAULT_MAX_BLOCKS,
) -> int:
    """Hash the prompt's leading prefix blocks into a ring key.

    The first ``min(len, block_tokens * max_blocks)`` tokens, rounded
    DOWN to a block boundary, form the key — so prompts sharing a
    system prefix but differing in their tails (or in trailing partial
    blocks) collapse to one key.  Prompts shorter than one block key on
    their whole content (a 3-token prompt still routes consistently).
    """
    if block_tokens < 1:
        raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
    if max_blocks < 1:
        raise ValueError(f"max_blocks must be >= 1, got {max_blocks}")
    take = min(len(prompt), block_tokens * max_blocks)
    if take >= block_tokens:
        take -= take % block_tokens
    head = prompt[:take] if take else prompt[:]
    blob = b",".join(b"%d" % int(t) for t in head)
    return _hash64(blob)


class HashRing:
    """Ketama-style consistent hash ring with virtual nodes.

    Each node owns ``vnodes`` points on a 64-bit ring; a key maps to the
    first point clockwise from its hash.  ``order(key)`` walks the ring
    and returns every DISTINCT node in encounter order — position 0 is
    the affinity home, the rest is the deterministic failover order.

    Not thread-safe by itself; the router mutates it only under its own
    state lock (membership changes are rare — DNS refresh, drain).
    """

    def __init__(self, nodes: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: list[int] = []  # sorted hash points
        self._owner: dict[int, str] = {}  # point -> node
        self._nodes: set[str] = set()
        for node in nodes:
            self.add(node)

    # ------------------------------------------------------- membership

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.vnodes):
            point = _hash64(f"{node}#{i}".encode())
            # Point collisions across nodes are astronomically unlikely
            # on a 64-bit ring; first owner wins deterministically.
            if point in self._owner:
                continue
            self._owner[point] = node
            bisect.insort(self._points, point)

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        keep = [p for p in self._points if self._owner[p] != node]
        for p in self._points:
            if self._owner[p] == node:
                del self._owner[p]
        self._points = keep

    @property
    def nodes(self) -> set[str]:
        return set(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    # ----------------------------------------------------------- lookup

    def lookup(self, key: int) -> Optional[str]:
        """The node owning ``key`` (None on an empty ring)."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, key % (1 << 64))
        if i == len(self._points):
            i = 0
        return self._owner[self._points[i]]

    def order(self, key: int, limit: Optional[int] = None) -> list[str]:
        """Distinct nodes in ring order starting at ``key``'s owner —
        the affinity-home-then-failover sequence.  ``limit`` caps the
        list (default: every node)."""
        if not self._points:
            return []
        want = len(self._nodes) if limit is None else min(limit, len(self._nodes))
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_right(self._points, key % (1 << 64))
        n = len(self._points)
        for step in range(n):
            node = self._owner[self._points[(start + step) % n]]
            if node in seen:
                continue
            seen.add(node)
            out.append(node)
            if len(out) >= want:
                break
        return out

    def snapshot(self) -> dict:
        """JSON-safe ring summary for /debug/router."""
        return {
            "vnodes": self.vnodes,
            "nodes": sorted(self._nodes),
            "points": len(self._points),
        }
