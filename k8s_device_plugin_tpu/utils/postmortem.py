"""Incident-triggered postmortem capture + dump-dir retention.

The forensics layer so far is *passive*: flight rings and span rings
roll, ``/debug`` surfaces answer while the process lives, and the only
durable record is whatever SIGUSR2/atexit dump happened to be asked
for.  When an anomaly incident fires, the evidence an operator needs is
exactly the state that is about to rot.  This module closes that gap
(arXiv:2510.16946's host-side diagnosis argument, applied at incident
time):

- :class:`PostmortemCapture` — a full-record incident listener
  (``AnomalyMonitor.add_listener``) that atomically snapshots the local
  flight ring, span ring, metrics exposition, and a ``/debug/state``-
  equivalent into a content-addressed bundle directory under the dump
  dir.  Debounced per incident key: one capture per episode, not one
  per cooldown re-fire.  Emits ``postmortem.captured`` /
  ``postmortem.skipped`` flight events and the
  ``tpu_postmortem_captures_total{trigger,outcome}`` /
  ``tpu_postmortem_bundle_bytes`` metrics.
- :func:`sweep_dump_dir` — the byte/count-budgeted LRU pruner shared by
  BOTH dump-dir writers (flight dumps and postmortem bundles):
  oldest-first by mtime, never touching an in-progress bundle (the
  ``.inprogress`` staging suffix) or the entry just written.  Emits
  ``postmortem.pruned`` flight events.

Bundle layout (``postmortem-<component>-<ts>-<digest12>/``)::

    manifest.json   schema, component, incident key/trigger, ts,
                    per-file sha256 digests + sizes, bundle digest
    incident.json   the full incident record (flight window included)
    flight.json     FlightRecorder.snapshot()
    spans.json      SpanRecorder.dump()
    metrics.prom    the Prometheus exposition text at capture time
    state.json      the component's /debug/state-equivalent snapshot

Content addressing: the bundle digest is the sha256 over the evidence
files' bytes; a re-capture producing byte-identical evidence (possible
when nothing moved between two incidents) is deduplicated as outcome
``duplicate`` rather than written twice.  Everything is stdlib-only and
never raises into the caller — a capture failure must not poison
detection (same contract as ``flight.dump_all``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
from typing import Optional

log = logging.getLogger("tpu.postmortem")


def metric_families(registry):
    """Get-or-create the ``tpu_postmortem_*`` families on ``registry``:
    (captures_total counter, bundle_bytes gauge).  Lookup-first so two
    hooks on one process-wide registry (or a re-built daemon in tests)
    share the families instead of raising on duplicate registration."""
    captures = registry.get("tpu_postmortem_captures_total")
    if captures is None:
        captures = registry.counter(
            "tpu_postmortem_captures_total",
            "postmortem capture attempts by trigger and outcome",
            labelnames=("trigger", "outcome"),
        )
    bundle_bytes = registry.get("tpu_postmortem_bundle_bytes")
    if bundle_bytes is None:
        bundle_bytes = registry.gauge(
            "tpu_postmortem_bundle_bytes",
            "size of the last written postmortem bundle",
        )
    return captures, bundle_bytes

BUNDLE_SCHEMA = "tpu-postmortem-bundle/v1"
BUNDLE_PREFIX = "postmortem-"
# Staging suffix for a bundle being written: rename-published on
# completion, and the sweeper skips anything still carrying it.
INPROGRESS_SUFFIX = ".inprogress"
# The flight-dump file pattern the shared pruner also manages.
FLIGHT_DUMP_PREFIX = "tpu-flight-"

DEFAULT_DEBOUNCE_S = 120.0
DEFAULT_BUDGET_MB = 256


def _entry_bytes(path: str) -> int:
    """Total size of one dump-dir entry (file, or bundle dir walked)."""
    try:
        if os.path.isdir(path):
            total = 0
            for root, _dirs, files in os.walk(path):
                for name in files:
                    try:
                        total += os.path.getsize(os.path.join(root, name))
                    except OSError:
                        pass
            return total
        return os.path.getsize(path)
    except OSError:
        return 0


def _list_entries(directory: str) -> list[dict]:
    """Managed dump-dir entries (flight dumps + published bundles),
    oldest mtime first.  In-progress bundles are invisible to the
    sweeper by construction."""
    entries = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name.endswith(INPROGRESS_SUFFIX):
            continue
        managed = (
            name.startswith(BUNDLE_PREFIX)
            or (name.startswith(FLIGHT_DUMP_PREFIX) and name.endswith(".json"))
        )
        if not managed:
            continue
        path = os.path.join(directory, name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        entries.append(
            {"name": name, "path": path, "mtime": mtime,
             "bytes": _entry_bytes(path)}
        )
    entries.sort(key=lambda e: (e["mtime"], e["name"]))
    return entries


def sweep_dump_dir(
    directory: str,
    budget_bytes: Optional[int] = None,
    max_entries: Optional[int] = None,
    *,
    protect=(),
    flight=None,
) -> dict:
    """LRU-prune the dump dir to its byte/count budget; returns the
    sweep accounting ``{entries, bytes, pruned, pruned_bytes}``.

    Oldest-first by mtime, across BOTH writers' artifacts (flight-dump
    files and postmortem bundle dirs).  Never prunes an in-progress
    bundle (``.inprogress`` names are not even listed) or anything in
    ``protect`` (the entry a capture just published).  A ``flight``
    recorder, when given, gets one ``postmortem.pruned`` event per
    removed entry.  Never raises."""
    entries = _list_entries(directory)
    protected = {os.path.basename(p) for p in protect}
    total = sum(e["bytes"] for e in entries)
    count = len(entries)
    pruned = 0
    pruned_bytes = 0
    for entry in entries:
        over_bytes = budget_bytes is not None and total > budget_bytes
        over_count = max_entries is not None and count > max_entries
        if not (over_bytes or over_count):
            break
        if entry["name"] in protected:
            continue
        try:
            if os.path.isdir(entry["path"]):
                shutil.rmtree(entry["path"])
            else:
                os.remove(entry["path"])
        except OSError as e:
            log.warning("dump-dir prune of %s failed: %s", entry["path"], e)
            continue
        total -= entry["bytes"]
        count -= 1
        pruned += 1
        pruned_bytes += entry["bytes"]
        if flight is not None:
            flight.record(
                "postmortem.pruned",
                entry=entry["name"],
                bytes=entry["bytes"],
                age_s=round(max(time.time() - entry["mtime"], 0.0), 1),
            )
    return {
        "entries": count,
        "bytes": total,
        "pruned": pruned,
        "pruned_bytes": pruned_bytes,
    }


class PostmortemCapture:
    """The single-process capture hook: incident in, bundle dir out.

    Wire it into a component's :class:`~.anomaly.AnomalyMonitor` via
    ``monitor.add_listener(capture.on_incident)``; every emitted
    incident then snapshots the component's forensic state to disk —
    once per incident key per ``debounce_s`` episode window.

    ``state_fn`` is the component's ``/debug/state``-equivalent
    snapshot callable (JSON-serializable return); ``registry`` is both
    the exposition that gets bundled AND where this hook's own metrics
    register (pass ``metrics=False`` to skip registration when the
    registry already carries the families — e.g. a second hook on the
    same process).
    """

    def __init__(
        self,
        component: str,
        directory: str,
        *,
        flight=None,
        spans=None,
        registry=None,
        state_fn=None,
        debounce_s: float = DEFAULT_DEBOUNCE_S,
        budget_bytes: Optional[int] = None,
        max_entries: Optional[int] = None,
        metrics: bool = True,
        now=time.monotonic,
    ):
        self.component = str(component)
        self.directory = directory
        self.flight = flight
        self.spans = spans
        self.registry = registry
        self.state_fn = state_fn
        self.debounce_s = float(debounce_s)
        self.budget_bytes = budget_bytes
        self.max_entries = max_entries
        self._now = now
        self._lock = threading.Lock()
        self._last_capture: dict[str, float] = {}  # guarded by: _lock
        self._digests: set[str] = set()  # guarded by: _lock
        self.captures = 0
        self.skipped = 0
        self.last_bundle: Optional[str] = None
        self.last_error: Optional[str] = None
        self._captures_total = None
        self._bundle_bytes = None
        if registry is not None and metrics:
            self._captures_total, self._bundle_bytes = metric_families(
                registry
            )

    # ------------------------------------------------------------ hooks

    def on_incident(self, incident: dict) -> None:
        """The ``AnomalyMonitor.add_listener`` adapter: capture keyed by
        the incident's cause metric (one bundle per episode even while
        the detector re-fires each cooldown)."""
        key = str(incident.get("metric", "incident"))
        self.capture("incident", key=key, incident=incident)

    # ---------------------------------------------------------- capture

    def _account(self, trigger: str, outcome: str) -> None:
        if self._captures_total is not None:
            self._captures_total.inc(trigger=trigger, outcome=outcome)

    def _skip(self, trigger: str, key: str, reason: str) -> None:
        self.skipped += 1
        self._account(trigger, reason)
        if self.flight is not None:
            self.flight.record(
                "postmortem.skipped", key=key, trigger=trigger, reason=reason
            )

    def capture(
        self,
        trigger: str,
        *,
        key: str,
        incident: Optional[dict] = None,
    ) -> Optional[str]:
        """Snapshot the component's forensic state into one bundle dir;
        returns the published path, or None (debounced / duplicate /
        no dir / error — the outcome lands in the metrics and a
        ``postmortem.skipped`` flight event).  Never raises."""
        try:
            return self._capture(trigger, key, incident)
        except Exception as e:  # the listener contract: never poison
            log.exception("postmortem capture failed")
            self.last_error = str(e)
            self._skip(trigger, key, "error")
            return None

    def _capture(
        self, trigger: str, key: str, incident: Optional[dict]
    ) -> Optional[str]:
        if not self.directory:
            self._skip(trigger, key, "no_dir")
            return None
        now = self._now()
        with self._lock:
            last = self._last_capture.get(key)
            if last is not None and now - last < self.debounce_s:
                debounced = True
            else:
                debounced = False
                self._last_capture[key] = now
        if debounced:
            self._skip(trigger, key, "debounced")
            return None

        files: dict[str, bytes] = {}
        if incident is not None:
            files["incident.json"] = json.dumps(
                incident, separators=(",", ":"), default=str
            ).encode()
        if self.flight is not None:
            files["flight.json"] = json.dumps(
                self.flight.snapshot(), separators=(",", ":")
            ).encode()
        if self.spans is not None:
            files["spans.json"] = json.dumps(
                self.spans.dump(), separators=(",", ":")
            ).encode()
        if self.registry is not None:
            files["metrics.prom"] = self.registry.render().encode()
        if self.state_fn is not None:
            try:
                state = self.state_fn()
            except Exception as e:
                state = {"error": str(e)}
            files["state.json"] = json.dumps(
                state, separators=(",", ":"), default=str
            ).encode()

        digest = hashlib.sha256()
        for name in sorted(files):
            digest.update(name.encode())
            digest.update(files[name])
        bundle_digest = digest.hexdigest()
        with self._lock:
            if bundle_digest in self._digests:
                duplicate = True
            else:
                duplicate = False
                self._digests.add(bundle_digest)
        if duplicate:
            self._skip(trigger, key, "duplicate")
            return None

        name = (
            f"{BUNDLE_PREFIX}{self.component}-{int(time.time())}"
            f"-{bundle_digest[:12]}"
        )
        final = os.path.join(self.directory, name)
        staging = final + INPROGRESS_SUFFIX
        manifest = {
            "schema": BUNDLE_SCHEMA,
            "component": self.component,
            "pid": os.getpid(),
            "ts": round(time.time(), 3),
            "trigger": trigger,
            "key": key,
            "digest": bundle_digest,
            "files": {
                n: {
                    "bytes": len(body),
                    "sha256": hashlib.sha256(body).hexdigest(),
                }
                for n, body in files.items()
            },
        }
        os.makedirs(staging, exist_ok=True)
        for fname, body in files.items():
            with open(os.path.join(staging, fname), "wb") as f:
                f.write(body)
        with open(os.path.join(staging, "manifest.json"), "w") as f:
            json.dump(manifest, f, separators=(",", ":"))
        # Publish: until this rename the sweeper cannot see the bundle,
        # after it the bundle is complete — no torn reads either way.
        # The digest in the name makes collisions impossible (same
        # digest deduplicated above).
        os.rename(staging, final)

        bundle_bytes = _entry_bytes(final)
        self.captures += 1
        self.last_bundle = final
        self._account(trigger, "captured")
        if self._bundle_bytes is not None:
            self._bundle_bytes.set(bundle_bytes)
        if self.flight is not None:
            self.flight.record(
                "postmortem.captured",
                key=key,
                trigger=trigger,
                bundle=name,
                bytes=bundle_bytes,
                digest=bundle_digest[:12],
            )
        sweep_dump_dir(
            self.directory,
            self.budget_bytes,
            self.max_entries,
            protect=(final,),
            flight=self.flight,
        )
        return final

    # --------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        with self._lock:
            keys = len(self._last_capture)
        return {
            "component": self.component,
            "directory": self.directory,
            "debounce_s": self.debounce_s,
            "budget_bytes": self.budget_bytes,
            "captures": self.captures,
            "skipped": self.skipped,
            "debounce_keys": keys,
            "last_bundle": self.last_bundle,
            "last_error": self.last_error,
        }
