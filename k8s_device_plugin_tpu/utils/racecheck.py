"""Lock-discipline race detector for host-side shared state.

SURVEY.md §5.2 records that the reference ships real data races with no
sanitizer anywhere (no ``-race`` in its build — reference Dockerfile:17 —
while its ListAndWatch/heartbeat code races, reference main.go:126-132).
The JAX device side here is functional and race-free by construction, but
the serving engine's HOST side has a documented threading contract:
``submit()``/``cancel()`` run on RPC-handler threads and the metrics
scraper reads gauges concurrently, so the queue, the free-page pool, and
the page refcounts must only ever be touched under the engine lock.

The stress suites (tests/test_stress.py, tests/test_engine_stress.py)
*exercise* those races; this module *detects* violations of the contract
itself — the TSan-style systematic check, scaled to what Python needs:

- ``GuardedDeque`` / ``GuardedDict`` wrap the shared containers and assert
  on EVERY mutating (and optionally reading) operation that the declared
  lock is held by the calling thread.  A violation raises
  ``LockDisciplineError`` at the exact faulty call site instead of
  corrupting state with a probability the stress test may or may not hit.
- ``ServingEngine(..., racecheck=True)`` (the engine wires this up) swaps
  its queue/free_pages/_page_refs for guarded versions; the fuzz/stress
  suites run with it ON, so every schedule they explore is checked, not
  just observed.

Single-threaded fast path: ``_is_owned`` is one C-level call; the guard
adds ~100ns per container op and is OFF by default in production engines.
"""

from __future__ import annotations

import threading
import warnings
from collections import deque
from typing import Iterable, Optional

# Lock types already warned about on the fail-open path (one warning per
# type, not per call — _owned runs at every mutation site).
_FAIL_OPEN_WARNED: set = set()


class LockDisciplineError(AssertionError):
    """A lock-protected container was touched without its lock held."""


def _owned(lock) -> bool:
    # RLock exposes _is_owned (CPython, PyPy); a plain Lock would need
    # owner tracking we don't use (the engine lock is reentrant).  This
    # is a test-only instrument, so when the introspection hook is
    # absent (exotic lock type, future rename) we FAIL OPEN — no
    # discipline checking — rather than turn every guarded op into an
    # AttributeError on code that may be perfectly correct.
    probe = getattr(lock, "_is_owned", None)
    if probe is None:
        # Warn once per lock type so a silent fail-open can't masquerade
        # as a passing race check (e.g. an RLock->Lock refactor would
        # otherwise turn every stress suite into a no-op detector).
        key = type(lock)
        if key not in _FAIL_OPEN_WARNED:
            _FAIL_OPEN_WARNED.add(key)
            warnings.warn(
                f"racecheck: lock type {key.__name__} has no _is_owned "
                "introspection hook; lock-discipline checking is DISABLED "
                "for containers guarded by it",
                RuntimeWarning,
                # user mutation site -> guarded wrapper -> _check -> _owned
                stacklevel=4,
            )
        return True
    return probe()


class OwnerGuard:
    """Single-owner discipline for state that is NOT lock-protected but
    owner-thread-only by contract — the engine's in-flight overlap
    record: the step loop dispatches and consumes it (no lock — the hot
    path) while ``submit()``/``cancel()`` mutate slots under the engine
    lock.  The dispatch/consume handoff itself must therefore only ever
    run on the ONE owner thread, or (for tests/tools that drive a
    drained engine from elsewhere) with the engine lock held, which
    serializes against the contract's other side.

    The first thread to call :meth:`check` off-lock becomes the owner;
    any other thread doing so afterwards raises
    :class:`LockDisciplineError` at the faulty call site.  A lock-held
    check re-binds ownership to the calling thread (holding the lock IS
    the license to take over — e.g. the stress suites drain on the main
    thread after stopping the server loop).

    ``steal_on_lock=False`` keeps the lock-held license but WITHOUT the
    ownership rebind: a lock-holding thread may touch the state (it is
    serialized against the owner, who also takes the lock for its own
    mutations under this mode's contract) yet does not become the new
    off-lock owner.  This is the router poll-loop shape: the poll thread
    owns the per-replica poll state off-lock, while request/stream
    threads marking a replica draining/fenced on failover must hold the
    router lock — a transient request thread must not steal ownership
    from the long-lived poll loop (its later off-lock poll would then
    false-trip while the request thread is still alive)."""

    def __init__(self, lock, name: str = "owned", steal_on_lock: bool = True):
        self._lock = lock
        self._name = name
        self._steal_on_lock = steal_on_lock
        self._owner: Optional[threading.Thread] = None

    def check(self, op: str) -> None:
        me = threading.current_thread()
        if _owned(self._lock):
            if self._steal_on_lock:
                self._owner = me
            return
        if self._owner is None or not self._owner.is_alive():
            # First toucher (or the previous owner thread exited — a
            # server loop died and another thread inherits the engine).
            self._owner = me
            return
        if self._owner is not me:
            raise LockDisciplineError(
                f"{self._name}.{op} from thread {me.name!r} (owner: "
                f"{self._owner.name!r}) without the engine lock held"
            )


class GuardedDeque(deque):
    """A deque that asserts ``lock`` is held on every mutation.

    Reads (len, iteration, indexing) are deliberately unguarded: the
    engine's contract allows lock-free reads of approximate state (gauge
    snapshots), and guarding them would flag the benign ones.  Mutations
    are never benign off-lock — a deque resize mid-iteration crashes the
    scraper thread.
    """

    _MUTATORS = (
        "append", "appendleft", "pop", "popleft", "extend", "extendleft",
        "remove", "insert", "clear", "rotate", "__setitem__", "__delitem__",
        "__iadd__",
    )

    def __init__(self, iterable: Iterable = (), *, lock, name: str = "deque"):
        super().__init__(iterable)
        self._lock = lock
        self._name = name

    def _check(self, op: str) -> None:
        if not _owned(self._lock):
            raise LockDisciplineError(
                f"{self._name}.{op}() without the engine lock held "
                f"(thread {threading.current_thread().name})"
            )


class GuardedDict(dict):
    """A dict that asserts ``lock`` is held on every mutation (same read
    policy as GuardedDeque)."""

    _MUTATORS = (
        "__setitem__", "__delitem__", "pop", "popitem", "clear", "update",
        "setdefault",
    )

    def __init__(self, *args, lock, name: str = "dict", **kw):
        # Build content first so the initial fill needs no lock.
        super().__init__(*args, **kw)
        self._lock = lock
        self._name = name

    def _check(self, op: str) -> None:
        if not _owned(self._lock):
            raise LockDisciplineError(
                f"{self._name}.{op}() without the engine lock held "
                f"(thread {threading.current_thread().name})"
            )


def _install_guards(cls, mutators):
    """Generate checking overrides for every mutator name: each calls
    _check(op) then the parent implementation.  Done at import time (not
    per instance) so instances carry no per-object closures and each op
    pays one extra attribute check, nothing more."""
    for op in mutators:
        parent = getattr(cls.__mro__[1], op)

        def make(op=op, parent=parent):
            def guarded(self, *a, **kw):
                self._check(op)
                return parent(self, *a, **kw)

            guarded.__name__ = op
            return guarded

        setattr(cls, op, make())


_install_guards(GuardedDeque, GuardedDeque._MUTATORS)
_install_guards(GuardedDict, GuardedDict._MUTATORS)
