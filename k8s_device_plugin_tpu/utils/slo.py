"""Fleet SLO plane: sliding-window SLI accounting, error budgets, and
multi-window multi-burn-rate alerting (ISSUE 16).

The forensic layers (spans, flight events, incidents, histograms)
answer *what happened*; this module answers the operator's questions:
are we inside SLO, how fast is the error budget burning, and who is
consuming the fleet.  Three pieces:

- :class:`SLOTracker` — time-bucketed good/total SLI counters per
  declared :class:`Objective`, summed over sliding windows (5m/30m/6h
  by default).  The clock is injectable (``now=``) so the unit suite
  runs zero-sleep, exactly like ``OverloadController``.
- :class:`BurnRateRule` + the tracker's ``evaluate()`` — Google-SRE
  multi-window multi-burn-rate alerting: a *fast-burn* rule pages when
  the short AND medium windows both burn budget at >= 14.4x the
  sustainable rate; a *slow-burn* rule tickets at >= 3x over the
  medium AND long windows.  Requiring both windows keeps a single bad
  bucket from paging; clearing only after ``clear_evals`` consecutive
  clean evaluations keeps a flapping signal from re-paging.
- :class:`UsageMeter` — per-tenant usage accounting (prompt/decode
  tokens, KV page-seconds, queue-wait seconds) under the same bounded
  16-tenant label map as ``OverloadController`` (the 17th distinct
  tenant folds into ``_other`` so cardinality never grows per tenant).

Thread-safety contract (the ``OverloadController`` precedent): every
mutating method is called by its owner — the engine under the engine
lock, or the router's poll thread — so the classes here add no locking
of their own.

Burn-rate arithmetic: with objective target ``t`` the error budget is
``1 - t``; the burn rate over a window is ``bad_fraction / (1 - t)``.
Burn 1.0 spends exactly the whole budget over the objective period;
14.4x spends a 30-day budget in ~2 days — the canonical page
threshold.

Structured-output validity is a *reserved* objective name
(``structured_validity``): ROADMAP #6's grammar-constrained decoding
will emit its verdicts through the same tracker; declaring it here
reserves the wire name without accounting an objective nobody feeds.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# Sliding windows, name -> seconds.  Short confirms an alert is STILL
# happening, long keeps it representative.
DEFAULT_WINDOWS: Dict[str, float] = {"5m": 300.0, "30m": 1800.0, "6h": 21600.0}

# Reserved for ROADMAP #6 (grammar-constrained decoding): the objective
# name structured-output validity verdicts will use.  Not in
# DEFAULT_OBJECTIVES — an objective with no feeder would read as a
# vacuously healthy SLO.
STRUCTURED_VALIDITY = "structured_validity"


@dataclass(frozen=True)
class Objective:
    """One declared service-level objective.

    ``target`` is the good-event ratio promised (0.99 = 1% error
    budget).  ``threshold_s`` is the latency cut for latency-shaped
    objectives (``record_latency`` turns seconds into a verdict);
    ``None`` for pure good/bad objectives like availability.
    """

    name: str
    target: float
    threshold_s: Optional[float] = None
    description: str = ""

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


def default_objectives(
    ttft_target_s: float = 2.0,
    itl_p99_target_s: float = 0.25,
) -> List[Objective]:
    """The serving objectives every engine accounts by default.  The
    latency cuts are CLI-tunable (``--slo-ttft-target`` /
    ``--slo-itl-target``); the ratio targets are the contract."""
    return [
        Objective(
            "ttft",
            target=0.99,
            threshold_s=ttft_target_s,
            description="time to first token <= target for 99% of requests",
        ),
        Objective(
            "itl_p99",
            target=0.99,
            threshold_s=itl_p99_target_s,
            description="per-request p99 inter-token gap <= target "
            "for 99% of requests",
        ),
        Objective(
            "availability",
            target=0.999,
            description="non-shed, non-dropped completion "
            "(client cancels excluded)",
        ),
    ]


@dataclass(frozen=True)
class BurnRateRule:
    """One multi-window burn-rate alert rule: fire when EVERY listed
    window burns at >= ``factor``; severity names the operator action
    (page vs ticket)."""

    name: str
    severity: str
    factor: float
    windows: Tuple[str, ...]


DEFAULT_RULES: Tuple[BurnRateRule, ...] = (
    BurnRateRule("fast_burn", "page", 14.4, ("5m", "30m")),
    BurnRateRule("slow_burn", "ticket", 3.0, ("30m", "6h")),
)


@dataclass
class _AlertState:
    active: bool = False
    since: float = 0.0
    clean_evals: int = 0
    fired_total: int = 0


@dataclass
class _Ring:
    """Per-objective time-bucketed good/total ring.  O(1) record, O(n)
    window sum; n = longest window / bucket width (~2160 at defaults),
    summed only on snapshot/evaluate, never per request."""

    bucket_s: float
    n: int
    ids: List[int] = field(default_factory=list)
    good: List[int] = field(default_factory=list)
    total: List[int] = field(default_factory=list)

    def __post_init__(self):
        self.ids = [-1] * self.n
        self.good = [0] * self.n
        self.total = [0] * self.n

    def add(self, now: float, good: int, total: int) -> None:
        bucket = int(now // self.bucket_s)
        slot = bucket % self.n
        if self.ids[slot] != bucket:
            self.ids[slot] = bucket
            self.good[slot] = 0
            self.total[slot] = 0
        self.good[slot] += good
        self.total[slot] += total

    def window_counts(self, now: float, window_s: float) -> Tuple[int, int]:
        """(good, total) summed over buckets inside the last window_s.
        The current partial bucket counts — freshness beats exactness
        at the bucket-width granularity."""
        newest = int(now // self.bucket_s)
        oldest = int((now - window_s) // self.bucket_s) + 1
        good = total = 0
        for slot in range(self.n):
            if oldest <= self.ids[slot] <= newest:
                good += self.good[slot]
                total += self.total[slot]
        return good, total


class SLOTracker:
    """Sliding-window SLI accounting + burn-rate alerting for a set of
    objectives.  One instance per engine (fed request verdicts under
    the engine lock) and one per router (fed per-replica summary deltas
    on the poll thread); no internal locking — see the module contract.
    """

    def __init__(
        self,
        objectives: Optional[List[Objective]] = None,
        windows: Optional[Dict[str, float]] = None,
        rules: Optional[Tuple[BurnRateRule, ...]] = None,
        bucket_s: float = 10.0,
        clear_evals: int = 3,
        now: Callable[[], float] = time.monotonic,
    ):
        self.objectives: Dict[str, Objective] = {
            o.name: o for o in (objectives or default_objectives())
        }
        self.windows = dict(windows or DEFAULT_WINDOWS)
        self.rules = tuple(rules if rules is not None else DEFAULT_RULES)
        for rule in self.rules:
            for w in rule.windows:
                if w not in self.windows:
                    raise ValueError(
                        f"rule {rule.name!r} references unknown window {w!r}"
                    )
        self.bucket_s = float(bucket_s)
        self.clear_evals = int(clear_evals)
        self._now = now
        n = int(max(self.windows.values()) // self.bucket_s) + 2
        self._rings: Dict[str, _Ring] = {
            name: _Ring(self.bucket_s, n) for name in self.objectives
        }
        # Cumulative lifetime [good, total] per objective — the compact
        # counters ?summary=1 exports for the router's delta merge.
        self._totals: Dict[str, List[int]] = {
            name: [0, 0] for name in self.objectives
        }
        self._alerts: Dict[Tuple[str, str], _AlertState] = {
            (obj, rule.name): _AlertState()
            for obj in self.objectives
            for rule in self.rules
        }

    # ------------------------------------------------------ recording

    def record(self, objective: str, good: bool, n: int = 1) -> None:
        """Account n identical verdicts for one objective."""
        ring = self._rings.get(objective)
        if ring is None or n <= 0:
            return
        ring.add(self._now(), n if good else 0, n)
        totals = self._totals[objective]
        totals[0] += n if good else 0
        totals[1] += n

    def record_latency(self, objective: str, seconds: float) -> bool:
        """Turn a latency sample into a verdict against the objective's
        threshold; returns the verdict (True = good)."""
        obj = self.objectives.get(objective)
        if obj is None or obj.threshold_s is None:
            return True
        good = seconds <= obj.threshold_s
        self.record(objective, good)
        return good

    def ingest(self, objective: str, good: int, total: int) -> None:
        """Merge a (good, total) DELTA from a downstream tracker into
        the current bucket — the router's fleet-aggregation path."""
        if objective not in self._rings or total <= 0:
            return
        good = max(0, min(good, total))
        self.record(objective, True, good)
        self.record(objective, False, total - good)

    # ------------------------------------------------------- querying

    def totals(self) -> Dict[str, List[int]]:
        """Cumulative lifetime [good, total] per objective (the
        ?summary=1 payload)."""
        return {name: list(v) for name, v in self._totals.items()}

    def window_counts(self, objective: str, window_s: float):
        return self._rings[objective].window_counts(self._now(), window_s)

    def bad_fraction(self, objective: str, window_s: float) -> float:
        good, total = self.window_counts(objective, window_s)
        return 0.0 if total == 0 else (total - good) / total

    def burn_rate(self, objective: str, window_s: float) -> float:
        """bad_fraction / error_budget: 1.0 burns exactly the budget
        over the period; 0.0 when the window saw no events (an idle
        engine is not out of SLO)."""
        obj = self.objectives[objective]
        return self.bad_fraction(objective, window_s) / obj.error_budget

    def budget_remaining(self, objective: str) -> float:
        """Error budget left over the LONGEST window, 1.0 (untouched)
        to <= 0.0 (overspent)."""
        longest = max(self.windows.values())
        return 1.0 - self.burn_rate(objective, longest)

    # ----------------------------------------------------- alerting

    def evaluate(self) -> List[dict]:
        """Evaluate every (objective, rule) pair; returns the state
        TRANSITIONS (fired / cleared) since the last call.  An alert
        fires only when every window in the rule burns >= factor with
        nonzero traffic, and clears only after ``clear_evals``
        consecutive clean evaluations — the hysteresis that keeps one
        bad bucket from flapping a page."""
        now = self._now()
        transitions: List[dict] = []
        for obj_name, obj in self.objectives.items():
            for rule in self.rules:
                burns = {}
                firing = True
                for w in rule.windows:
                    good, total = self.window_counts(
                        obj_name, self.windows[w]
                    )
                    burn = (
                        0.0
                        if total == 0
                        else ((total - good) / total) / obj.error_budget
                    )
                    burns[w] = burn
                    if total == 0 or burn < rule.factor:
                        firing = False
                state = self._alerts[(obj_name, rule.name)]
                if firing:
                    state.clean_evals = 0
                    if not state.active:
                        state.active = True
                        state.since = now
                        state.fired_total += 1
                        transitions.append(
                            self._alert_dict(obj_name, rule, burns, "fired")
                        )
                elif state.active:
                    state.clean_evals += 1
                    if state.clean_evals >= self.clear_evals:
                        state.active = False
                        transitions.append(
                            self._alert_dict(obj_name, rule, burns, "cleared")
                        )
        return transitions

    def _alert_dict(self, objective, rule, burns, state_str) -> dict:
        return {
            "objective": objective,
            "rule": rule.name,
            "severity": rule.severity,
            "factor": rule.factor,
            "windows": list(rule.windows),
            "burn_rates": {w: round(b, 3) for w, b in burns.items()},
            "state": state_str,
        }

    def active_alerts(self) -> List[dict]:
        out = []
        for (obj_name, rule_name), state in self._alerts.items():
            if not state.active:
                continue
            rule = next(r for r in self.rules if r.name == rule_name)
            burns = {
                w: round(self.burn_rate(obj_name, self.windows[w]), 3)
                for w in rule.windows
            }
            d = self._alert_dict(obj_name, rule, burns, "active")
            d["since"] = state.since
            out.append(d)
        return out

    # ----------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The full /debug/slo payload: per-objective targets, window
        counts, burn rates, budget remaining, and active alerts."""
        objectives = {}
        for name, obj in self.objectives.items():
            per_window = {}
            for wname, wsec in self.windows.items():
                good, total = self.window_counts(name, wsec)
                per_window[wname] = {
                    "good": good,
                    "total": total,
                    "burn_rate": round(
                        0.0
                        if total == 0
                        else ((total - good) / total) / obj.error_budget,
                        4,
                    ),
                }
            objectives[name] = {
                "target": obj.target,
                "threshold_s": obj.threshold_s,
                "description": obj.description,
                "totals": list(self._totals[name]),
                "windows": per_window,
                "budget_remaining": round(self.budget_remaining(name), 4),
            }
        return {
            "objectives": objectives,
            "rules": [
                {
                    "name": r.name,
                    "severity": r.severity,
                    "factor": r.factor,
                    "windows": list(r.windows),
                }
                for r in self.rules
            ],
            "alerts": self.active_alerts(),
            "alerts_fired_total": sum(
                s.fired_total for s in self._alerts.values()
            ),
        }


class UsageMeter:
    """Per-tenant usage accounting: who consumed the fleet.

    Bounded exactly like ``OverloadController``'s tenant ledger: the
    first ``max_tracked_tenants`` distinct tenants get their own row
    (empty tenant -> ``default``); every later tenant folds into
    ``_other``, so the exported ``tpu_engine_tenant_*`` label sets stay
    under the fleet cardinality budget no matter how many tenants a
    storm invents.  Mutated under the engine lock; no locking here.
    """

    max_tracked_tenants = 16
    FIELDS = (
        "requests",
        "prompt_tokens",
        "decode_tokens",
        "kv_page_seconds",
        "queue_wait_seconds",
    )

    def __init__(self, max_tracked_tenants: Optional[int] = None):
        if max_tracked_tenants is not None:
            self.max_tracked_tenants = int(max_tracked_tenants)
        self._tracked: set = set()
        self._rows: Dict[str, Dict[str, float]] = {}

    def _tenant_label(self, tenant: str) -> str:
        label = tenant or "default"
        if label in self._tracked:
            return label
        if len(self._tracked) < self.max_tracked_tenants:
            self._tracked.add(label)
            return label
        return "_other"

    def record_request(
        self,
        tenant: str,
        prompt_tokens: int = 0,
        decode_tokens: int = 0,
        kv_page_seconds: float = 0.0,
        queue_wait_seconds: float = 0.0,
    ) -> str:
        """Charge one finished request to its tenant; returns the label
        it was charged to (the folded ``_other`` for late tenants) so
        the caller can export the same label to metrics."""
        label = self._tenant_label(tenant)
        row = self._rows.setdefault(
            label, {f: 0.0 for f in self.FIELDS}
        )
        row["requests"] += 1
        row["prompt_tokens"] += max(0, int(prompt_tokens))
        row["decode_tokens"] += max(0, int(decode_tokens))
        row["kv_page_seconds"] += max(0.0, float(kv_page_seconds))
        row["queue_wait_seconds"] += max(0.0, float(queue_wait_seconds))
        return label

    def snapshot(self) -> dict:
        """The /debug/usage payload: per-tenant rows plus the fold
        telemetry (how many distinct tenants the cap absorbed)."""
        return {
            "max_tracked_tenants": self.max_tracked_tenants,
            "tracked_tenants": len(self._tracked),
            "tenants": {
                label: {
                    k: (int(v) if k.endswith("tokens") or k == "requests"
                        else round(v, 4))
                    for k, v in row.items()
                }
                for label, row in self._rows.items()
            },
        }
