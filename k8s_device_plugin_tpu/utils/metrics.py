"""Prometheus-format metrics, stdlib-only.

The reference has no metrics at all (SURVEY.md §5.5: glog lines only, "no
metrics endpoint, no Prometheus") — this subsystem is deliberately beyond
parity, per SURVEY.md §7 step 7.  A tiny text-exposition implementation is
used instead of the `prometheus_client` package so the plugin image keeps
zero non-gRPC dependencies.

Exposition format: https://prometheus.io/docs/instrumenting/exposition_formats/
(text version 0.0.4) — `# HELP` / `# TYPE` headers, one `name{labels} value`
line per labeled series.
"""

from __future__ import annotations

import bisect
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Mapping


def _escape_label_value(value: str) -> str:
    return value.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _format_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _format_value(value: float) -> str:
    # Integers render without a trailing ".0" (matches common exporters).
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class _Metric:
    TYPE = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: Iterable[str] = ()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict[tuple[str, ...], float] = {}

    def _key(self, labels: Mapping[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, want {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.TYPE}"]
        with self._lock:
            if not self._series:
                return lines if self.labelnames else lines + [f"{self.name} 0"]
            for key in sorted(self._series):
                labels = dict(zip(self.labelnames, key))
                lines.append(
                    f"{self.name}{_format_labels(labels)} "
                    f"{_format_value(self._series[key])}"
                )
        return lines


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop one labeled series (a per-device gauge whose device was
        unplugged must stop exporting, not freeze at its last value)."""
        with self._lock:
            self._series.pop(self._key(labels), None)


class _Timer:
    """Context manager observing elapsed wall seconds into any metric
    with an ``observe(seconds)`` method (Summary, Histogram)."""

    def __init__(self, observe):
        self._observe = observe

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._observe(time.monotonic() - self._t0)
        return False


class Summary:
    """count + sum pair (enough for rate()/avg in PromQL; no quantiles)."""

    TYPE = "summary"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += float(value)

    def time(self) -> "_Timer":
        return _Timer(self.observe)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def collect(self) -> list[str]:
        with self._lock:
            return [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.TYPE}",
                f"{self.name}_count {self._count}",
                f"{self.name}_sum {_format_value(self._sum)}",
            ]


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` exposition): what
    PromQL's histogram_quantile() needs for p50/p99 dashboards — the
    piece Summary (count+sum only) can't provide.

    ``labelnames`` (optional) makes it a labeled family: each distinct
    labelset owns its own bucket counts, exported as
    ``name_bucket{<labels>,le="..."}`` series the way prometheus_client
    renders them (the exposition linter checks cumulative buckets per
    non-le labelset).  Keep the label space SMALL and closed — a
    per-priority-class split, never a per-request/tenant one."""

    TYPE = "histogram"
    # Log-spaced seconds, 1ms..10s: covers local-chip decode steps
    # (~ms), relay-RTT steps (~100ms), and compile stalls (~s).
    DEFAULT_BUCKETS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0,
    )

    def __init__(
        self,
        name: str,
        help_text: str,
        buckets=None,
        labelnames: Iterable[str] = (),
    ):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * len(self.buckets)
        self._count = 0
        self._sum = 0.0
        # Labeled series: labelset key -> [bucket_counts, count, sum].
        self._series: dict[tuple[str, ...], list] = {}

    def _key(self, labels: Mapping[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"want {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def observe(self, value: float, **labels: str) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        if self.labelnames:
            key = self._key(labels)
            with self._lock:
                series = self._series.get(key)
                if series is None:
                    series = self._series[key] = [
                        [0] * len(self.buckets), 0, 0.0
                    ]
                if i < len(self.buckets):
                    series[0][i] += 1
                series[1] += 1
                series[2] += v
            return
        if labels:
            raise ValueError(f"{self.name} takes no labels")
        with self._lock:
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1
            self._count += 1
            self._sum += v

    def time(self) -> "_Timer":
        return _Timer(self.observe)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> tuple[tuple[int, ...], int, float]:
        """(bucket_counts, count, sum) at this instant — the ``since``
        anchor for :meth:`quantile`, so a benchmark can report the timed
        region's percentiles with warmup observations subtracted."""
        with self._lock:
            return tuple(self._bucket_counts), self._count, self._sum

    def quantile(self, q: float, since=None) -> float | None:
        """Estimate the q-quantile (0 <= q <= 1) the way PromQL's
        histogram_quantile() does: find the bucket where the cumulative
        count crosses q*total and interpolate linearly inside it.  With
        ``since`` (a prior :meth:`snapshot`), only observations recorded
        after that snapshot count.  Returns None on an empty window; a
        crossing in the +Inf bucket reports the highest finite bound
        (the same clamp PromQL applies)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        counts, total, _ = self.snapshot()
        if since is not None:
            prev_counts, prev_total, _ = since
            counts = tuple(c - p for c, p in zip(counts, prev_counts))
            total -= prev_total
        if total <= 0:
            return None
        rank = q * total
        cum = 0
        for le, n, lower in zip(
            self.buckets, counts, (0.0,) + self.buckets[:-1]
        ):
            cum += n
            if cum >= rank and n > 0:
                frac = (rank - (cum - n)) / n
                return lower + (le - lower) * frac
        return self.buckets[-1]

    def collect(self) -> list[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.TYPE}",
            ]
            if self.labelnames:
                for key in sorted(self._series):
                    counts, count, total = self._series[key]
                    labels = dict(zip(self.labelnames, key))
                    blob = _format_labels(labels)  # "{k=\"v\",...}"
                    inner = blob[1:-1]
                    cum = 0
                    for le, n in zip(self.buckets, counts):
                        cum += n
                        lines.append(
                            f"{self.name}_bucket{{{inner},"
                            f'le="{_format_value(le)}"}} {cum}'
                        )
                    lines.append(
                        f'{self.name}_bucket{{{inner},le="+Inf"}} {count}'
                    )
                    lines.append(
                        f"{self.name}_sum{blob} {_format_value(total)}"
                    )
                    lines.append(f"{self.name}_count{blob} {count}")
                return lines
            cum = 0
            for le, n in zip(self.buckets, self._bucket_counts):
                cum += n
                lines.append(
                    f'{self.name}_bucket{{le="{_format_value(le)}"}} {cum}'
                )
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {self._count}')
            lines.append(f"{self.name}_sum {_format_value(self._sum)}")
            lines.append(f"{self.name}_count {self._count}")
            return lines


class MetricsRegistry:
    """Holds metrics and renders the exposition text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        """Look up an already-registered metric family by name (None
        when absent) — the get-or-create seam for hooks that may be
        constructed more than once against a process-wide registry."""
        with self._lock:
            return self._metrics.get(name)

    def counter(self, name: str, help_text: str, labelnames: Iterable[str] = ()) -> Counter:
        return self._register(Counter(name, help_text, labelnames))

    def gauge(self, name: str, help_text: str, labelnames: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge(name, help_text, labelnames))

    def summary(self, name: str, help_text: str) -> Summary:
        return self._register(Summary(name, help_text))

    def histogram(
        self,
        name: str,
        help_text: str,
        buckets=None,
        labelnames: Iterable[str] = (),
    ) -> Histogram:
        return self._register(
            Histogram(name, help_text, buckets, labelnames=labelnames)
        )

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for metric in metrics:
            lines.extend(metric.collect())
        return "\n".join(lines) + "\n"


# Prometheus text exposition 0.0.4 — the one place the scrape
# content-type lives.
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def write_exposition(handler, registry: "MetricsRegistry") -> None:
    """Answer one GET /metrics on a BaseHTTPRequestHandler: render the
    registry and write a 200 text-exposition response.  Shared by the
    plugin's MetricsServer and the serving EngineServer so the two
    /metrics endpoints cannot drift in content-type or framing."""
    body = registry.render().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", PROM_CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class MetricsServer:
    """Serves GET /metrics (exposition text) and GET /healthz on a daemon
    thread.  Port 0 picks a free port (tests); `.port` reports it.

    ``health`` is an optional callable consulted by /healthz: True (or no
    callable) ⇒ 200 "ok", False ⇒ 503 — so a liveness probe reflects the
    daemon's actual state, not just this HTTP thread's.

    ``debug`` maps extra GET paths (e.g. ``/debug/devices``) to
    callables returning a JSON-serializable snapshot — the plugin-side
    introspection companion to the serving engine's ``/debug/state``.
    A callable declaring at least one positional parameter receives the
    parsed query dict (``{name: [values]}``; e.g. the span endpoint's
    ``?rid=`` filter); a no-arg callable is called bare.  A snapshot
    callable that raises answers 500 with the error, never kills the
    metrics thread.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        host: str = "0.0.0.0",
        port: int = 9100,
        health=None,
        debug=None,
    ):
        import inspect as _inspect
        import json as _json
        import urllib.parse as _urlparse

        registry_ref = registry
        health_ref = health
        debug_ref = dict(debug or {})
        # Decided once at construction, not per request: which debug
        # callables want the query dict (any positional parameter).
        wants_query = set()
        for _path, _fn in debug_ref.items():
            try:
                if _inspect.signature(_fn).parameters:
                    wants_query.add(_path)
            except (TypeError, ValueError):
                pass  # builtins without signatures: call bare

        class Handler(BaseHTTPRequestHandler):
            def _json_reply(self, code: int, obj) -> None:
                body = _json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?")[0]
                if path in debug_ref:
                    try:
                        if path in wants_query:
                            query = _urlparse.parse_qs(
                                _urlparse.urlparse(self.path).query
                            )
                            snap = debug_ref[path](query)
                        else:
                            snap = debug_ref[path]()
                    except Exception as e:  # snapshot bug must not kill scrapes
                        self._json_reply(500, {"error": str(e)})
                        return
                    self._json_reply(200, snap)
                elif path == "/metrics":
                    write_exposition(self, registry_ref)
                elif path == "/healthz":
                    try:
                        healthy = health_ref is None or bool(health_ref())
                    except Exception:
                        healthy = False
                    body = b"ok\n" if healthy else b"unhealthy\n"
                    self.send_response(200 if healthy else 503)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *args):  # quiet: scrapes are frequent
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        assert self._thread is None
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpu-metrics", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()
