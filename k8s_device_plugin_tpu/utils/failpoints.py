"""Failpoint registry: named, armable fault-injection sites.

The observability stack (flight recorder, EWMA anomaly incidents,
allocation-drift audit) was built watching one healthy node — its
detectors' precision against *real injected faults* was assumed, never
measured.  This module is the injection half of the chaos harness
(tools/chaos_report.py + tests/test_chaos_scenarios.py score the
detection half): named failpoints are threaded into the real code paths
(health probes, Allocate, ListAndWatch, attribution polls, engine
admission/readback — the catalog lives in docs/chaos.md) and armed per
scenario, by test, by CLI flag, or by environment variable.

Design rules, in priority order:

- **Zero overhead when disarmed.**  ``fire()`` with nothing armed is one
  attribute load and a dict truthiness check — no lock, no allocation.
  The engine calls it on every decode readback; a disarmed registry must
  be invisible in the step-time profile.
- **Forensically replayable.**  Every arm/disarm/trigger is recorded as
  a flight event (``failpoint.armed`` / ``failpoint.trigger`` /
  ``failpoint.disarmed``) when a recorder is wired, so a chaos dump
  shows the injected cause in sequence with the detected effect.
- **Bounded.**  An armed failpoint can carry a trigger budget
  (``*count`` in the spec) after which it disarms itself — a scenario's
  injection window ends deterministically even if the test dies.

Fault modes:

``error[:message]``
    :meth:`FailpointRegistry.fire` raises :class:`FailpointError`; the
    call site translates it into its own failure shape (an RPC abort, a
    submit rejection, a down-marked poll).
``delay:seconds``
    ``fire()`` sleeps — latency injection that flows into the same
    histograms and EWMA baselines real slowness would.
``hang[:max_seconds]``
    ``fire()`` blocks until the failpoint is disarmed (or
    ``max_seconds``, default 30 — a chaos harness must not be able to
    wedge a process beyond recovery).
``flap[:period]``
    ``fire()`` returns a :class:`FailpointHit` whose ``value``
    alternates every ``period`` triggers (default 1) — the transient
    probe-failure shape the health debounce exists for.
``truncate[:fraction]``
    ``fire()`` returns a hit the CALL SITE interprets as "corrupt your
    output": the snapshot writer tears the file to ``fraction`` of its
    bytes (default 0.5), the snapshot reader reads only that prefix —
    the disk-corruption shape the warm-restart degradation contract is
    scored against.  Sites that do not understand truncation ignore the
    hit (non-error hits are advisory by design).
``corrupt[:nbytes]``
    ``fire()`` returns a hit the CALL SITE interprets as "flip bytes of
    your output IN PLACE" — the silent-data-corruption sibling of
    ``truncate``: nothing tears, nothing errors, the payload is simply
    WRONG.  The engine's decode readback (``engine.readback``) flips
    ``nbytes`` bytes (default 1) of the synced token buffer, so the
    stream keeps flowing with a wrong token — the SDC ground truth the
    canary prober's bit-exactness verdict is scored against
    (docs/chaos.md).  Sites that do not understand corruption ignore
    the hit.

Spec grammar (``--failpoints`` on both CLIs, ``TPU_FAILPOINTS`` env)::

    name=mode[:arg][*count][;name2=...]

    TPU_FAILPOINTS='plugin.allocate=error*2;engine.readback=delay:0.25*6'

Stdlib-only, no dependencies on the metrics/flight modules beyond duck
typing (anything with ``.record(kind, **fields)`` works as a flight
sink).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("tpu.failpoints")

ENV = "TPU_FAILPOINTS"

MODES = ("error", "delay", "hang", "flap", "truncate", "corrupt")

# Hard ceiling on hang-mode blocking: chaos must stay recoverable.
MAX_HANG_S = 30.0


class FailpointError(RuntimeError):
    """Raised by ``fire()`` at a call site whose failpoint is armed in
    ``error`` mode.  Call sites translate it into their own failure
    shape; it must never escape a daemon loop undocumented."""


class FailpointHit:
    """What ``fire()`` returns when an armed (non-error) failpoint
    triggered: which one, in which mode, the per-arm trigger ordinal,
    for ``flap`` whether the fault is currently ACTIVE, and the arm's
    raw ``arg`` (``truncate`` call sites read their fraction off it)."""

    __slots__ = ("name", "mode", "n", "value", "arg")

    def __init__(
        self, name: str, mode: str, n: int, value: bool, arg=None
    ):
        self.name = name
        self.mode = mode
        self.n = n
        self.value = value
        self.arg = arg

    def __repr__(self) -> str:  # debugging/log friendliness
        return (
            f"FailpointHit(name={self.name!r}, mode={self.mode!r}, "
            f"n={self.n}, value={self.value}, arg={self.arg!r})"
        )


class _Armed:
    """One armed failpoint's mutable state (registry-lock guarded)."""

    __slots__ = ("name", "mode", "arg", "remaining", "triggers", "unhang")

    def __init__(self, name: str, mode: str, arg, remaining: Optional[int]):
        self.name = name
        self.mode = mode
        self.arg = arg
        self.remaining = remaining  # None = unlimited
        self.triggers = 0
        self.unhang = threading.Event()


def parse_spec(spec: str) -> list[tuple[str, str, Optional[str], Optional[int]]]:
    """Parse the ``name=mode[:arg][*count]`` grammar into
    (name, mode, arg, count) tuples; raises ValueError on anything
    malformed (a chaos run with a typo'd spec must fail loudly, not run
    fault-free and report perfect SLOs)."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"failpoint spec {part!r} must be name=mode[:arg][*count]"
            )
        name, rhs = (s.strip() for s in part.split("=", 1))
        if not name:
            raise ValueError(f"failpoint spec {part!r} has an empty name")
        count: Optional[int] = None
        if "*" in rhs:
            rhs, count_s = rhs.rsplit("*", 1)
            try:
                count = int(count_s)
            except ValueError:
                raise ValueError(
                    f"failpoint {name!r}: trigger count {count_s!r} is not "
                    "an integer"
                ) from None
            if count < 1:
                raise ValueError(
                    f"failpoint {name!r}: trigger count must be >= 1, "
                    f"got {count}"
                )
        mode, _, arg_s = rhs.partition(":")
        mode = mode.strip()
        arg: Optional[str] = arg_s.strip() or None
        if mode not in MODES:
            raise ValueError(
                f"failpoint {name!r}: unknown mode {mode!r} "
                f"(expected one of {', '.join(MODES)})"
            )
        if mode in ("delay", "hang") and arg is not None:
            try:
                seconds = float(arg)
            except ValueError:
                raise ValueError(
                    f"failpoint {name!r}: {mode} argument {arg!r} is not "
                    "a number of seconds"
                ) from None
            if seconds < 0:
                raise ValueError(
                    f"failpoint {name!r}: {mode} seconds must be >= 0"
                )
        if mode == "delay" and arg is None:
            raise ValueError(f"failpoint {name!r}: delay requires :seconds")
        if mode == "flap" and arg is not None:
            try:
                period = int(arg)
            except ValueError:
                raise ValueError(
                    f"failpoint {name!r}: flap period {arg!r} is not an "
                    "integer"
                ) from None
            if period < 1:
                raise ValueError(
                    f"failpoint {name!r}: flap period must be >= 1"
                )
        if mode == "truncate" and arg is not None:
            try:
                fraction = float(arg)
            except ValueError:
                raise ValueError(
                    f"failpoint {name!r}: truncate fraction {arg!r} is not "
                    "a number"
                ) from None
            if not 0.0 <= fraction < 1.0:
                raise ValueError(
                    f"failpoint {name!r}: truncate fraction must be in "
                    f"[0, 1), got {fraction}"
                )
        if mode == "corrupt" and arg is not None:
            try:
                nbytes = int(arg)
            except ValueError:
                raise ValueError(
                    f"failpoint {name!r}: corrupt nbytes {arg!r} is not "
                    "an integer"
                ) from None
            if nbytes < 1:
                raise ValueError(
                    f"failpoint {name!r}: corrupt nbytes must be >= 1, "
                    f"got {nbytes}"
                )
        out.append((name, mode, arg, count))
    return out


class FailpointRegistry:
    """Named fault-injection sites, armed and fired at runtime.

    One process-wide :data:`DEFAULT` instance serves the production call
    sites (the module-level ``fire``/``arm``/``disarm`` aliases bind to
    it); tests needing isolation construct their own and fire it
    explicitly."""

    def __init__(self, name: str = "default"):
        self.name = name
        self._lock = threading.Lock()
        self._armed: dict[str, _Armed] = {}
        self._flight = None
        self.triggers_total = 0
        # Lifetime trigger counts per failpoint name — survives disarm
        # so a scenario can assert "the injection actually ran N times"
        # after its window closed.
        self._history: dict[str, int] = {}

    # ------------------------------------------------------------- wiring

    def set_flight(self, flight) -> None:
        """Wire a flight recorder (utils/flight.py — anything with
        ``record(kind, **fields)``); arms/triggers/disarms become black-
        box events from here on."""
        self._flight = flight

    # ------------------------------------------------------ arm / disarm

    def arm(
        self,
        name: str,
        mode: str,
        arg=None,
        count: Optional[int] = None,
    ) -> None:
        """Arm one failpoint (re-arming replaces, releasing any hung
        waiters of the previous arm).  ``count`` bounds triggers; the
        failpoint disarms itself when the budget is spent."""
        if mode not in MODES:
            raise ValueError(
                f"unknown failpoint mode {mode!r} (expected one of "
                f"{', '.join(MODES)})"
            )
        if count is not None and count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        fp = _Armed(name, mode, arg, count)
        with self._lock:
            old = self._armed.get(name)
            if old is not None:
                old.unhang.set()
            self._armed[name] = fp
        log.warning(
            "failpoint ARMED: %s=%s%s%s",
            name,
            mode,
            f":{arg}" if arg is not None else "",
            f"*{count}" if count is not None else "",
        )
        if self._flight is not None:
            self._flight.record(
                "failpoint.armed",
                name=name,
                mode=mode,
                arg=arg,
                count=count,
            )

    def arm_spec(self, spec: str) -> list[str]:
        """Arm every failpoint in a ``name=mode[:arg][*count];...`` spec
        string; returns the armed names.  Parses the WHOLE spec before
        arming anything, so a malformed entry cannot leave a scenario
        half-armed."""
        parsed = parse_spec(spec)
        for name, mode, arg, count in parsed:
            self.arm(name, mode, arg=arg, count=count)
        return [name for name, _, _, _ in parsed]

    def disarm(self, name: str) -> bool:
        """Disarm one failpoint; releases hung waiters.  True when it
        was armed."""
        with self._lock:
            fp = self._armed.pop(name, None)
        if fp is None:
            return False
        fp.unhang.set()
        log.warning("failpoint disarmed: %s", name)
        if self._flight is not None:
            self._flight.record(
                "failpoint.disarmed", name=name, triggers=fp.triggers
            )
        return True

    def disarm_all(self) -> int:
        """Disarm everything (scenario teardown); returns how many were
        armed."""
        with self._lock:
            names = list(self._armed)
        for name in names:
            self.disarm(name)
        return len(names)

    # ---------------------------------------------------------- queries

    def is_armed(self, name: str) -> bool:
        with self._lock:
            return name in self._armed

    def triggers(self, name: str) -> int:
        """Lifetime trigger count for ``name`` (survives disarm)."""
        with self._lock:
            return self._history.get(name, 0)

    def snapshot(self) -> dict:
        """JSON-safe registry state (armed sites + lifetime counts) —
        debug-endpoint / report material."""
        with self._lock:
            return {
                "registry": self.name,
                "triggers_total": self.triggers_total,
                "armed": {
                    fp.name: {
                        "mode": fp.mode,
                        "arg": fp.arg,
                        "remaining": fp.remaining,
                        "triggers": fp.triggers,
                    }
                    for fp in self._armed.values()
                },
                "triggered": dict(self._history),
            }

    # ------------------------------------------------------------- fire

    def fire(self, name: str, **ctx) -> Optional[FailpointHit]:
        """The call-site hook.  Disarmed (the overwhelmingly common
        case): returns None after one dict truthiness check.  Armed:
        counts the trigger, records a flight event (``ctx`` fields ride
        along), then applies the mode — raising :class:`FailpointError`
        (``error``), sleeping (``delay``), blocking until disarm
        (``hang``), or returning a hit whose ``value`` alternates
        (``flap``)."""
        if not self._armed:  # zero-overhead fast path
            return None
        with self._lock:
            fp = self._armed.get(name)
            if fp is None:
                return None
            fp.triggers += 1
            n = fp.triggers
            self.triggers_total += 1
            self._history[name] = self._history.get(name, 0) + 1
            if fp.remaining is not None:
                fp.remaining -= 1
                if fp.remaining <= 0:
                    # Budget spent: self-disarm (the injection window
                    # closes even if the arming test dies first).
                    self._armed.pop(name, None)
                    fp.unhang.set()
        if self._flight is not None:
            self._flight.record(
                "failpoint.trigger", name=name, mode=fp.mode, n=n, **ctx
            )
        if fp.mode == "error":
            raise FailpointError(
                str(fp.arg) if fp.arg else f"failpoint {name!r} armed (error)"
            )
        if fp.mode == "delay":
            time.sleep(float(fp.arg))
            return FailpointHit(name, "delay", n, True, fp.arg)
        if fp.mode == "hang":
            limit = min(float(fp.arg), MAX_HANG_S) if fp.arg else MAX_HANG_S
            fp.unhang.wait(timeout=limit)
            return FailpointHit(name, "hang", n, True, fp.arg)
        if fp.mode in ("truncate", "corrupt"):
            # Advisory: the call site tears (truncate) or byte-flips
            # (corrupt) its own output — docs/chaos.md catalog; sites
            # that do not understand the advice ignore the hit.
            return FailpointHit(name, fp.mode, n, True, fp.arg)
        # flap: fault value alternates every `period` triggers, starting
        # ACTIVE (the first probe after arming sees the fault).
        period = int(fp.arg) if fp.arg else 1
        return FailpointHit(
            name, "flap", n, ((n - 1) // period) % 2 == 0, fp.arg
        )


# Process-wide registry: the production call sites (plugin, engine,
# attribution) fire this one; cli.py / http_server main() arm it from
# --failpoints / TPU_FAILPOINTS and wire their flight recorders in.
DEFAULT = FailpointRegistry()

fire = DEFAULT.fire
arm = DEFAULT.arm
arm_spec = DEFAULT.arm_spec
disarm = DEFAULT.disarm
disarm_all = DEFAULT.disarm_all
is_armed = DEFAULT.is_armed
set_flight = DEFAULT.set_flight
snapshot = DEFAULT.snapshot


def fire_scoped(name: str, scope: str, **ctx) -> Optional[FailpointHit]:
    """Fire a scoped site then its generic parent on :data:`DEFAULT`.

    Call sites that fan out over dynamic peers (the router dials K
    replicas through ONE code path) need per-peer arming without
    minting K registry constants: ``fire_scoped("router.replica_conn",
    "10.0.0.7:8000")`` fires ``router.replica_conn.10.0.0.7:8000``
    first (arm it to fault ONE replica), then the bare
    ``router.replica_conn`` (arm it to fault every dial).  An ``error``
    arm on either raises before the other fires; ``ctx`` rides on both
    trigger events.  Disarmed both ways it is still just two dict
    truthiness checks."""
    hit = DEFAULT.fire(f"{name}.{scope}", **ctx)
    generic = DEFAULT.fire(name, scope=scope, **ctx)
    return hit if hit is not None else generic


def arm_from_env(environ=None) -> list[str]:
    """Arm :data:`DEFAULT` from ``TPU_FAILPOINTS`` (no-op when unset);
    returns the armed names.  Called by both CLI mains so a DaemonSet /
    serving pod can be chaos-armed via env alone."""
    environ = os.environ if environ is None else environ
    spec = environ.get(ENV, "")
    if not spec:
        return []
    return DEFAULT.arm_spec(spec)
