"""Request-scoped tracing: trace IDs, nested spans, a bounded span ring.

The reference has no observability at all (SURVEY.md §5.5) and the
serving engine until now had per-process counters only — no way to ask
"where did THIS request's 900 ms go?".  This module is the host-side
span layer arXiv:2510.16946 argues accelerator fleets are missing:
stdlib-only (contextvars + deque + logging), cheap enough to leave on,
and readable without any collector — the ring snapshot is served
straight from ``/debug/state``.

Three pieces:

- **Trace IDs**: ``new_trace_id()`` mints one; ``sanitize_trace_id()``
  validates a client-supplied ``X-Request-Id`` (bounded, printable) and
  mints a fresh one otherwise, so a hostile header can never corrupt
  logs or the exposition.
- **Nested spans**: ``SpanRecorder.span()`` is a context manager whose
  parent link follows a contextvar — same-thread nesting needs no
  plumbing.  Cross-thread structure (the serving topology: HTTP handler
  threads submit, ONE owner thread steps) uses ``reserve_id()`` +
  explicit ``parent_id``/``span_id`` on ``record_span`` — the request
  carries its root id across threads.
- **Bounded ring**: a ``deque(maxlen=capacity)`` of finished spans;
  overflow drops the OLDEST and counts ``dropped`` (diagnosis wants the
  recent past, and an unbounded buffer in a serving daemon is a leak).

Every recorded span can also be emitted as one structured JSON event
through utils/logging.py (``emit=True``): the JsonFormatter merges the
``event`` dict into the log line, so `kubectl logs` carries the same
record the ring serves.
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import threading
import time
from collections import deque
from typing import NamedTuple, Optional

log = logging.getLogger("tpu.spans")

# Engine-scoped (not request-scoped) spans use this trace id.
ENGINE_TRACE = "engine"
# Daemon-side RPC spans (utils/tracing.timed_rpc) use this one: the one
# span ring tells kubelet-RPC and engine-request timelines apart by trace.
DAEMON_TRACE = "daemon"

_MAX_TRACE_ID_LEN = 128
_FORBIDDEN = set('"\\\n\r')


def new_trace_id() -> str:
    """A fresh 16-hex trace id (random, not time-derived: ids must not
    collide across concurrently restarting pods)."""
    return os.urandom(8).hex()


def sanitize_trace_id(raw: object) -> str:
    """A usable trace id from a client-supplied ``X-Request-Id`` header.

    Accepts any printable string up to 128 chars without quotes,
    backslashes, or newlines (the characters that would need escaping in
    log lines and Prometheus label values); anything else — including a
    missing header — gets a fresh generated id, never an error: tracing
    must not add a rejection path to the serving API.
    """
    if isinstance(raw, str):
        rid = raw.strip()
        if (
            0 < len(rid) <= _MAX_TRACE_ID_LEN
            and rid.isprintable()
            and not (_FORBIDDEN & set(rid))
        ):
            return rid
    return new_trace_id()


# --------------------------------------------------------- hop context
#
# Cross-process span propagation (fleet-wide tracing): the router stamps
# one ``X-Trace-Context`` header on EVERY upstream dial — first attempt,
# each retry, each hedge leg, and the failover resubmission all carry a
# DISTINCT attempt span id — and the replica roots its per-request span
# tree under that id instead of floating free.  The format is
# W3C-traceparent-shaped (version-traceid-parentid-tail) but keeps the
# repo's trace-id contract (any printable id the sanitize gate accepts,
# dashes included — parsing splits from the RIGHT so a dashed trace id
# survives) and replaces the W3C flags byte with ``<hop><attempt>``
# (two hex bytes): which edge of the request's journey this dial is,
# and which attempt along that edge.

TRACE_CONTEXT_HEADER = "X-Trace-Context"
_CTX_VERSION = "00"
_SPAN_HEX_RE = re.compile(r"^[0-9a-f]{16}$")
_BYTE_HEX_RE = re.compile(r"^[0-9a-f]{2}$")


class HopContext(NamedTuple):
    """One parsed ``X-Trace-Context``: the sender's trace id, the span
    id of the sending attempt (16 lowercase hex — the cross-process
    parent link the assembler joins on), and the hop/attempt indexes
    (0-255 each; the wire clamps)."""

    trace_id: str
    parent_span: str
    hop: int
    attempt: int


def format_span_id(span_id: int) -> str:
    """A span id as the 16-hex wire form ``X-Trace-Context`` carries
    (process-local ints; the pair (process, id) is globally unique and
    the assembler scopes the join per source)."""
    return f"{int(span_id) & 0xFFFFFFFFFFFFFFFF:016x}"


def format_trace_context(
    trace_id: str, parent_span_id: int, hop: int, attempt: int
) -> str:
    """The ``X-Trace-Context`` value for one outbound dial.  Hop and
    attempt clamp into [0, 255] (a request surviving 255 attempts has
    bigger problems than a saturated counter)."""
    hop = min(max(int(hop), 0), 255)
    attempt = min(max(int(attempt), 0), 255)
    return (
        f"{_CTX_VERSION}-{trace_id}-{format_span_id(parent_span_id)}"
        f"-{hop:02x}{attempt:02x}"
    )


def parse_trace_context(raw: object) -> Optional[HopContext]:
    """Parse a client/router-supplied ``X-Trace-Context``; None on ANY
    malformation (wrong version, bad hex, hostile trace id) — the
    receiver then falls back to the plain ``X-Request-Id`` contract.
    Parsing never raises and never mints ids: a header that fails here
    simply doesn't link, it cannot corrupt the span ring."""
    if not isinstance(raw, str):
        return None
    value = raw.strip()
    # Longest legal header: "00-" + 128-char id + "-" + 16 hex + "-" + 4.
    if not (8 < len(value) <= _MAX_TRACE_ID_LEN + 25):
        return None
    if not value.startswith(_CTX_VERSION + "-"):
        return None
    # Split from the RIGHT: the trace id may itself contain dashes
    # (sanitize_trace_id admits any printable id), so only the two
    # fixed-width trailing fields are separator-addressed.
    body = value[len(_CTX_VERSION) + 1:]
    parts = body.rsplit("-", 2)
    if len(parts) != 3:
        return None
    trace_id, parent_span, tail = parts
    # The embedded trace id must pass the SAME gate a bare X-Request-Id
    # does — compare against the sanitizer instead of re-implementing it
    # (sanitize returns the input verbatim iff it was acceptable).
    if not trace_id or sanitize_trace_id(trace_id) != trace_id:
        return None
    if not _SPAN_HEX_RE.match(parent_span):
        return None
    if len(tail) != 4:
        return None
    hop_hex, attempt_hex = tail[:2], tail[2:]
    if not (_BYTE_HEX_RE.match(hop_hex) and _BYTE_HEX_RE.match(attempt_hex)):
        return None
    return HopContext(
        trace_id, parent_span, int(hop_hex, 16), int(attempt_hex, 16)
    )


# The active span's id and trace id for same-thread nesting.  Module-level
# (not per-recorder): a thread has one active span regardless of which
# recorder it lands in.
_current_span_id: contextvars.ContextVar[int] = contextvars.ContextVar(
    "tpu_span_id", default=0
)
_current_trace_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "tpu_trace_id", default=""
)


def current_trace_id() -> str:
    """The trace id of the innermost active span ("" when none)."""
    return _current_trace_id.get()


class _ActiveSpan:
    """Context-manager handle for one in-flight span (attrs may be added
    mid-flight via ``set``)."""

    def __init__(self, recorder: "SpanRecorder", name: str, trace_id: str, attrs: dict):
        self._recorder = recorder
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs
        self.span_id = recorder.reserve_id()

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_ActiveSpan":
        self._parent = _current_span_id.get()
        self._tok_span = _current_span_id.set(self.span_id)
        self._tok_trace = _current_trace_id.set(self.trace_id)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        end = time.monotonic()
        _current_span_id.reset(self._tok_span)
        _current_trace_id.reset(self._tok_trace)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._recorder.record_span(
            self.name,
            self.trace_id,
            start_monotonic=self._t0,
            end_monotonic=end,
            span_id=self.span_id,
            parent_id=self._parent,
            attrs=self.attrs,
        )
        return False


class SpanRecorder:
    """Thread-safe bounded ring of finished spans + span-id allocator.

    ``capacity`` bounds host memory; overflow evicts the oldest span and
    increments ``dropped`` (visible in /debug/state so an operator knows
    the window was truncated).  ``emit=True`` additionally logs each
    span as one structured event through the ``tpu.spans`` logger.
    """

    def __init__(
        self, capacity: int = 512, emit: bool = False, name: str = "spans"
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.emit = emit
        # Keys this recorder in multi-recorder flight dumps and the
        # trace assembler's source labels (a serving pod has an
        # "engine" ring; the router daemon a "router" ring).
        self.name = name
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._next_id = 1
        self.dropped = 0

    def reserve_id(self) -> int:
        """Allocate a span id BEFORE the span is recorded — how a root
        span's id crosses threads (children record against it while the
        root is still open)."""
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            return sid

    def span(self, name: str, trace_id: Optional[str] = None, **attrs) -> _ActiveSpan:
        """Context manager timing the enclosed region; nests under the
        thread's active span (contextvars) and inherits its trace id
        unless one is given."""
        tid = trace_id if trace_id is not None else (current_trace_id() or new_trace_id())
        return _ActiveSpan(self, name, tid, attrs)

    def record_span(
        self,
        name: str,
        trace_id: str,
        *,
        start_monotonic: float,
        end_monotonic: Optional[float] = None,
        span_id: Optional[int] = None,
        parent_id: int = 0,
        attrs: Optional[dict] = None,
    ) -> int:
        """Record a span from explicit monotonic timestamps (the engine's
        post-hoc shape: queue wait is known only at admission, decode
        duration only at finish).  Returns the span id."""
        end = time.monotonic() if end_monotonic is None else end_monotonic
        sid = self.reserve_id() if span_id is None else span_id
        entry = {
            "name": name,
            "trace_id": trace_id,
            "span_id": sid,
            "parent_id": parent_id,
            # Wall-clock start derived from the monotonic pair so ring
            # entries line up with log timestamps and Prometheus scrapes.
            "start": round(time.time() - (time.monotonic() - start_monotonic), 6),
            "duration_ms": round(max(end - start_monotonic, 0.0) * 1e3, 3),
        }
        if attrs:
            entry["attrs"] = dict(attrs)
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(entry)
        if self.emit:
            log.info(
                "span %s trace=%s %.3fms",
                name,
                trace_id,
                entry["duration_ms"],
                extra={"event": entry},
            )
        return sid

    def snapshot(self) -> list[dict]:
        """Recent spans, oldest first (JSON-safe copies)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def dump(self, trace_id: Optional[str] = None) -> dict:
        """The ``GET /debug/spans`` body (also what flight dumps embed):
        the ring plus its truncation accounting, optionally filtered to
        ONE request's tree so the assembler's live mode doesn't pull
        whole rings."""
        spans = self.snapshot()
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return {
            "name": self.name,
            "spans": spans,
            "dropped": self.dropped,
            "capacity": self.capacity,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0


def monotonic_to_wall(t_monotonic: float) -> float:
    """Convert a ``time.monotonic()`` stamp to approximate wall time."""
    return time.time() - (time.monotonic() - t_monotonic)
