"""One shared JAX platform-selection override.

A TPU-VM image's site hooks may pin the hardware platform
programmatically BEFORE user code runs; the ``JAX_PLATFORMS`` env var
alone does not undo a programmatic pin — ``jax.config.update`` does.
Every entry point that must honor the pod-spec env (repo-root
``bench.py``'s measurement subprocess, the in-pod benchmark runner, the
serving-engine CLI) routes through here so the semantics can't drift.
"""

from __future__ import annotations

import os
from typing import Callable, Optional


def honor_jax_platforms_env(
    *,
    empty_is_auto: bool,
    log: Optional[Callable[[str], None]] = None,
) -> None:
    """Apply ``JAX_PLATFORMS`` from the environment over any programmatic pin.

    ``empty_is_auto``: what ``JAX_PLATFORMS=""`` means.  True — reset to
    automatic backend selection (bench.py's fallback ladder needs this to
    un-pin a wedged accelerator); False — treat empty as unset and leave
    any existing pin alone (the benchmark/serving CLIs: an empty var in a
    pod spec should be a no-op, not a reset).

    Best-effort by contract: a failed update is reported through ``log``
    (when given) and never raises — no entry point should die over
    platform plumbing.
    """
    import jax

    if "JAX_PLATFORMS" not in os.environ:
        return
    value = os.environ["JAX_PLATFORMS"]
    if not value and not empty_is_auto:
        return
    try:
        jax.config.update("jax_platforms", value or None)
    except Exception as e:
        if log is not None:
            log(f"could not apply JAX_PLATFORMS={value!r}: {e}")
