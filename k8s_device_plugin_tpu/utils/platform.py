"""One shared JAX platform-selection override.

A TPU-VM image's site hooks may pin the hardware platform
programmatically BEFORE user code runs; the ``JAX_PLATFORMS`` env var
alone does not undo a programmatic pin — ``jax.config.update`` does.
Every entry point that must honor the pod-spec env (repo-root
``bench.py``'s measurement subprocess, the in-pod benchmark runner, the
serving-engine CLI) routes through here so the semantics can't drift.
"""

from __future__ import annotations

import os
from typing import Callable, Optional


# Peak dense bf16 matmul throughput per chip, by device_kind substring
# (first match wins; more specific substrings first).  Public figures:
# v4 275, v5e 197, v5p 459, v6e/Trillium 918, v3 123, v2 45 TFLOP/s.
# Used for MFU reporting (bench.py) — an unknown generation yields None
# and MFU is simply omitted, never guessed.
PEAK_BF16_FLOPS_BY_KIND: tuple[tuple[str, float], ...] = (
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_bf16_flops(device) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for a jax device, or None if unknown."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    for sub, peak in PEAK_BF16_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return None


def honor_jax_platforms_env(
    *,
    empty_is_auto: bool,
    log: Optional[Callable[[str], None]] = None,
) -> None:
    """Apply ``JAX_PLATFORMS`` from the environment over any programmatic pin.

    ``empty_is_auto``: what ``JAX_PLATFORMS=""`` means.  True — reset to
    automatic backend selection (bench.py's fallback ladder needs this to
    un-pin a wedged accelerator); False — treat empty as unset and leave
    any existing pin alone (the benchmark/serving CLIs: an empty var in a
    pod spec should be a no-op, not a reset).

    Best-effort by contract: a failed update is reported through ``log``
    (when given) and never raises — no entry point should die over
    platform plumbing.
    """
    import jax

    if "JAX_PLATFORMS" not in os.environ:
        return
    value = os.environ["JAX_PLATFORMS"]
    if not value and not empty_is_auto:
        return
    try:
        jax.config.update("jax_platforms", value or None)
    except Exception as e:
        if log is not None:
            log(f"could not apply JAX_PLATFORMS={value!r}: {e}")


def enable_compilation_cache(
    cache_dir: str,
    *,
    min_compile_seconds: float = 1.0,
    log: Optional[Callable[[str], None]] = None,
) -> None:
    """Persist XLA compilations under ``cache_dir`` so a restarted pod
    reuses them instead of recompiling (TPU compiles run 20-40s per
    program; a liveness-probe restart of the serving pod would otherwise
    pay them all again — the manifests mount an emptyDir here, which
    survives container restarts within the pod).

    ``min_compile_seconds`` filters entries: only compilations at least
    this slow are written (sub-second CPU test compiles would churn the
    dir).  An empty ``cache_dir`` is a no-op, so every entry point can
    pass its flag/env value straight through (same self-contained
    semantics as honor_jax_platforms_env).  Best-effort: serving must
    come up cacheless rather than die over cache plumbing.
    """
    if not cache_dir:
        return
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_seconds
        )
        if log is not None:
            log(f"persistent compilation cache at {cache_dir}")
    except Exception as e:
        if log is not None:
            log(f"compilation cache unavailable ({cache_dir}): {e}")
