"""Structured logging setup for the plugin daemon.

The reference logs through glog with leveled verbosity flags set on the
container command line (reference Dockerfile:25, main.go glog calls).  We emit
one structured line per event — either logfmt-ish text or JSON — on stderr,
which is what `kubectl logs` collects from a DaemonSet pod.
"""

from __future__ import annotations

import json
import logging
import sys
import time


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        # Structured events (utils/spans.py emission, or any caller passing
        # ``extra={"event": {...}}``): merged into the line so one JSON
        # record carries the machine-readable fields alongside the message.
        # The fixed keys above win on collision — a span attr must not be
        # able to spoof the log level.
        event = getattr(record, "event", None)
        if isinstance(event, dict):
            entry = {**event, **entry}
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, separators=(",", ":"), default=str)


def setup_logging(level: str = "INFO", json_logs: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_logs:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)-7s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
