"""Profiling/tracing hooks — an aux subsystem the reference lacks entirely
(SURVEY.md §5.1: no tracing, no pprof, vendored x/net/trace never imported).

Two layers:

- Workload (device) side: ``trace()`` wraps a region in a jax.profiler trace
  whose output loads in TensorBoard/XProf or Perfetto — XLA op timelines,
  HBM usage, ICI collective timing.  ``annotate()`` names a region so host
  Python shows up aligned with device ops.
- Daemon (host) side: ``timed_rpc`` decorates gRPC servicer methods with
  wall-time logging + optional metrics-registry observation; cheap enough to
  leave on (one perf_counter pair per call).
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import time
from typing import Iterator, Optional

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed region into
    ``trace_dir`` (no-op when trace_dir is falsy, so callers can wire it
    straight to an optional flag/env)."""
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    log.info("profiler trace -> %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-region inside an active trace (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def default_trace_dir(environ=None) -> Optional[str]:
    """Resolve the conventional trace-dir env (TPU_PLUGIN_TRACE_DIR)."""
    environ = os.environ if environ is None else environ
    return environ.get("TPU_PLUGIN_TRACE_DIR") or None


def timed_rpc(fn=None, *, observe=None, threshold_ms: float = 0.0):
    """Decorator for daemon RPC handlers: debug-log wall time per call, and
    feed ``observe(seconds)`` (e.g. a metrics summary) when provided.
    ``threshold_ms`` promotes slow calls to WARNING."""

    def wrap(f):
        @functools.wraps(f)
        def inner(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return f(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                if observe is not None:
                    observe(dt)
                if threshold_ms and dt * 1e3 >= threshold_ms:
                    log.warning("%s took %.1f ms", f.__name__, dt * 1e3)
                else:
                    log.debug("%s took %.2f ms", f.__name__, dt * 1e3)

        return inner

    return wrap if fn is None else wrap(fn)
