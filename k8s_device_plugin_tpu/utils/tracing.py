"""Profiling/tracing hooks — an aux subsystem the reference lacks entirely
(SURVEY.md §5.1: no tracing, no pprof, vendored x/net/trace never imported).

Two layers:

- Workload (device) side: ``trace()`` wraps a region in a jax.profiler trace
  whose output loads in TensorBoard/XProf or Perfetto — XLA op timelines,
  HBM usage, ICI collective timing.  ``annotate()`` names a region so host
  Python shows up aligned with device ops.
- Daemon (host) side: ``timed_rpc`` decorates gRPC servicer methods with
  wall-time logging, optional metrics-registry observation, AND a
  daemon-side span into the utils/spans.py ring — one tracing story with
  two entry points (request spans from the engine, RPC spans from the
  daemon); cheap enough to leave on (one monotonic pair per call).
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading
import time
from typing import Iterator, Optional

from .spans import DAEMON_TRACE

log = logging.getLogger(__name__)

# Traces started through this module, counted so annotate() can tell
# whether naming a region would reach a profiler at all.
_active_traces = 0
_active_lock = threading.Lock()


def trace_active() -> bool:
    """True while a jax.profiler trace started via :func:`trace` runs."""
    return _active_traces > 0


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed region into
    ``trace_dir`` (no-op when trace_dir is falsy, so callers can wire it
    straight to an optional flag/env)."""
    global _active_traces
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    log.info("profiler trace -> %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        with _active_lock:
            _active_traces += 1
        try:
            yield
        finally:
            with _active_lock:
                _active_traces -= 1


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-region inside an active trace (TraceAnnotation).

    A guaranteed no-op when no profiler trace (started via this module)
    is active or when jax is unavailable, so host-only callers — the
    plugin daemon runs in an image that need not ship jax — can
    annotate hot regions unconditionally."""
    if not trace_active():
        yield
        return
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.TraceAnnotation(name):
        yield


def default_trace_dir(environ=None) -> Optional[str]:
    """Resolve the conventional trace-dir env (TPU_PLUGIN_TRACE_DIR)."""
    environ = os.environ if environ is None else environ
    return environ.get("TPU_PLUGIN_TRACE_DIR") or None


def timed_rpc(
    fn=None,
    *,
    observe=None,
    threshold_ms: float = 0.0,
    spans=None,
    name: Optional[str] = None,
):
    """Decorator for daemon RPC handlers: debug-log wall time per call,
    feed ``observe(seconds)`` (e.g. a metrics summary — the hook is
    unchanged), and record one daemon-side span per call into ``spans``
    — either a utils/spans.py SpanRecorder or a no-arg callable
    returning one/None (late binding: decoration happens before the
    daemon wires its recorder).  RPC spans carry the DAEMON_TRACE trace
    id, so the one span ring tells engine-request and kubelet-RPC
    timelines apart by trace.  ``threshold_ms`` promotes slow calls to
    WARNING."""

    def wrap(f):
        span_name = name or f"rpc.{f.__name__}"

        @functools.wraps(f)
        def inner(*args, **kwargs):
            t0 = time.monotonic()
            try:
                return f(*args, **kwargs)
            finally:
                end = time.monotonic()
                dt = end - t0
                if observe is not None:
                    observe(dt)
                recorder = spans() if callable(spans) else spans
                if recorder is not None:
                    recorder.record_span(
                        span_name,
                        DAEMON_TRACE,
                        start_monotonic=t0,
                        end_monotonic=end,
                    )
                if threshold_ms and dt * 1e3 >= threshold_ms:
                    log.warning("%s took %.1f ms", f.__name__, dt * 1e3)
                else:
                    log.debug("%s took %.2f ms", f.__name__, dt * 1e3)

        return inner

    return wrap if fn is None else wrap(fn)
