"""Bloom-filter digest of content-addressed KV prefix roots.

The fleet KV fabric needs every replica to advertise WHICH cumulative
token prefixes it could serve over the handoff wire — but the honest
answer (the full key list) is unbounded: a warm replica holds hundreds
of trie-resident and arena-offloaded prefixes, each keyed by its full
token tuple, and the advertisement rides the router's ``?summary=1``
poll, which is deliberately cheap (lock-free on the engine side, one
small JSON object per replica per poll tick).  So the advertisement is
a fixed-size bloom filter over the same content keys the arena and
``donor_for`` already use: ``(trie_root, cumulative_tokens)``.

Semantics the fabric layers on top rely on:

- **No false negatives.**  A prefix the replica advertised is always
  queryable; the router's locator may MISS real owners only through
  digest staleness (one poll interval), never through the filter.
- **False positives are survivable by construction.**  The router may
  stamp an owner that holds nothing; the puller's parse-before-admit
  verifier then admits zero entries and the request degrades to local
  prefill.  A bloom FP costs one wasted fetch, never correctness —
  which is why a probabilistic digest is admissible here at all.
- **Jax-free.**  The router and the test fakes build and query these
  digests; this module must import without the workloads extra.

Wire form is a small JSON-safe dict (``to_wire``/``from_wire``): hex
bit-string plus the (m, k) geometry and an entry count, versioned so a
geometry change never silently mixes filters.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Optional

# Digest geometry.  1024 bytes / 8192 bits with k=4 holds ~850 prefixes
# at <1% FP; a tiny-page CPU bench fleet advertises tens of roots, real
# fleets hundreds — and an over-full filter only degrades toward wasted
# fetches, never wrong tokens.  The wire form carries (m, k) anyway, so
# geometry can grow without a protocol rev.
DEFAULT_M_BITS = 8192
DEFAULT_K_HASHES = 4
WIRE_VERSION = 1

_MAX_WIRE_BITS = 1 << 20  # refuse absurd advertised geometry (128 KiB)


def prefix_key_bytes(root: int, tokens: Iterable[int]) -> bytes:
    """Canonical byte form of one content key.  Matches the arena's
    ``("prefix", root, tuple(tokens))`` addressing: same root + same
    cumulative token tuple -> same bytes, everywhere in the fleet."""
    return ("%d:" % int(root)).encode() + ",".join(
        str(int(t)) for t in tokens
    ).encode()


class PrefixBloom:
    """Fixed-geometry bloom filter over prefix content keys.

    Not thread-safe: builders fill one privately then publish the wire
    dict atomically (the engine rebuilds under its lock and caches the
    rendered dict; the router parses a fresh instance per poll).
    """

    __slots__ = ("m", "k", "count", "_bits")

    def __init__(self, m: int = DEFAULT_M_BITS, k: int = DEFAULT_K_HASHES):
        if m <= 0 or m % 8 or m > _MAX_WIRE_BITS:
            raise ValueError(f"bloom m must be in (0, {_MAX_WIRE_BITS}] and byte-aligned, got {m}")
        if not 1 <= k <= 16:
            raise ValueError(f"bloom k must be in [1, 16], got {k}")
        self.m = int(m)
        self.k = int(k)
        self.count = 0
        self._bits = bytearray(m // 8)

    def _positions(self, key: bytes) -> list[int]:
        # One blake2b evaluation yields all k positions: 4-byte slices of
        # the 64-byte digest, mod m.  k<=16 always fits one digest.
        digest = hashlib.blake2b(key, digest_size=4 * self.k).digest()
        return [
            int.from_bytes(digest[4 * i : 4 * i + 4], "big") % self.m
            for i in range(self.k)
        ]

    def add(self, root: int, tokens: Iterable[int]) -> None:
        key = prefix_key_bytes(root, tokens)
        for pos in self._positions(key):
            self._bits[pos >> 3] |= 1 << (pos & 7)
        self.count += 1

    def contains(self, root: int, tokens: Iterable[int]) -> bool:
        key = prefix_key_bytes(root, tokens)
        return all(
            self._bits[pos >> 3] & (1 << (pos & 7))
            for pos in self._positions(key)
        )

    def to_wire(self) -> dict:
        """JSON-safe advertisement dict for the ``?summary=1`` payload."""
        return {
            "v": WIRE_VERSION,
            "m": self.m,
            "k": self.k,
            "count": self.count,
            "bits": self._bits.hex(),
        }

    @classmethod
    def from_wire(cls, wire: object) -> Optional["PrefixBloom"]:
        """Parse an advertised digest; ``None`` for anything malformed
        (wrong version, bad geometry, bit-string/geometry mismatch).
        The router treats an unparseable digest exactly like a replica
        with no advertisement — the locator simply cannot place it."""
        if not isinstance(wire, dict):
            return None
        try:
            if int(wire.get("v", -1)) != WIRE_VERSION:
                return None
            m, k = int(wire["m"]), int(wire["k"])
            bits = bytes.fromhex(wire["bits"])
            count = int(wire.get("count", 0))
        except (KeyError, TypeError, ValueError):
            return None
        if m <= 0 or m % 8 or m > _MAX_WIRE_BITS or not 1 <= k <= 16:
            return None
        if len(bits) != m // 8 or count < 0:
            return None
        bloom = cls(m, k)
        bloom._bits = bytearray(bits)
        bloom.count = count
        return bloom
