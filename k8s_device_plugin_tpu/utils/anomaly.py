"""EWMA/z-score anomaly baselines and structured incident records.

The flight recorder (utils/flight.py) answers "what happened before it
went wrong" — this module decides WHEN something went wrong, without an
operator watching dashboards: each tracked metric (engine step time,
TTFT, Allocate latency, health-sweep duration) keeps an exponentially
weighted mean/variance baseline, and a SUSTAINED deviation — several
consecutive observations past a z-score threshold, not one outlier —
emits a structured **incident record**: cause metric, baseline,
observed value, z-score, plus the surrounding flight-recorder window.
Incidents go three ways at once: a bounded in-memory list served by
``GET /debug/incidents``, one structured line through the JSON logger
(the ``kubectl logs`` trail), and back into the flight recorder itself
(so a later dump shows the incident in sequence with its causes).

EWMA rather than a windowed mean: O(1) state per metric, no timestamp
bookkeeping, and the baseline adapts to slow drift (a server warming
its caches) while still flagging step changes — the standard host-side
telemetry shape (arXiv:2510.16946 §4's "lightweight online detection").
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Optional

from .flight import FlightRecorder

log = logging.getLogger("tpu.anomaly")


class EwmaBaseline:
    """Exponentially weighted mean/variance with a warmup gate.

    ``score(value)`` returns the z-score of the value against the
    current baseline WITHOUT folding it in (a spike must be scored
    against the past, never against itself), or None until ``warmup``
    samples have been absorbed.  ``update(value)`` folds a sample in;
    ``observe`` is score-then-update for callers without an
    accept/reject policy.  ``alpha`` is the usual smoothing factor
    (small = long memory); variance uses the standard EWMA recurrence
    var' = (1-a) * (var + a * delta^2).
    """

    def __init__(self, alpha: float = 0.05, warmup: int = 30):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if warmup < 1:
            raise ValueError(f"warmup must be >= 1, got {warmup}")
        self.alpha = alpha
        self.warmup = warmup
        self.count = 0
        self.mean = 0.0
        self.var = 0.0

    def score(self, value: float) -> Optional[float]:
        if self.count < self.warmup:
            return None
        std = math.sqrt(self.var)
        # Floor the std at a fraction of the mean so a perfectly steady
        # warmup (var ~ 0) doesn't turn the first normal jitter into an
        # infinite z-score.
        floor = abs(self.mean) * 0.05 + 1e-9
        return (float(value) - self.mean) / max(std, floor)

    def update(self, value: float) -> None:
        v = float(value)
        if self.count == 0:
            self.mean = v
        else:
            delta = v - self.mean
            self.mean += self.alpha * delta
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * delta * delta)
        self.count += 1

    def observe(self, value: float) -> Optional[float]:
        z = self.score(value)
        self.update(value)
        return z


class AnomalyDetector:
    """One metric's sustained-deviation gate over an EWMA baseline.

    Emits (returns) an incident fragment only after ``sustain``
    CONSECUTIVE observations with z >= ``z_threshold`` (one-sided high
    by default — for latencies, fast is never an incident), then holds
    a ``cooldown_s`` refractory window so a long outage is one incident,
    not one per step.  Deviating samples never fold into the baseline
    (they must not drag it up toward themselves, or a slow leak would
    never fire); a persistent level shift therefore keeps re-firing once
    per cooldown — which is the honest report: it IS anomalous against
    all learned history.
    """

    def __init__(
        self,
        metric: str,
        *,
        alpha: float = 0.05,
        warmup: int = 30,
        z_threshold: float = 4.0,
        sustain: int = 3,
        direction: str = "high",
        cooldown_s: float = 30.0,
    ):
        if sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        if direction not in ("high", "low", "both"):
            raise ValueError(f"direction must be high/low/both, got {direction!r}")
        self.metric = metric
        self.z_threshold = float(z_threshold)
        self.sustain = sustain
        self.direction = direction
        self.cooldown_s = float(cooldown_s)
        self.baseline = EwmaBaseline(alpha=alpha, warmup=warmup)
        self._run = 0
        self._run_peak = 0.0
        self._last_incident_t = 0.0
        self.incidents_emitted = 0

    def _deviates(self, z: float) -> bool:
        if self.direction == "high":
            return z >= self.z_threshold
        if self.direction == "low":
            return -z >= self.z_threshold
        return abs(z) >= self.z_threshold

    def observe(self, value: float, now: Optional[float] = None) -> Optional[dict]:
        """Feed one observation; returns an incident fragment (no flight
        window attached yet — the monitor does that) when the sustained
        gate trips, else None."""
        now = time.monotonic() if now is None else now
        z = self.baseline.score(value)
        if z is None or not self._deviates(z):
            # Normal (or warming) sample: learn it, break any run.
            self.baseline.update(value)
            self._run = 0
            self._run_peak = 0.0
            return None
        self._run += 1
        peak = abs(float(value))
        if self._run == 1 or peak > abs(self._run_peak):
            self._run_peak = float(value)
        if self._run < self.sustain:
            return None
        in_cooldown = now - self._last_incident_t < self.cooldown_s
        # Keep the run latched through cooldown so a continuing outage
        # re-arms the moment cooldown expires, but emit nothing now.
        self._run = self.sustain - 1 if self.sustain > 1 else 0
        if in_cooldown and self._last_incident_t > 0.0:
            return None
        self._last_incident_t = now
        self.incidents_emitted += 1
        return {
            "kind": "incident",
            "metric": self.metric,
            "observed": float(value),
            "peak": self._run_peak,
            "baseline_mean": self.baseline.mean,
            "baseline_std": math.sqrt(self.baseline.var),
            "z": round(z, 2),
            "sustained": self.sustain,
            "samples": self.baseline.count,
        }


class AnomalyMonitor:
    """A set of detectors plus the incident fan-out (ring, log, flight).

    ``observe(metric, value)`` lazily creates a default detector per
    metric; ``configure(metric, **kw)`` pre-creates one with explicit
    thresholds (what the engine/daemon wiring does).  ``snapshot()`` is
    the JSON body of ``GET /debug/incidents``.  ``on_incident`` is an
    optional callable (e.g. a Prometheus counter's ``inc``) invoked with
    the metric name per emitted incident.
    """

    def __init__(
        self,
        flight: Optional[FlightRecorder] = None,
        capacity: int = 64,
        window_events: int = 100,
        on_incident=None,
    ):
        self.flight = flight
        self.window_events = window_events
        self._on_incident = on_incident
        self._lock = threading.Lock()
        self._detectors: dict[str, AnomalyDetector] = {}
        self._incidents: deque[dict] = deque(maxlen=capacity)
        self._listeners: list = []
        self.incidents_dropped = 0
        self.incidents_total = 0

    def add_listener(self, fn) -> None:
        """Register a full-record incident listener: ``fn(incident)`` is
        called once per emitted incident with the complete record (flight
        window included), AFTER the ring/log/flight fan-out and the
        ``on_incident`` metric hook.  This is the postmortem-capture
        seam (utils/postmortem.py): a listener that does real work (file
        I/O) runs outside the monitor lock and its exceptions are
        swallowed — a broken listener must never poison detection."""
        with self._lock:
            self._listeners.append(fn)

    def configure(self, metric: str, **kwargs) -> AnomalyDetector:
        with self._lock:
            det = self._detectors.get(metric)
            if det is None:
                det = self._detectors[metric] = AnomalyDetector(metric, **kwargs)
            return det

    def recalibrate(self, metric: str) -> bool:
        """Discard one detector's learned baseline (thresholds retained);
        the next ``warmup`` observations re-learn "normal" from scratch.

        The regime-change seam: deviating samples deliberately never
        fold into the baseline, so a baseline that locked onto the wrong
        regime — startup-compile outliers, a pre-migration traffic
        shape — can never adapt on its own.  The chaos harness uses this
        after its compile warmup so injected-fault precision is measured
        against a baseline warmed on production-shaped load.  Returns
        False when the metric has no detector."""
        with self._lock:
            det = self._detectors.get(metric)
            if det is None:
                return False
            det.baseline = EwmaBaseline(
                alpha=det.baseline.alpha, warmup=det.baseline.warmup
            )
            det._run = 0
            det._run_peak = 0.0
            return True

    def observe(self, metric: str, value: float) -> Optional[dict]:
        """Feed one observation; returns the full incident record (with
        flight window) when one fires.  Thread-safe: detector state
        mutates under the monitor lock (Allocate observes from
        concurrent gRPC worker threads); the rare emission fan-out runs
        after release (it re-takes the lock for the ring)."""
        with self._lock:
            det = self._detectors.get(metric)
            if det is None:
                det = self._detectors[metric] = AnomalyDetector(metric)
            fragment = det.observe(value)
        if fragment is None:
            return None
        return self._emit(fragment)

    def report(self, metric: str, observed: float = 1.0, **fields) -> dict:
        """Directly emit one incident for a DISCRETE fault — a condition
        that is wrong on its first observation (attribution drift, an
        invariant violation), where an EWMA baseline is meaningless.
        Same fan-out as a detector-emitted incident (ring + JSON log +
        flight + ``on_incident`` hook); ``fields`` ride in the record."""
        fragment = {
            "kind": "incident",
            "metric": str(metric),
            "observed": float(observed),
            "baseline_mean": 0.0,
            "baseline_std": 0.0,
            "z": 0.0,
            "direct": True,
        }
        for key, value in fields.items():
            fragment.setdefault(key, value)
        return self._emit(fragment)

    def _emit(self, fragment: dict) -> dict:
        incident = {"ts": round(time.time(), 3), **fragment}
        # Attach the black box BEFORE appending the incident event to it,
        # so the window shows the lead-up, not the incident itself.
        if self.flight is not None:
            incident["flight_window"] = self.flight.window(
                last=self.window_events
            )
        with self._lock:
            self.incidents_total += 1
            if len(self._incidents) == self._incidents.maxlen:
                self.incidents_dropped += 1
            self._incidents.append(incident)
        if self.flight is not None:
            self.flight.record(
                "incident",
                metric=incident["metric"],
                observed=incident["observed"],
                baseline_mean=incident["baseline_mean"],
                z=incident["z"],
            )
        # One structured line through the JSON logger: the same record,
        # minus the bulky window, greppable in `kubectl logs`.
        log.warning(
            "incident: %s observed=%.6g baseline=%.6g z=%.1f",
            incident["metric"],
            incident["observed"],
            incident["baseline_mean"],
            incident["z"],
            extra={
                "event": {k: v for k, v in incident.items() if k != "flight_window"}
            },
        )
        if self._on_incident is not None:
            try:
                self._on_incident(incident["metric"])
            except Exception:
                log.exception("incident hook failed")
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(incident)
            except Exception:
                log.exception("incident listener failed")
        return incident

    def incidents(self) -> list[dict]:
        with self._lock:
            return [dict(i) for i in self._incidents]

    def snapshot(self) -> dict:
        """JSON body for ``GET /debug/incidents``: the bounded incident
        list (newest last) plus per-metric baseline state, so an
        operator can see what "normal" currently means."""
        with self._lock:
            detectors = {
                name: {
                    "mean": det.baseline.mean,
                    "std": math.sqrt(det.baseline.var),
                    "samples": det.baseline.count,
                    "warmed_up": det.baseline.count >= det.baseline.warmup,
                    "z_threshold": det.z_threshold,
                    "sustain": det.sustain,
                    "incidents": det.incidents_emitted,
                }
                for name, det in self._detectors.items()
            }
            return {
                "incidents_total": self.incidents_total,
                "incidents_dropped": self.incidents_dropped,
                "detectors": detectors,
                "incidents": [dict(i) for i in self._incidents],
            }
